"""LogisticRegression configuration.

Key=value config-file schema preserved from the reference
(ref: Applications/LogisticRegression/src/configure.h:10-103,
example/mnist.config). Unknown keys are ignored with a warning, like the
reference's map-based parser.
"""

from __future__ import annotations

from dataclasses import dataclass

from ...io import TextReader
from ...util import log


@dataclass
class Configure:
    input_size: int = 0
    output_size: int = 0
    sparse: bool = False
    train_epoch: int = 1
    minibatch_size: int = 20
    read_buffer_size: int = 2048
    show_time_per_sample: int = 10000
    regular_coef: float = 0.0005
    learning_rate: float = 0.8
    learning_rate_coef: float = 1e6
    # FTRL parameters (ref: configure.h:45-48)
    alpha: float = 0.005
    beta: float = 1.0
    lambda1: float = 5.0
    lambda2: float = 0.002
    init_model_file: str = ""
    train_file: str = "train.data"
    reader_type: str = "default"  # default / weight / bsparse
    test_file: str = ""
    output_model_file: str = "logreg.model"
    output_file: str = "logreg.output"
    use_ps: bool = False
    pipeline: bool = True
    sync_frequency: int = 1
    updater_type: str = "default"  # default / sgd / ftrl
    objective_type: str = "default"  # default / sigmoid / softmax / ftrl
    regular_type: str = "default"  # default / L1 / L2

    @classmethod
    def from_file(cls, path: str) -> "Configure":
        config = cls()
        reader = TextReader(path)
        while True:
            line = reader.get_line()
            if line is None:
                break
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            key, _, value = line.partition("=")
            key, value = key.strip(), value.strip()
            if not hasattr(config, key):
                log.info("logreg config: ignoring unknown key %s", key)
                continue
            current = getattr(config, key)
            if isinstance(current, bool):
                setattr(config, key,
                        value.lower() in ("true", "1", "yes", "on"))
            elif isinstance(current, int):
                setattr(config, key, int(float(value)))
            elif isinstance(current, float):
                setattr(config, key, float(value))
            else:
                setattr(config, key, value)
        reader.close()
        if config.objective_type == "ftrl":
            # FTRL implies sparse updater/storage (ref: ps_model.cpp:30-41).
            config.updater_type = "ftrl"
            config.sparse = True
        return config
