"""LogReg models: local, parameter-server, and FTRL.

TPU-native re-design of the reference's model layer
(ref: Applications/LogisticRegression/src/model/model.cpp,
model/ps_model.cpp). The per-sample gradient loop + separate updater pass
collapse into ONE jitted train step per minibatch (forward, backward,
update fused on device); the PS variant keeps the reference's structure —
pull every ``sync_frequency`` minibatches with double-buffered async gets
(ref: ps_model.cpp:236-271), push lr-scaled deltas (ref: ps_model.cpp:
185-203, updater.cpp:55-70) — but both directions ride the device-resident
table path, so model bytes never touch the host.

FTRL-proximal (ref: updater/ftrl_updater.h, util/ftrl_sparse_table.h)
keeps per-weight state z (signed accumulator) and n (squared-gradient sum);
the PS form pushes (delta_z, delta_n) to two tables with the default adder,
matching the reference's FTRL gradient wire format {delta_z, delta_n}
(ref: util/data_type.h:13-54).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ... import create_array_table, create_matrix_table
from ...updater.engine import pad_ids
from ...util import log
from .config import Configure
from .objective import (learning_rate, make_dense_step, make_predict,
                        make_sparse_step)
from .reader import Batch


def _weight_shape(config: Configure):
    rows = config.input_size + (1 if config.sparse else 0)
    return (rows, max(config.output_size, 1))


class LocalModel:
    """Single-process model: weights live on device, one jit per batch
    (ref: model/model.cpp:63-110)."""

    def __init__(self, config: Configure):
        self.config = config
        self._w = jnp.zeros(_weight_shape(config), jnp.float32)
        step = make_sparse_step(config) if config.sparse \
            else make_dense_step(config)
        scale_lr = config.updater_type in ("sgd", "ftrl")

        def fused(w, lr, *batch_args):
            loss_sum, correct, grad = step(w, *batch_args)
            delta = grad * lr if scale_lr else grad
            return w - delta, loss_sum, correct

        self._step = jax.jit(fused, donate_argnums=(0,))
        self._predict = make_predict(config)
        self.update_count = 0

    def update(self, batch: Batch) -> float:
        lr = jnp.float32(learning_rate(self.config, self.update_count))
        self._w, loss_sum, _ = self._step(self._w, lr, *_args(batch))
        self.update_count += 1
        return float(loss_sum)

    def predict(self, batch: Batch) -> np.ndarray:
        return np.asarray(self._predict(self._w, *_args(batch)[:-2]))

    @property
    def weights(self) -> np.ndarray:
        return np.asarray(self._w)

    def load_weights(self, w: np.ndarray) -> None:
        self._w = jnp.asarray(w, jnp.float32).reshape(self._w.shape)

    def store(self, stream) -> None:
        stream.write(self.weights.astype(np.float32).tobytes())

    def load(self, stream) -> None:
        shape = _weight_shape(self.config)
        raw = stream.read(int(np.prod(shape)) * 4)
        self.load_weights(np.frombuffer(raw, np.float32).reshape(shape))


def _args(batch: Batch):
    if batch.x is not None:
        return (jnp.asarray(batch.x), jnp.asarray(batch.labels),
                jnp.asarray(batch.weights))
    return (jnp.asarray(batch.keys), jnp.asarray(batch.values),
            jnp.asarray(batch.labels), jnp.asarray(batch.weights))


class PSModel:
    """Parameter-server model (ref: model/ps_model.cpp:23-271).

    Dense: whole model in one ArrayTable with the sgd server updater;
    pulls ride ``get_device`` (HBM to HBM) and pushes are device deltas, so
    model bytes never touch the host. Sparse: row-sharded sparse
    MatrixTable whose pulls return only this worker's dirty rows. Pulls
    happen every ``sync_frequency`` minibatches; meanwhile the worker
    trains on its local replica and pushes lr-scaled deltas that the
    server's sgd updater subtracts (ref: ps_model.cpp:172-203,
    sgd_updater.h:15-19).
    """

    def __init__(self, config: Configure):
        self.config = config
        rows, cols = _weight_shape(config)
        self._w = jnp.zeros((rows, cols), jnp.float32)
        if config.sparse:
            self._table = create_matrix_table(
                rows, cols, is_sparse=True, is_pipeline=config.pipeline,
                updater_type="sgd")
        else:
            self._table = create_array_table(rows * cols,
                                             updater_type="sgd")
        self._objective_step = make_sparse_step(config) if config.sparse \
            else make_dense_step(config)
        scale_lr = config.updater_type in ("sgd", "ftrl")
        self._scale = jax.jit(lambda g, lr: g * lr if scale_lr else g)
        self._apply_local = jax.jit(lambda w, d: w - d,
                                    donate_argnums=(0,))
        self._gather_rows = jax.jit(
            lambda d, r: d.at[r].get(mode="fill", fill_value=0))
        self._predict = make_predict(config)
        self.update_count = 0
        self._batch_count = 0
        self._pull()

    # -- pull (ref: ps_model.cpp:172-182) --
    def _pull(self) -> None:
        if self.config.sparse:
            # Writable copy — np.asarray of a jax array is read-only and
            # the reply handler assigns dirty rows into it.
            buf = np.array(self._w)
            self._table.get(out=buf)
            self._w = jnp.asarray(buf)
        else:
            self._w = self._table.get_device().reshape(self._w.shape)

    def update(self, batch: Batch) -> float:
        config = self.config
        lr = jnp.float32(learning_rate(config, self.update_count))
        loss_sum, _, grad = self._objective_step(self._w, *_args(batch))
        delta = self._scale(grad, lr)
        if config.sparse:
            touched = np.unique(batch.keys.reshape(-1))
            touched = touched[touched < config.input_size].astype(np.int32)
            rows = pad_ids(touched, config.input_size + 1)
            row_delta = np.asarray(self._gather_rows(delta, rows))
            self._table.add_rows_async(touched, row_delta[:touched.size])
        else:
            self._table.add_async(delta.reshape(-1))
        # Apply locally too so training continues between pulls.
        self._w = self._apply_local(self._w, delta)
        self.update_count += 1
        self._batch_count += 1
        if self._batch_count % config.sync_frequency == 0:
            self._pull()
        return float(loss_sum)

    def predict(self, batch: Batch) -> np.ndarray:
        return np.asarray(self._predict(self._w, *_args(batch)[:-2]))

    @property
    def weights(self) -> np.ndarray:
        return np.asarray(self._w)

    def store(self, stream) -> None:
        stream.write(self.weights.astype(np.float32).tobytes())

    def load(self, stream) -> None:
        shape = _weight_shape(self.config)
        raw = stream.read(int(np.prod(shape)) * 4)
        loaded = np.frombuffer(raw, np.float32).reshape(shape)
        # Upload into the PS with the negate-add trick: push (current -
        # loaded) through the subtracting sgd updater
        # (ref: ps_model.cpp:116-169).
        self._pull()
        delta = (np.asarray(self._w) - loaded)
        if self.config.sparse:
            rows = np.arange(shape[0], dtype=np.int32)
            self._table.add_rows(rows, delta)
        else:
            self._table.add(delta.reshape(-1))
        self._pull()


class FTRLModel:
    """FTRL-proximal (ref: updater/ftrl_updater.h semantics): per-weight
    state z, n; w derived lazily:
        w = 0                                  if |z| <= lambda1
        w = -(z - sign(z)*lambda1) / ((beta + sqrt(n))/alpha + lambda2)
    update: g = grad; sigma = (sqrt(n + g^2) - sqrt(n)) / alpha;
            z += g - sigma*w ; n += g^2.
    """

    def __init__(self, config: Configure, use_ps: bool = False):
        self.config = config
        shape = _weight_shape(config)
        self._z = jnp.zeros(shape, jnp.float32)
        self._n = jnp.zeros(shape, jnp.float32)
        step = make_sparse_step(config) if config.sparse \
            else make_dense_step(config)
        alpha, beta = config.alpha, config.beta
        l1, l2 = config.lambda1, config.lambda2

        def weights_of(z, n):
            shrunk = jnp.sign(z) * jnp.maximum(jnp.abs(z) - l1, 0.0)
            return -shrunk / ((beta + jnp.sqrt(n)) / alpha + l2)

        def fused(z, n, *batch_args):
            w = weights_of(z, n)
            loss_sum, correct, g = step(w, *batch_args)
            sigma = (jnp.sqrt(n + g * g) - jnp.sqrt(n)) / alpha
            return z + (g - sigma * w), n + g * g, loss_sum, correct, \
                g, sigma * w

        self._fused = jax.jit(fused, donate_argnums=(0, 1))
        self._weights_of = jax.jit(weights_of)
        self._predict = make_predict(config)
        self.update_count = 0
        self._use_ps = use_ps
        if use_ps:
            size = int(np.prod(shape))
            self._z_table = create_array_table(size)  # default adder
            self._n_table = create_array_table(size)
            self._batch_count = 0

    def update(self, batch: Batch) -> float:
        old_z, old_n = self._z, self._n
        self._z, self._n, loss_sum, _, g, sigma_w = \
            self._fused(old_z, old_n, *_args(batch))
        if self._use_ps:
            # Push the FTRL gradient pair {delta_z, delta_n}
            # (ref: util/data_type.h:13-54).
            self._z_table.add_async((g - sigma_w).reshape(-1))
            self._n_table.add_async((g * g).reshape(-1))
            self._batch_count += 1
            if self._batch_count % self.config.sync_frequency == 0:
                shape = self._z.shape
                self._z = self._z_table.get_device().reshape(shape)
                self._n = self._n_table.get_device().reshape(shape)
        self.update_count += 1
        return float(loss_sum)

    def predict(self, batch: Batch) -> np.ndarray:
        w = self._weights_of(self._z, self._n)
        return np.asarray(self._predict(w, *_args(batch)[:-2]))

    @property
    def weights(self) -> np.ndarray:
        return np.asarray(self._weights_of(self._z, self._n))

    def store(self, stream) -> None:
        stream.write(np.asarray(self._z).tobytes())
        stream.write(np.asarray(self._n).tobytes())

    def load(self, stream) -> None:
        shape = _weight_shape(self.config)
        count = int(np.prod(shape)) * 4
        self._z = jnp.asarray(
            np.frombuffer(stream.read(count), np.float32).reshape(shape))
        self._n = jnp.asarray(
            np.frombuffer(stream.read(count), np.float32).reshape(shape))


def create_model(config: Configure):
    """Factory (ref: model.cpp Model::Get / main.cpp flow)."""
    if config.objective_type == "ftrl" or config.updater_type == "ftrl":
        return FTRLModel(config, use_ps=config.use_ps)
    if config.use_ps:
        return PSModel(config)
    return LocalModel(config)
