"""Sample readers: libsvm-style text + binary sparse, with prefetch.

TPU-native re-design of the reference's threaded ``SampleReader``
(ref: Applications/LogisticRegression/src/reader.cpp, data formats
documented at configure.h:56-69):

- ``default``: text; dense = ``label v v v ...``, sparse = libsvm
  ``label k:v k:v ...``
- ``weight``: first column is ``label:weight``
- ``bsparse``: binary ``count(u64) label(i32) weight(f64) key(u64)...``

Instead of the reference's per-sample ring buffer, samples are batched
into fixed-shape minibatch arrays (TPU wants static shapes): dense batches
are ``[B, input_size]`` matrices; sparse batches are padded
``[B, max_nnz]`` (keys, values) pairs with key==input_size as padding
(dropped by scatter/gather). A background thread prefetches the next batch
while the current one trains (the reference's async reader + the
``-pipeline`` overlap collapse into this).
"""

from __future__ import annotations

import struct
import queue as queue_mod
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ...io import StreamFactory, TextReader
from ...runtime import thread_roles
from ...updater.engine import bucket_size
from .config import Configure


class Sample:
    __slots__ = ("label", "weight", "keys", "values")

    def __init__(self, label: int, weight: float = 1.0,
                 keys: Optional[np.ndarray] = None,
                 values: Optional[np.ndarray] = None):
        self.label = label
        self.weight = weight
        self.keys = keys
        self.values = values


def parse_text_line(line: str, sparse: bool,
                    weighted: bool) -> Optional[Sample]:
    parts = line.split()
    if not parts:
        return None
    head = parts[0]
    if weighted:
        label_s, _, weight_s = head.partition(":")
        label, weight = int(float(label_s)), float(weight_s or 1.0)
    else:
        label, weight = int(float(head)), 1.0
    if sparse:
        keys, values = [], []
        for tok in parts[1:]:
            k, _, v = tok.partition(":")
            keys.append(int(k))
            values.append(float(v))
        return Sample(label, weight, np.asarray(keys, np.int64),
                      np.asarray(values, np.float32))
    values = np.asarray([float(v) for v in parts[1:]], np.float32)
    return Sample(label, weight, None, values)


def iter_samples(config: Configure, path: str) -> Iterator[Sample]:
    if config.reader_type == "bsparse":
        yield from _iter_bsparse(path)
        return
    weighted = config.reader_type == "weight"
    for one_path in path.split(";"):
        reader = TextReader(one_path)
        while True:
            line = reader.get_line()
            if line is None:
                break
            sample = parse_text_line(line, config.sparse, weighted)
            if sample is not None:
                yield sample
        reader.close()


def _iter_bsparse(path: str) -> Iterator[Sample]:
    """ref: configure.h:66-69 binary format."""
    for one_path in path.split(";"):
        with StreamFactory.get_stream(one_path, "r") as stream:
            while True:
                raw = stream.read(8)
                if len(raw) < 8:
                    break
                (count,) = struct.unpack("<Q", raw)
                label, weight = struct.unpack("<id", stream.read(12))
                keys = np.frombuffer(stream.read(8 * count), dtype="<u8")
                yield Sample(label, weight, keys.astype(np.int64),
                             np.ones(count, np.float32))


class Batch:
    """Fixed-shape minibatch. Dense: ``x [B, D]``. Sparse: padded
    ``keys [B, K]`` / ``values [B, K]`` with ``keys == input_size`` padding.
    ``count`` = real samples (rows beyond it are zero-weight padding)."""

    __slots__ = ("labels", "weights", "x", "keys", "values", "count")

    def __init__(self, labels, weights, x=None, keys=None, values=None,
                 count: int = 0):
        self.labels = labels
        self.weights = weights
        self.x = x
        self.keys = keys
        self.values = values
        self.count = count


def make_batches(config: Configure, samples: Iterator[Sample],
                 batch_size: Optional[int] = None) -> Iterator[Batch]:
    batch_size = batch_size or config.minibatch_size
    buf: List[Sample] = []
    for sample in samples:
        buf.append(sample)
        if len(buf) == batch_size:
            yield _pack(config, buf, batch_size)
            buf = []
    if buf:
        yield _pack(config, buf, batch_size)


def _pack(config: Configure, buf: List[Sample], batch_size: int) -> Batch:
    n = len(buf)
    labels = np.zeros(batch_size, np.int32)
    weights = np.zeros(batch_size, np.float32)  # padding rows weigh 0
    labels[:n] = [s.label for s in buf]
    weights[:n] = [s.weight for s in buf]
    if not config.sparse:
        x = np.zeros((batch_size, config.input_size), np.float32)
        for i, sample in enumerate(buf):
            x[i, :sample.values.size] = sample.values
        return Batch(labels, weights, x=x, count=n)
    max_nnz = bucket_size(max(s.keys.size for s in buf))
    keys = np.full((batch_size, max_nnz), config.input_size, np.int64)
    values = np.zeros((batch_size, max_nnz), np.float32)
    for i, sample in enumerate(buf):
        keys[i, :sample.keys.size] = sample.keys
        values[i, :sample.values.size] = sample.values
    return Batch(labels, weights, keys=keys, values=values, count=n)


class PrefetchReader:
    """Background-thread batch prefetcher (the reference's async
    SampleReader ring buffer, ref: reader.cpp; double-buffering like
    ASyncBuffer, ref: include/multiverso/util/async_buffer.h:11-116)."""

    def __init__(self, config: Configure, path: str, depth: int = 4):
        self._queue: "queue_mod.Queue[Optional[Batch]]" = \
            queue_mod.Queue(maxsize=depth)
        self._config = config
        self._path = path
        self._thread = thread_roles.spawn(
            thread_roles.BACKGROUND, target=self._fill,
            name="mv-logreg-prefetch")

    def _fill(self) -> None:
        try:
            for batch in make_batches(self._config,
                                      iter_samples(self._config, self._path)):
                self._queue.put(batch)
        finally:
            self._queue.put(None)

    def __iter__(self) -> Iterator[Batch]:
        while True:
            batch = self._queue.get()
            if batch is None:
                return
            yield batch
