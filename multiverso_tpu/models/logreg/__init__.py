"""LogisticRegression application (ref: Applications/LogisticRegression)."""

from .config import Configure  # noqa: F401
from .model import FTRLModel, LocalModel, PSModel, create_model  # noqa: F401
from .reader import (Batch, PrefetchReader, Sample, iter_samples,  # noqa: F401
                     make_batches, parse_text_line)
