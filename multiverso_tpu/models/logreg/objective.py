"""Batched objectives: linear / sigmoid / softmax (+ regularizers).

TPU-native re-design of the reference's per-sample objective loop
(ref: Applications/LogisticRegression/src/objective/objective.cpp,
sigmoid_objective.h, softmax_objective.h): one jitted function computes the
whole minibatch on the MXU — ``logits = X @ W`` for dense input, a
gather+einsum over padded (keys, values) for sparse input — with the
gradient as ``Xᵀ diff`` (dense) or a scatter-add over touched rows
(sparse). Semantics preserved:

- diff = predict - onehot(label) (ref: objective.cpp Diff);
- displayed loss: clipped-log loss for sigmoid/softmax (MathLog clips at
  1e-6, ref: objective.cpp:16-18), squared error for linear;
- regularization: L1 = coef*sign(w), L2 = coef*w added to the gradient
  (sparse models only regularize touched rows, ref: objective.cpp
  AddRegularization);
- prediction correctness: argmax (binary: round), ref: objective.cpp
  Correct.

Sparse batches pad keys with ``input_size``; the weight matrix carries one
extra padding row so gathers/scatters of padding are harmless zeros.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from .config import Configure

_LOG_CLIP = 1e-6  # ref: objective.cpp:16-18


def _onehot(labels, num_classes):
    """Binary (one output): target = (label == 1), ref: objective.cpp:
    103-111; multiclass: standard one-hot."""
    if num_classes == 1:
        return (labels == 1).astype(jnp.float32)[:, None]
    return jax.nn.one_hot(labels, num_classes, dtype=jnp.float32)


def _regular_grad(regular_type: str, coef: float):
    if regular_type == "L1":
        return lambda w: coef * jnp.sign(w)
    if regular_type == "L2":
        return lambda w: coef * w
    return lambda w: jnp.zeros_like(w)


def _activation_and_loss(objective_type: str):
    """Returns (activation, per-sample loss(pred, onehot))."""
    if objective_type == "sigmoid":
        return jax.nn.sigmoid, lambda p, y: -jnp.sum(
            y * jnp.log(jnp.clip(p, _LOG_CLIP))
            + (1 - y) * jnp.log(jnp.clip(1 - p, _LOG_CLIP)), axis=-1)
    if objective_type in ("softmax", "ftrl_softmax"):
        return (lambda z: jax.nn.softmax(z, axis=-1),
                lambda p, y: -jnp.sum(
                    y * jnp.log(jnp.clip(p, _LOG_CLIP)), axis=-1))
    # default: linear prediction, squared loss (ref: objective.cpp Loss)
    return (lambda z: z,
            lambda p, y: jnp.mean((p - y) ** 2, axis=-1))


def make_dense_step(config: Configure) -> Callable:
    """jit: (w, x, labels, weights) -> (loss_sum, correct, grad).
    ``w`` is [input_size, output_size]; grad is batch-averaged
    (ref: model.cpp:78-103 averages delta over the minibatch)."""
    act, loss_fn = _activation_and_loss(config.objective_type)
    reg = _regular_grad(config.regular_type, config.regular_coef)
    classes = max(config.output_size, 1)

    def step(w, x, labels, weights):
        logits = x @ w
        pred = act(logits)
        y = _onehot(labels, classes)
        diff = (pred - y) * weights[:, None]
        count = jnp.maximum(jnp.sum(weights > 0), 1)
        grad = x.T @ diff / count + reg(w)
        loss_sum = jnp.sum(loss_fn(pred, y) * weights)
        correct = _count_correct(pred, labels, weights, classes)
        return loss_sum, correct, grad

    return jax.jit(step)


def make_sparse_step(config: Configure) -> Callable:
    """jit: (w, keys, values, labels, weights) -> (loss_sum, correct, grad).
    ``w`` is [input_size + 1, output_size] (last row = padding); the grad
    is a same-shape scatter-add, suitable for row-sparse table Adds."""
    act, loss_fn = _activation_and_loss(config.objective_type)
    reg = _regular_grad(config.regular_type, config.regular_coef)
    classes = max(config.output_size, 1)

    def step(w, keys, values, labels, weights):
        rows = w[keys]  # [B, K, C] gather; padding row is zeros
        logits = jnp.einsum("bk,bkc->bc", values, rows)
        pred = act(logits)
        y = _onehot(labels, classes)
        diff = (pred - y) * weights[:, None]
        count = jnp.maximum(jnp.sum(weights > 0), 1)
        # scatter: grad[keys[b,k]] += values[b,k] * diff[b]
        updates = values[..., None] * diff[:, None, :] / count
        grad = jnp.zeros_like(w).at[keys].add(updates)
        # regularize only touched rows (ref: objective.cpp
        # AddRegularization sparse branch)
        touched = jnp.zeros((w.shape[0], 1), w.dtype).at[keys].set(
            1.0, mode="drop")
        grad = grad + touched * reg(w)
        loss_sum = jnp.sum(loss_fn(pred, y) * weights)
        correct = _count_correct(pred, labels, weights, classes)
        return loss_sum, correct, grad

    return jax.jit(step)


def _count_correct(pred, labels, weights, classes) -> jnp.ndarray:
    if classes == 1:
        hit = (pred[:, 0] >= 0.5).astype(jnp.int32) == labels
    else:
        hit = jnp.argmax(pred, axis=-1).astype(jnp.int32) == labels
    return jnp.sum(jnp.where(weights > 0, hit, False))


def make_predict(config: Configure) -> Callable:
    act, _ = _activation_and_loss(config.objective_type)
    if config.sparse:
        def predict(w, keys, values):
            rows = w[keys]
            return act(jnp.einsum("bk,bkc->bc", values, rows))
    else:
        def predict(w, x):
            return act(x @ w)
    return jax.jit(predict)


def learning_rate(config: Configure, update_count: int) -> float:
    """ref: updater.cpp:67-69."""
    return max(1e-3, config.learning_rate
               - update_count / (config.learning_rate_coef
                                 * config.minibatch_size))
