"""Python half of the C ABI shim (loaded by native/c_api).

The C library (native/c_api/multiverso_c_api.cpp) forwards every c_api
call (ref: include/multiverso/c_api.h:14-54) here; buffers arrive as
zero-copy memoryviews over caller memory, wrapped as numpy arrays. Float32
only, matching the reference's c_api instantiation (ref: src/c_api.cpp).
"""

from __future__ import annotations

import numpy as np

import multiverso_tpu as mv


def init(argv) -> None:
    # The reference's binding passes a throwaway argv[0] placeholder
    # (ref: binding/python/multiverso/api.py init); drop it like
    # ParseCMDFlags skips the program name.
    mv.init(list(argv[1:]) if argv else [])


def shutdown() -> None:
    mv.shutdown()


def barrier() -> None:
    mv.barrier()


def net_bind(rank: int, endpoint: str) -> None:
    """MV_NetBind (ref: multiverso.h:55-59): declare this process's rank
    and TCP endpoint before init — app-driven deployment without a
    machine file."""
    mv.net_bind(int(rank), endpoint)


def net_connect(ranks, endpoints) -> None:
    """MV_NetConnect (ref: multiverso.h:60-64)."""
    mv.net_connect([int(r) for r in ranks], list(endpoints))


def num_workers() -> int:
    return mv.num_workers()


def worker_id() -> int:
    return mv.worker_id()


def server_id() -> int:
    return mv.server_id()


def _float_array(view, size=None) -> np.ndarray:
    arr = np.frombuffer(view, dtype=np.float32)
    return arr if size is None else arr[:size]


def _int_array(view) -> np.ndarray:
    return np.frombuffer(view, dtype=np.int32)


# -- array table --

def new_array_table(size: int):
    return mv.create_array_table(size, dtype=np.float32)


def get_array_table(table, out_view) -> None:
    out = _float_array(out_view)
    table.get(out=out)


def add_array_table(table, delta_view, sync: int) -> None:
    delta = _float_array(delta_view)
    if sync:
        table.add(delta)
    else:
        table.add_async(delta.copy())  # caller may reuse its buffer


# -- matrix table --

def new_matrix_table(num_row: int, num_col: int):
    return mv.create_matrix_table(num_row, num_col, dtype=np.float32)


def get_matrix_all(table, out_view) -> None:
    out = _float_array(out_view).reshape(table.num_row, table.num_col)
    table.get(out=out)


def add_matrix_all(table, delta_view, sync: int) -> None:
    delta = _float_array(delta_view)
    if sync:
        table.add(delta)
    else:
        table.add_async(delta.copy())


def get_matrix_rows(table, out_view, rows_view) -> None:
    rows = _int_array(rows_view)
    out = _float_array(out_view).reshape(rows.size, table.num_col)
    table.get_rows(rows, out=out)


def add_matrix_rows(table, delta_view, rows_view, sync: int) -> None:
    rows = _int_array(rows_view)
    delta = _float_array(delta_view).reshape(rows.size, table.num_col)
    if sync:
        table.add_rows(rows, delta)
    else:
        table.add_rows_async(rows.copy(), delta.copy())
