"""Runtime lock-order witness: named locks + deadlock-cycle detection.

The static half of the project's concurrency discipline lives in
``tools/mvlint`` (lock-discipline / device-dispatch passes); this module
is the *runtime* half — the witness(4)-style checker that catches what
lexical analysis cannot: cross-module acquisition ORDER. Three of the
four merged PRs fixed latent ordering hangs after the fact (the PR-1
server-vs-server XLA wedge, the PR-4 two_workers device-pool wedge);
the witness turns the next one from a flaky CI hang into a diagnostic
naming both threads, both locks, and both acquisition stacks.

Usage: construct locks through the factories —

    self._lock = named_lock("tcp[r0].lifecycle")
    self._cond = named_condition("mt_queue[3]")

With ``-debug_locks`` **off** (the default) the factories return plain
``threading`` primitives: zero wrapper frames, zero steady-state
overhead — the production hot path is byte-identical to before. With
the flag **on at construction time**, each factory returns a witness
wrapper that, per acquisition, records the per-thread held-set and adds
edges to one process-wide lock-order graph: acquiring B while holding A
records A -> B. The first acquisition that would close a cycle (a
B -> A edge when A -> B is on record) raises :class:`LockOrderError`
*before blocking*, so the potential deadlock is reported even on runs
where the fatal interleaving never actually fires — that is the whole
point of witness-style checking.

Because the flag is sampled at construction, locks created at import
time (module-level singletons) are witnessed only when the flag is set
before their module first loads — e.g. ``-debug_locks=true`` on the
command line, or ``set_flag`` at the top of a test. Everything the
LocalCluster/TcpNet runtime builds per run is constructed after flag
parsing and is fully covered.
"""

from __future__ import annotations

import contextlib
import threading
import time
import traceback
from typing import Dict, List, Optional, Tuple

from .configure import define_bool, get_flag

define_bool("debug_locks", False,
            "construct witness-wrapped named locks: per-thread held-set "
            "tracking + global lock-order graph with cycle detection "
            "(raises LockOrderError naming both threads, both locks and "
            "both acquisition stacks on a potential-deadlock edge). "
            "Sampled at lock CONSTRUCTION time; off = plain "
            "threading primitives, zero overhead")


class LockOrderError(RuntimeError):
    """A lock acquisition would close a cycle in the lock-order graph."""


# -- witness state (one graph per process) --

_tls = threading.local()  # .held: List[_WitnessLock] for this thread

#: (held_name, acquired_name) -> (thread name, held stack, acquire stack)
_edges: Dict[Tuple[str, str], Tuple[str, str, str]] = {}
_graph_lock = threading.Lock()

#: Every diagnostic the witness produced, in order (tests assert on
#: this; the raising path appends before it raises).
_reports: List[str] = []


def enabled() -> bool:
    """Whether locks constructed NOW would be witnessed."""
    return bool(get_flag("debug_locks"))


def reports() -> List[str]:
    with _graph_lock:
        return list(_reports)


def reset() -> None:
    """Drop the order graph and report log (test isolation)."""
    with _graph_lock:
        _edges.clear()
        _reports.clear()


def _held() -> list:
    held = getattr(_tls, "held", None)
    if held is None:
        held = _tls.held = []
    return held


def _stack() -> str:
    # Skip the witness frames themselves; keep the caller context.
    return "".join(traceback.format_stack(limit=16)[:-3])


_RLOCK_TYPE = type(threading.RLock())


def _note_attempt(lock: "_WitnessLock", blocking: bool = True,
                  timeout: float = -1) -> None:
    """Record order edges for acquiring ``lock`` while holding the
    thread's current held-set; raise on a would-be cycle. Runs BEFORE
    the real acquire blocks, so a true AB/BA interleaving reports
    instead of deadlocking."""
    held = _held()
    me = threading.current_thread().name
    for h, h_stack in held:
        if h is not lock:
            continue
        if isinstance(lock.lock, _RLOCK_TYPE):
            return  # RLock re-entry: legal, no new ordering fact
        if not blocking or timeout >= 0:
            return  # bounded probe: fails naturally, caller handles it
        # Re-acquiring a held NON-reentrant lock with an unbounded
        # blocking acquire: the simplest deadlock there is — report it
        # instead of silently hanging (the hang is what this tool
        # exists to replace).
        report = (f"self-deadlock: thread {me!r} re-acquiring "
                  f"non-reentrant lock {lock.name!r} it already "
                  f"holds\n  first held at:\n{_indent(h_stack)}"
                  f"  re-acquired at:\n{_indent(_stack())}")
        with _graph_lock:
            _reports.append(report)
        raise LockOrderError(report)
    if not blocking or timeout >= 0:
        # Bounded probes cannot deadlock forever: a cycle report here
        # would crash shutdown paths (acquire_timeout) that are
        # deadlock-free by construction, and a pre-recorded edge for an
        # acquire that then times out would poison later reports. The
        # witness stays conservative: no edges, no raise.
        return
    if not held:
        return  # nothing to order against: skip the stack capture
    my_stack = _stack()
    with _graph_lock:
        for h, h_stack in held:
            if h.name == lock.name:
                continue
            edge = (h.name, lock.name)
            if edge in _edges:
                continue
            cycle = _find_path(lock.name, h.name)
            if cycle is not None:
                other_thread, other_held_stack, other_acq_stack = \
                    _edges[(cycle[0], cycle[1])]
                report = (
                    f"potential deadlock: lock-order cycle "
                    f"{' -> '.join(cycle)} -> {cycle[0]}\n"
                    f"  thread {me!r} holds {h.name!r} and wants "
                    f"{lock.name!r}; held at:\n{_indent(h_stack)}"
                    f"  ... wants it at:\n{_indent(my_stack)}"
                    f"  thread {other_thread!r} previously took "
                    f"{cycle[1]!r} while holding {cycle[0]!r}; "
                    f"held at:\n{_indent(other_held_stack)}"
                    f"  ... acquired at:\n{_indent(other_acq_stack)}")
                _reports.append(report)
                raise LockOrderError(report)
            _edges[edge] = (me, h_stack, my_stack)


def _note_acquired(lock: "_WitnessLock") -> None:
    _held().append((lock, _stack()))


def _note_released(lock: "_WitnessLock") -> bool:
    """Drop the most recent held entry for ``lock``; True iff one was
    actually held (callers re-add only what they removed)."""
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] is lock:
            del held[i]
            return True
    return False


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS over the order graph: a path src ->* dst (edge list held
    under _graph_lock by the caller). Returns the node path or None."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for (a, b) in _edges:
            if a == node and b not in seen:
                seen.add(b)
                stack.append((b, path + [b]))
    return None


def _indent(text: str) -> str:
    return "".join(f"    {line}\n" for line in text.rstrip().splitlines())


class _WitnessLock:
    """Witness wrapper around a Lock/RLock. Not re-entrant bookkeeping
    itself — re-entrant acquires of a wrapped RLock are recognized in
    ``_note_attempt`` and tracked per nesting level in the held list."""

    __slots__ = ("lock", "name")

    def __init__(self, inner, name: str):
        self.lock = inner
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        # Re-check the flag per acquire: a wrapper outlives a flag
        # flip (monitors in the process-wide Dashboard registry, for
        # one), and witness bookkeeping — the stack captures above
        # all — must not keep taxing hot paths after -debug_locks is
        # turned off. Release stays unconditional so an entry added
        # while enabled is always removed.
        if not enabled():
            return self.lock.acquire(blocking, timeout)
        _note_attempt(self, blocking, timeout)
        got = self.lock.acquire(blocking, timeout)
        if got:
            _note_acquired(self)
        return got

    def release(self) -> None:
        self.lock.release()
        _note_released(self)

    def locked(self) -> bool:
        return self.lock.locked()

    def __enter__(self) -> "_WitnessLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"<witness {self.name} over {self.lock!r}>"


class _WitnessCondition:
    """Witness wrapper around ``threading.Condition``.

    The underlying lock is tracked through a :class:`_WitnessLock`;
    ``wait``/``wait_for`` drop it from the held-set for the duration
    (the condition releases its lock while waiting — holding it in the
    witness view would manufacture false ordering edges from whatever
    the *waking* code acquires)."""

    __slots__ = ("_wit", "_cond")

    def __init__(self, name: str, lock=None):
        if isinstance(lock, _WitnessLock):
            self._wit = lock
        elif lock is None:
            self._wit = _WitnessLock(threading.Lock(), name)
        else:  # a plain primitive handed in: wrap it under this name
            self._wit = _WitnessLock(lock, name)
        self._cond = threading.Condition(self._wit.lock)

    @property
    def name(self) -> str:
        return self._wit.name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._wit.acquire(blocking, timeout)

    def release(self) -> None:
        self._wit.release()

    def __enter__(self) -> "_WitnessCondition":
        self._wit.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self._wit.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        # Re-add only what was actually removed: wait() on an
        # un-acquired condition raises RuntimeError, and a phantom
        # held entry would turn the thread's NEXT legitimate acquire
        # into a false self-deadlock report.
        removed = _note_released(self._wit)
        try:
            return self._cond.wait(timeout)
        finally:
            if removed:
                _note_acquired(self._wit)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        removed = _note_released(self._wit)
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            if removed:
                _note_acquired(self._wit)

    def notify(self, n: int = 1) -> None:
        self._cond.notify(n)

    def notify_all(self) -> None:
        self._cond.notify_all()


# -- injectable thread model (tools/mvchk) --

#: When a model is installed, the factories below build ITS
#: cooperative primitives instead of ``threading``'s, and
#: :func:`monotonic` reads its virtual clock — that is the entire
#: hook surface the mvchk deterministic-schedule checker needs to run
#: MtQueue/Waiter under controlled interleavings. Sampled at
#: CONSTRUCTION time like ``-debug_locks``: primitives built while no
#: model is installed are plain ``threading`` objects with zero
#: steady-state overhead.
_THREAD_MODEL = None


def install_thread_model(model) -> None:
    """``model`` provides ``lock(name)``, ``rlock(name)``,
    ``condition(name, lock)`` and ``monotonic()``."""
    global _THREAD_MODEL
    _THREAD_MODEL = model


def clear_thread_model() -> None:
    global _THREAD_MODEL
    _THREAD_MODEL = None


def monotonic() -> float:
    """``time.monotonic()``, or the installed model's virtual clock —
    deadline math in the primitives routes through here so a model
    checker can expire timeouts deterministically."""
    if _THREAD_MODEL is not None:
        return _THREAD_MODEL.monotonic()
    return time.monotonic()


# -- factories (the only public construction path) --

def named_lock(name: str):
    """A ``threading.Lock`` — witness-wrapped iff -debug_locks is set
    at the moment of construction."""
    if _THREAD_MODEL is not None:
        return _THREAD_MODEL.lock(name)
    if enabled():
        return _WitnessLock(threading.Lock(), name)
    return threading.Lock()


def named_rlock(name: str):
    if _THREAD_MODEL is not None:
        return _THREAD_MODEL.rlock(name)
    if enabled():
        return _WitnessLock(threading.RLock(), name)
    return threading.RLock()


def named_condition(name: str, lock=None):
    """A ``threading.Condition``. Pass ``lock`` to share a mutex the
    way ``threading.Condition(mutex)`` does — a ``named_lock`` result
    (plain or witnessed) is accepted."""
    if _THREAD_MODEL is not None:
        return _THREAD_MODEL.condition(name, lock)
    if enabled() or isinstance(lock, _WitnessLock):
        return _WitnessCondition(name, lock)
    return threading.Condition(lock)


@contextlib.contextmanager
def acquire_timeout(lock, timeout: float):
    """``with``-discipline bounded acquisition: yields True iff the
    lock was taken within ``timeout`` seconds, releasing on exit iff
    taken. The body must branch on the yielded flag. This is the one
    sanctioned alternative to a bare ``acquire/release`` pair (the
    lock-discipline lint flags those), for paths — e.g. shutdown —
    where blocking forever on a wedged peer is worse than skipping."""
    got = lock.acquire(timeout=timeout)
    try:
        yield got
    finally:
        if got:
            lock.release()
