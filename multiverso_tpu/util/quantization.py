"""Wire compression filters for sparse table traffic.

TPU-native equivalent of the reference's ``SparseFilter``
(ref: include/multiverso/util/quantization_util.h:25-158). Per payload blob:
if more than half of the values are within ``clip_value`` of zero, the blob
is rewritten as a compact codec frame (int32 indices + typed values — see
``multiverso_tpu.util.wire_codec``); a side "size record" carries the
original element count, with -1 meaning "left uncompressed". ``filter_in``
compresses an outgoing list of arrays, ``filter_out`` reverses it.

The reference encoded surviving pairs as float64 (16 bytes per pair,
break-even only below 50% density); that format is REMOVED — frames are
now int32 index + fp32 value (8 bytes per pair, lossless) or the codec's
quantized tiers when the caller opts into lossy transport.

The reference's ``OneBitsFilter`` is an empty stub
(quantization_util.h:160-161); here ``OneBitFilter`` implements the standard
1-bit SGD scheme (sign + per-blob scale, error feedback left to the caller)
as the functional completion of that stub.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import wire_codec

UNCOMPRESSED = -1


class SparseFilter:
    def __init__(self, clip_value: float = 0.0, lossy: bool = False):
        self._clip = float(clip_value)
        self._lossy = bool(lossy)
        #: Error-feedback residual of the last lossy ``filter_in`` (one
        #: entry per blob; None where the encoding was lossless). The
        #: caller folds it into the next delta, OneBitFilter-style.
        self.last_residuals: List[Optional[np.ndarray]] = []

    def filter_in(self, blobs: Sequence[np.ndarray]
                  ) -> Tuple[List[np.ndarray], np.ndarray]:
        """Compress each blob independently.

        Returns (compressed_blobs, size_record) where size_record[i] is the
        original element count if blob i was compressed, else UNCOMPRESSED.
        """
        out: List[np.ndarray] = []
        self.last_residuals = []
        sizes = np.empty(len(blobs), dtype=np.int64)
        for i, blob in enumerate(blobs):
            arr = np.asarray(blob)
            flat = arr.reshape(-1)
            nonzero = np.abs(flat) > self._clip
            n_keep = int(np.count_nonzero(nonzero))
            if flat.size > 0 and n_keep * 2 < flat.size:
                frame, residual = wire_codec.encode_blob(
                    flat, lossy=self._lossy, clip=self._clip)
                out.append(np.frombuffer(frame, np.uint8))
                self.last_residuals.append(residual)
                sizes[i] = flat.size
            else:
                out.append(flat)
                self.last_residuals.append(None)
                sizes[i] = UNCOMPRESSED
        return out, sizes

    def filter_out(self, blobs: Sequence[np.ndarray], size_record: np.ndarray,
                   dtype=np.float32) -> List[np.ndarray]:
        """Reverse ``filter_in``."""
        out: List[np.ndarray] = []
        for blob, size in zip(blobs, size_record):
            if size == UNCOMPRESSED:
                out.append(np.asarray(blob, dtype=dtype))
                continue
            full = wire_codec.decode_blob(np.asarray(blob))
            out.append(full.astype(dtype, copy=False))
        return out


class OneBitFilter:
    """1-bit quantization: sign bitmap + mean-magnitude scales per sign.

    Functional completion of the reference's empty ``OneBitsFilter`` stub
    (quantization_util.h:160-161). Encoding per blob: (packed sign bits,
    positive mean, negative mean, original size). Decoding reconstructs
    each element as the mean magnitude of its sign class. Error-feedback
    residual is returned to the caller to accumulate locally.
    """

    def encode(self, arr: np.ndarray):
        flat = np.asarray(arr, dtype=np.float32).reshape(-1)
        pos = flat > 0
        pos_mean = float(flat[pos].mean()) if pos.any() else 0.0
        neg = ~pos
        neg_mean = float(flat[neg].mean()) if neg.any() else 0.0
        bits = np.packbits(pos.astype(np.uint8))
        decoded = np.where(pos, pos_mean, neg_mean).astype(np.float32)
        residual = flat - decoded
        return (bits, pos_mean, neg_mean, flat.size), residual

    def decode(self, encoded) -> np.ndarray:
        bits, pos_mean, neg_mean, size = encoded
        pos = np.unpackbits(bits)[:size].astype(bool)
        return np.where(pos, np.float32(pos_mean), np.float32(neg_mean))
