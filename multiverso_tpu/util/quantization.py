"""Wire compression filters for sparse table traffic.

TPU-native equivalent of the reference's ``SparseFilter``
(ref: include/multiverso/util/quantization_util.h:25-158). Per payload blob:
if more than half of the values are within ``clip_value`` of zero, the blob
is rewritten as (index, value) pairs; a side "size record" carries the
original element count, with -1 meaning "left uncompressed". ``filter_in``
compresses an outgoing list of arrays, ``filter_out`` reverses it.

Vectorized with numpy (the reference loops element-wise); on-device
equivalents for ICI paths live in ``multiverso_tpu.parallel.collectives``
(top-k / threshold sparsification before a ragged all-to-all).

The reference's ``OneBitsFilter`` is an empty stub
(quantization_util.h:160-161); here ``OneBitFilter`` implements the standard
1-bit SGD scheme (sign + per-blob scale, error feedback left to the caller)
as the functional completion of that stub.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

UNCOMPRESSED = -1


class SparseFilter:
    def __init__(self, clip_value: float = 0.0):
        self._clip = float(clip_value)

    def filter_in(self, blobs: Sequence[np.ndarray]) -> Tuple[List[np.ndarray], np.ndarray]:
        """Compress each blob independently.

        Returns (compressed_blobs, size_record) where size_record[i] is the
        original element count if blob i was compressed, else UNCOMPRESSED.
        """
        out: List[np.ndarray] = []
        sizes = np.empty(len(blobs), dtype=np.int64)
        for i, blob in enumerate(blobs):
            arr = np.asarray(blob)
            flat = arr.reshape(-1)
            nonzero = np.abs(flat) > self._clip
            n_keep = int(np.count_nonzero(nonzero))
            if flat.size > 0 and n_keep * 2 < flat.size:
                idx = np.nonzero(nonzero)[0]
                vals = flat[idx]
                # float64 pairs: holds indices exactly up to 2^53 and float32
                # values exactly; halves the wire size whenever <50% survive.
                pairs = np.empty(idx.size * 2, dtype=np.float64)
                pairs[0::2] = idx
                pairs[1::2] = vals
                out.append(pairs)
                sizes[i] = flat.size
            else:
                out.append(flat)
                sizes[i] = UNCOMPRESSED
        return out, sizes

    def filter_out(self, blobs: Sequence[np.ndarray], size_record: np.ndarray,
                   dtype=np.float32) -> List[np.ndarray]:
        """Reverse ``filter_in``."""
        out: List[np.ndarray] = []
        for blob, size in zip(blobs, size_record):
            if size == UNCOMPRESSED:
                out.append(np.asarray(blob, dtype=dtype))
                continue
            pairs = np.asarray(blob, dtype=np.float64)
            full = np.zeros(int(size), dtype=dtype)
            idx = pairs[0::2].astype(np.int64)
            full[idx] = pairs[1::2].astype(dtype)
            out.append(full)
        return out


class OneBitFilter:
    """1-bit quantization: sign bitmap + mean-magnitude scales per sign.

    Functional completion of the reference's empty ``OneBitsFilter`` stub
    (quantization_util.h:160-161). Encoding per blob: (packed sign bits,
    positive mean, negative mean, original size). Decoding reconstructs
    each element as the mean magnitude of its sign class. Error-feedback
    residual is returned to the caller to accumulate locally.
    """

    def encode(self, arr: np.ndarray):
        flat = np.asarray(arr, dtype=np.float32).reshape(-1)
        pos = flat > 0
        pos_mean = float(flat[pos].mean()) if pos.any() else 0.0
        neg = ~pos
        neg_mean = float(flat[neg].mean()) if neg.any() else 0.0
        bits = np.packbits(pos.astype(np.uint8))
        decoded = np.where(pos, pos_mean, neg_mean).astype(np.float32)
        residual = flat - decoded
        return (bits, pos_mean, neg_mean, flat.size), residual

    def decode(self, encoded) -> np.ndarray:
        bits, pos_mean, neg_mean, size = encoded
        pos = np.unpackbits(bits)[:size].astype(bool)
        return np.where(pos, np.float32(pos_mean), np.float32(neg_mean))
