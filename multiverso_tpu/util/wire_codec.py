"""Compact quantized wire codec for PS / model-average traffic.

Replaces the float64 (index, value) pair encoding of the original
``SparseFilter`` (which spent 16 bytes per surviving pair and only broke
even below 50% density) with a compact self-describing frame:

    [24-byte header][payload]

    offset  size  field
    0       2     magic  b"MV"
    2       1     version (1)
    3       1     tier
    4       1     original dtype code (see _DTYPES)
    5       1     index encoding (sparse tiers: 0 = absolute int32,
                  1 = u32 first index + u16 gaps — SparCML-style
                  delta-compressed index stream)
    6       2     quantization chunk size (u16; int8 tiers)
    8       8     n    — original element count (u64)
    16      8     nnz  — stored element count (u64; == n for dense tiers)

Tiers (SparCML-style sparse index + value streams; EQuARX-style
quantized values). Per-pair cost shown with absolute / gap indices:

    RAW        (0)  original bytes verbatim, any dtype
    SPARSE_F32 (1)  idx[nnz] + float32 val[nnz]      (lossless, 8 / 6 B)
    SPARSE_F16 (2)  idx[nnz] + float16 val[nnz]      (lossy,    6 / 4 B)
    SPARSE_I8  (3)  idx[nnz] + f32 scale/chunk + i8  (lossy,   ~5 / 3 B)
    DENSE_F16  (4)  float16 val[n]                    (lossy)
    DENSE_I8   (5)  f32 scale/chunk + int8 val[n]     (lossy)

Tier selection is per blob: among the tiers the caller allows (lossless
only by default), pick the smallest wire size, breaking ties toward
higher fidelity. fp16 tiers are only eligible when the blob's magnitudes
fit fp16's normal range (no overflow to inf, no flush of the largest
values); int8 tiers only when the per-blob dynamic range is modest enough
that a per-chunk scale keeps quantization noise below ~1% of the chunk
max. Lossy encodes return an error-feedback residual (``OneBitFilter``
convention: the caller folds it into the next delta), so quantization
noise averages out over steps instead of accumulating.

The message-level helpers (``encode_message``/``decode_message``) apply
the codec blob-by-blob as the transport filter stage: header slot
``CODEC_SLOT`` marks an encoded message, so frames are self-describing
on the wire and a receiver never guesses. Senders must still negotiate —
``encode_message`` is only called for peers that advertised
``CAP_WIRE_CODEC`` during registration (zoo/controller), so a peer
running without the codec keeps receiving plain frames.
"""

from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from .configure import (define_bool, define_double, get_flag,
                        register_tunable_hook)

define_bool("wire_codec", True,
            "advertise + apply the compact wire codec on cross-process "
            "transports (lossless tiers at the transport filter stage; "
            "negotiated per peer at registration)")
define_bool("wire_codec_lossy", False,
            "allow the int8/fp16 value tiers for sparse matrix Add "
            "traffic, with worker-side error-feedback residuals "
            "(pulls stay lossless)")
define_double("wire_codec_density", 0.5,
              "break-even density for the LOSSLESS sparse tier: float32 "
              "payloads whose nonzero fraction sits below this ride "
              "sparse index+value streams; denser ones pass through "
              "RAW. 0.5 is the wire-cost break-even for the worst-case "
              "absolute-int32 index stream (8 B/pair vs 4 B/element); "
              "lower it when encode CPU dominates a fast local wire, "
              "raise it (toward ~0.67) when the u16-gap stream (6 "
              "B/pair) is known to engage")


def _density_retuned(value) -> None:
    """``-wire_codec_density`` is read fresh per encoded frame
    (``break_even_density``), so a live retune needs no state rebind —
    the hook declares the handoff (TUNABLE_FLAGS contract) and logs
    the step for rank-local traceability (docs/AUTOTUNE.md)."""
    from . import log
    log.info("wire codec: -wire_codec_density retuned to %s (applies "
             "from the next encoded frame)", value)


register_tunable_hook("wire_codec_density", _density_retuned)

MAGIC = b"MV"
VERSION = 1
HEADER = struct.Struct("<2sBBBBHQQ")  # magic, ver, tier, dtype, idx, chunk, n, nnz
HEADER_BYTES = HEADER.size  # 24

# Index-stream encodings for the sparse tiers.
IDX_I32 = 0   # absolute int32 indices
IDX_GAP16 = 1  # u32 first index + u16 gaps (all gaps must fit 16 bits)

# Tier codes (wire-stable; new tiers append).
RAW = 0
SPARSE_F32 = 1
SPARSE_F16 = 2
SPARSE_I8 = 3
DENSE_F16 = 4
DENSE_I8 = 5

_TIER_NAMES = {RAW: "raw", SPARSE_F32: "sparse_f32", SPARSE_F16: "sparse_f16",
               SPARSE_I8: "sparse_i8", DENSE_F16: "dense_f16",
               DENSE_I8: "dense_i8"}

# Wire-stable dtype codes for the ORIGINAL array (decode restores it).
_DTYPES = [np.dtype(d) for d in (
    np.float32, np.float64, np.int32, np.int64, np.uint8, np.float16,
    np.int8, np.int16, np.uint16, np.uint32, np.uint64, np.bool_)]
_DTYPE_CODE = {d: i for i, d in enumerate(_DTYPES)}

_CHUNK = 256          # int8 quantization chunk (one fp32 scale per chunk)
_FP16_MAX = 65504.0   # largest finite fp16
# int8 eligibility: per-chunk scale gives a step of chunkmax/127; a blob
# whose magnitudes span more than this ratio would quantize its small
# values to zero outright (error feedback covers noise, not starvation).
_I8_MAX_DYNAMIC_RANGE = 1e4

# Message header slot marking a codec-encoded payload — single source
# of truth lives next to the header layout in core.message (slot 5 is
# the error flag; the reference leaves 5-7 unused, message.h:28-38);
# re-exported here because every codec caller already imports this
# module.
from ..core.message import CODEC_SLOT  # noqa: E402

# Capability bit advertised in the registration handshake.
CAP_WIRE_CODEC = 1


def tier_name(tier: int) -> str:
    return _TIER_NAMES.get(tier, f"tier{tier}")


def _dtype_code(dtype: np.dtype) -> Optional[int]:
    return _DTYPE_CODE.get(np.dtype(dtype))


def _quantize_i8(vals: np.ndarray, chunk: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-chunk symmetric int8: q = round(v * 127 / chunkmax)."""
    n = vals.size
    nchunks = max((n + chunk - 1) // chunk, 1)
    padded = np.zeros(nchunks * chunk, np.float32)
    padded[:n] = vals
    mags = np.abs(padded).reshape(nchunks, chunk).max(axis=1)
    scales = (mags / 127.0).astype(np.float32)
    safe = np.where(scales > 0, scales, 1.0)
    q = np.clip(np.rint(padded.reshape(nchunks, chunk) / safe[:, None]),
                -127, 127).astype(np.int8)
    return q.reshape(-1)[:n], scales


def _dequantize_i8(q: np.ndarray, scales: np.ndarray, chunk: int) -> np.ndarray:
    n = q.size
    nchunks = scales.size
    padded = np.zeros(nchunks * chunk, np.int8)
    padded[:n] = q
    vals = padded.reshape(nchunks, chunk).astype(np.float32) * scales[:, None]
    return vals.reshape(-1)[:n]


def _fp16_fits(vals: np.ndarray) -> bool:
    if vals.size == 0:
        return True
    peak = float(np.max(np.abs(vals)))
    return np.isfinite(peak) and peak <= _FP16_MAX


def _i8_fits(vals: np.ndarray) -> bool:
    if vals.size == 0:
        return True
    mags = np.abs(vals[vals != 0])
    if mags.size == 0:
        return True
    peak = float(mags.max())
    return np.isfinite(peak) and peak / float(mags.min()) \
        <= _I8_MAX_DYNAMIC_RANGE


def encode_blob(arr, *, lossy: bool = False,
                clip: float = 0.0) -> Tuple[bytes, Optional[np.ndarray]]:
    """Encode one array into a flat codec frame (compat wrapper over
    ``encode_blob_views``; the transport filter stage uses the views
    form directly so the header/payload never get joined).

    Returns ``(frame_bytes, residual)``; ``residual`` is the fp32
    error-feedback vector (original - decoded) when a lossy tier was
    chosen, else None. Non-float32 arrays and empty arrays ride RAW.
    """
    parts, residual = encode_blob_views(arr, lossy=lossy, clip=clip)
    frame = b"".join(  # mvlint: ignore[copy-lint] - the FLAT form IS
        # this wrapper's contract (table-level codec frames, tests,
        # bench); the wire path rides the unjoined parts
        p if isinstance(p, (bytes, bytearray))
        else p.tobytes() for p in parts)  # mvlint: ignore[copy-lint]
    return frame, residual


def encode_blob_views(arr, *, lossy: bool = False,
                      clip: float = 0.0
                      ) -> Tuple[List, Optional[np.ndarray]]:
    """Encode one array into codec-frame PARTS: ``parts[0]`` is the
    24-byte header, the rest are the payload streams (index / scale /
    value arrays) in wire order — handed to ``Blob.from_parts`` so the
    scatter-gather framer writes each straight from its own memory
    instead of paying the old ``head + payload.tobytes()`` concat. For
    a RAW-tier float-dense payload the value stream is a zero-copy
    view of the caller's array. Joining the parts reproduces
    ``encode_blob``'s frame byte for byte."""
    arr = np.asarray(arr)
    flat = np.ascontiguousarray(arr).reshape(-1)
    dcode = _dtype_code(flat.dtype)
    if dcode is None:
        flat = flat.view(np.uint8)
        dcode = _DTYPE_CODE[np.dtype(np.uint8)]
    n = flat.size
    if flat.dtype != np.float32 or n == 0:
        head = HEADER.pack(MAGIC, VERSION, RAW, dcode, 0, 0, n, n)
        return [head, flat], None

    # Non-finite values MUST survive: NaN compares False against the
    # clip so a plain magnitude test would drop a diverging trainer's
    # NaN gradients and deliver zeros — masking the divergence and
    # desyncing remote state from local. (NaN also poisons the fp16/i8
    # eligibility checks below, so lossy tiers stay out too.)
    nonzero = (np.abs(flat) > clip) | ~np.isfinite(flat)
    nnz = int(np.count_nonzero(nonzero))
    # Sparse tiers cannot win at >= 80% density (cheapest is ~5 B/pair
    # vs 4 B/element raw), so skip the index-stream work entirely for
    # dense blobs — np.nonzero would allocate an int64 vector up to 2x
    # the payload just to throw it away.
    if nnz * 5 <= n * 4:
        idx = np.nonzero(nonzero)[0]
        # Index stream: u16 gaps when every gap fits (the common case
        # for power-law ML traffic — SparCML's insight), else absolute
        # int32.
        gaps = np.diff(idx)
        gap_ok = nnz > 0 and (gaps.size == 0 or int(gaps.max()) < 65536) \
            and int(idx[0]) < 2 ** 32
    else:
        idx = gaps = None
        gap_ok = False
    idx_enc = IDX_GAP16 if gap_ok else IDX_I32
    idx_bytes = (4 + 2 * (nnz - 1)) if gap_ok else 4 * nnz
    nchunks_d = max((n + _CHUNK - 1) // _CHUNK, 1)
    nchunks_s = max((nnz + _CHUNK - 1) // _CHUNK, 1)
    # (cost_bytes, fidelity_rank, tier): min cost wins, ties -> fidelity.
    candidates = [(n * 4, 0, RAW)]
    if idx is not None:
        candidates.append((idx_bytes + nnz * 4, 1, SPARSE_F32))
    if lossy:
        # Dense blobs skip the boolean-mask gather: the eligibility
        # checks ignore zeros anyway (fp16 looks at the max magnitude,
        # i8 excludes exact zeros), and flat[nonzero] would copy ~the
        # whole payload — the dominant encode cost for the allreduce
        # engine's dense model-average segments.
        vals = flat if idx is None else flat[idx]
        if _fp16_fits(vals):
            candidates.append((n * 2, 2, DENSE_F16))
            if idx is not None:
                candidates.append((idx_bytes + nnz * 2, 2, SPARSE_F16))
        if _i8_fits(vals):
            candidates.append((n + nchunks_d * 4, 3, DENSE_I8))
            if idx is not None:
                candidates.append((idx_bytes + nnz + nchunks_s * 4, 3,
                                   SPARSE_I8))
    _, _, tier = min(candidates)

    residual: Optional[np.ndarray] = None
    if tier == RAW:
        payload = [flat]  # zero-copy view: the dense fast path
        stored = n
        idx_enc = 0
    elif tier in (SPARSE_F32, SPARSE_F16, SPARSE_I8):
        vals = flat[idx]
        stored = nnz
        if idx_enc == IDX_GAP16:
            idx_stream = [np.asarray([idx[0]], np.uint32),
                          gaps.astype(np.uint16)]
        else:
            idx_stream = [idx.astype(np.int32)]
        if tier == SPARSE_F32:
            payload = idx_stream + [vals]
        elif tier == SPARSE_F16:
            half = vals.astype(np.float16)
            payload = idx_stream + [half]
            residual = np.zeros(n, np.float32)
            residual[idx] = vals - half.astype(np.float32)
        else:
            q, scales = _quantize_i8(vals, _CHUNK)
            payload = idx_stream + [scales, q]
            residual = np.zeros(n, np.float32)
            residual[idx] = vals - _dequantize_i8(q, scales, _CHUNK)
    elif tier == DENSE_F16:
        half = flat.astype(np.float16)
        payload = [half]
        stored = n
        idx_enc = 0
        residual = flat - half.astype(np.float32)
    else:  # DENSE_I8
        q, scales = _quantize_i8(flat, _CHUNK)
        payload = [scales, q]
        stored = n
        idx_enc = 0
        residual = flat - _dequantize_i8(q, scales, _CHUNK)
    head = HEADER.pack(MAGIC, VERSION, tier, dcode, idx_enc,
                       _CHUNK if tier in (SPARSE_I8, DENSE_I8) else 0,
                       n, stored)
    return [head] + payload, residual


def is_codec_frame(data) -> bool:
    """Structural sniff: does this buffer start with a valid codec
    header? Used by receivers whose peer MAY be running without the
    table-level codec (e.g. a cross-rank -sparse_compress mismatch) to
    fall back to the raw layout instead of raising into an actor loop.
    A raw float32 payload whose first bytes spell the magic+version is
    astronomically unlikely (a specific denormal pattern)."""
    buf = _as_bytes(data)
    if len(buf) < HEADER_BYTES:
        return False
    magic, version, tier, dcode, idx_enc, _, n, nnz = \
        HEADER.unpack_from(buf, 0)
    return (magic == MAGIC and version == VERSION
            and tier in _TIER_NAMES and dcode < len(_DTYPES)
            and idx_enc in (IDX_I32, IDX_GAP16) and nnz <= n)


def peek_tier(data) -> int:
    """Tier code of a codec frame (raises on a non-codec buffer)."""
    buf = _as_bytes(data)
    magic, version, tier, _, _, _, _, _ = HEADER.unpack_from(buf, 0)
    if magic != MAGIC or version != VERSION:
        raise ValueError("not a wire-codec frame")
    return tier


def _as_bytes(data) -> memoryview:
    if isinstance(data, np.ndarray):
        return memoryview(np.ascontiguousarray(data).view(np.uint8)
                          .reshape(-1))
    return memoryview(data)


def _validated_header(buf) -> Tuple[int, int, int, int, int, int]:
    """Unpack + validate one frame header; the single unpack site both
    decode paths share. Returns (tier, dcode, idx_enc, chunk, n, nnz)."""
    magic, version, tier, dcode, idx_enc, chunk, n, nnz = \
        HEADER.unpack_from(buf, 0)
    if magic != MAGIC:
        raise ValueError("wire codec: bad magic (not a codec frame)")
    if version != VERSION:
        raise ValueError(f"wire codec: unsupported version {version}")
    return tier, dcode, idx_enc, chunk, n, nnz


def decode_blob_sparse(data) -> Tuple[Optional[np.ndarray], np.ndarray]:
    """Sparse-stream view of one codec frame: ``(idx, vals)``.

    For the sparse tiers ``idx`` is the int64 index vector and ``vals``
    the float32 values, one per index — WITHOUT materializing the dense
    array. This is the collective merge path: an owner folds
    ``acc[idx] += vals`` in O(nnz) per incoming stream instead of the
    O(n) a dense decode + dense add would cost. For RAW / dense tiers
    ``idx`` is None and ``vals`` is the full flat payload (RAW keeps its
    original dtype; dense lossy tiers dequantize to float32).
    ``vals`` may be a read-only view into the frame buffer — callers
    must not mutate it (``decode_blob`` copies where its contract needs
    ownership)."""
    buf = _as_bytes(data)
    tier, dcode, idx_enc, chunk, n, nnz = _validated_header(buf)
    return _decode_streams(buf, tier, dcode, idx_enc, chunk, n, nnz)


def _decode_streams(buf, tier, dcode, idx_enc, chunk, n,
                    nnz) -> Tuple[Optional[np.ndarray], np.ndarray]:
    body = buf[HEADER_BYTES:]
    dtype = _DTYPES[dcode]
    if tier == RAW:
        return None, np.frombuffer(body, dtype, n)
    if tier == DENSE_F16:
        return None, np.frombuffer(body, np.float16, n).astype(np.float32)
    if tier == DENSE_I8:
        nchunks = max((n + chunk - 1) // chunk, 1)
        scales = np.frombuffer(body, np.float32, nchunks)
        q = np.frombuffer(body, np.int8, n, nchunks * 4)
        return None, _dequantize_i8(q, scales, chunk)
    if tier not in (SPARSE_F32, SPARSE_F16, SPARSE_I8):
        raise ValueError(f"wire codec: unknown tier {tier}")
    if idx_enc == IDX_GAP16:
        first = int(np.frombuffer(body, np.uint32, 1)[0])
        gaps = np.frombuffer(body, np.uint16, nnz - 1, 4)
        idx = np.empty(nnz, np.int64)
        idx[0] = first
        idx[1:] = first + np.cumsum(gaps.astype(np.int64))
        off = 4 + 2 * (nnz - 1)
    else:
        idx = np.frombuffer(body, np.int32, nnz)
        off = nnz * 4
    if tier == SPARSE_F32:
        vals = np.frombuffer(body, np.float32, nnz, off)
    elif tier == SPARSE_F16:
        vals = np.frombuffer(body, np.float16, nnz, off) \
            .astype(np.float32)
    else:
        nchunks = max((nnz + chunk - 1) // chunk, 1)
        scales = np.frombuffer(body, np.float32, nchunks, off)
        q = np.frombuffer(body, np.int8, nnz, off + nchunks * 4)
        vals = _dequantize_i8(q, scales, chunk)
    return idx, vals


def decode_blob(data) -> np.ndarray:
    """Decode one codec frame back to a flat array of its original dtype."""
    buf = _as_bytes(data)
    tier, dcode, idx_enc, chunk, n, nnz = _validated_header(buf)
    idx, vals = _decode_streams(buf, tier, dcode, idx_enc, chunk, n, nnz)
    dtype = _DTYPES[dcode]
    if idx is None:
        if tier == RAW:
            return vals.copy()  # the caller owns its decoded array
        return vals.astype(dtype, copy=False)
    full = np.zeros(n, np.float32)
    full[idx] = vals
    return full.astype(dtype, copy=False)


# ---------------------------------------------------------------------------
# Message-level filter stage (used by the communicator + allreduce engine).
# ---------------------------------------------------------------------------

#: Below this total payload size, framing overhead + the density scan
#: cost more than the bytes they could save — the message passes through.
MIN_ENCODE_BYTES = 1024


def density_of(arr) -> float:
    """Nonzero fraction of a host array (0.0 for an empty one) — one
    cheap count_nonzero pass, the signal every sparse-vs-dense decision
    in the tree keys on (this filter gate, the allreduce engine's
    ``choose_algo``)."""
    arr = np.asarray(arr)
    if arr.size == 0:
        return 0.0
    return float(np.count_nonzero(arr)) / arr.size


def break_even_density() -> float:
    """Density below which the LOSSLESS sparse tier beats RAW, as a
    wire-cost model: worst case the index stream is absolute int32
    (8 B/pair vs 4 B/element raw → 0.5); the common power-law case
    lands the u16-gap stream (6 B/pair → ~0.67). ``-wire_codec_density``
    (default 0.5, the conservative bound) is the canonical knob — the
    allreduce engine's sparse-tier switchover clamps its own cutoff to
    this value, so one flag moves every break-even decision."""
    return float(get_flag("wire_codec_density"))


def worth_encoding(arr: np.ndarray) -> bool:
    """Would the LOSSLESS codec actually shrink this host array? Only
    float32 payloads can land in a sub-RAW tier, and sparsity must pay
    for the index stream (``break_even_density``). The density pass
    spares dense traffic the full frame-copy round trip (encode +
    decode) that a RAW frame would cost for -24 bytes of 'savings'."""
    if arr.dtype != np.float32 or arr.nbytes < MIN_ENCODE_BYTES:
        return False
    return density_of(arr) < break_even_density()


def _compressible(blob) -> bool:
    """Message-filter gate: ``worth_encoding`` over a Blob (keys as
    uint8 views, option blobs, and table-level codec frames that are
    ALREADY compressed all sniff False by dtype)."""
    if blob.on_device:
        # Probing a device payload would transfer it host-side TWICE
        # (once here, once at serialize); let it pass through raw.
        return False
    dtype = getattr(blob.data, "dtype", None)
    if dtype is None or np.dtype(dtype) != np.float32:
        return False
    return worth_encoding(np.asarray(blob.data))


def encode_message(msg, *, lossy: bool = False) -> bool:
    """Encode a message's blobs in place (lossless tiers only by
    default) and mark header slot ``CODEC_SLOT``. Returns True when the
    message was encoded. Callers must have negotiated codec support with
    ``msg.dst`` first — an un-negotiated peer cannot decode the frame.
    Messages with no compressible blob pass through untouched."""
    from ..core.blob import Blob
    if not msg.data or msg.header[CODEC_SLOT]:
        return False
    if not any(_compressible(b) for b in msg.data):
        return False
    encoded: List = []
    for blob in msg.data:
        # Scatter-gather frames: header and payload streams stay
        # separate parts all the way to the vectored socket write
        # (tcp.serialize_views) — the old head+payload join copied
        # every encoded byte once more for nothing.
        parts, _ = encode_blob_views(np.asarray(blob.data), lossy=lossy)
        encoded.append(Blob.from_parts(parts))
    msg.data = encoded
    msg.header[CODEC_SLOT] = 1
    return True


def decode_message(msg) -> None:
    """Reverse ``encode_message`` (no-op unless the codec slot is set)."""
    from ..core.blob import Blob
    if not msg.header[CODEC_SLOT]:
        return
    msg.data = [Blob(decode_blob(b.data)) for b in msg.data]
    msg.header[CODEC_SLOT] = 0
