"""Local address enumeration for machine-file rank discovery.

TPU-native equivalent of the reference's ``net_util``
(ref: src/util/net_util.cpp, include/multiverso/util/net_util.h:10): the
ZMQ transport finds its own rank by matching the machine file's addresses
against the local interfaces (ref: zmq_net.h:25-61). Implemented with the
standard library only.
"""

from __future__ import annotations

import socket
from typing import Optional, Set, Tuple


def outbound_address() -> Optional[str]:
    """This host's outbound-interface address via the UDP-connect trick
    (the OS picks the interface without sending a packet); None when no
    route exists. Preferred over gethostbyname(hostname), which resolves
    to 127.0.1.1 on stock Debian hosts."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
    except OSError:
        return None


def local_addresses() -> Set[str]:
    """Names/IPs that resolve to this host (always includes loopback)."""
    addrs = {"127.0.0.1", "localhost", "0.0.0.0", "::1"}
    hostname = socket.gethostname()
    addrs.add(hostname)
    try:
        for info in socket.getaddrinfo(hostname, None):
            addrs.add(info[4][0])
    except OSError:
        pass
    out = outbound_address()
    if out is not None:
        addrs.add(out)
    return addrs


def free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (for tests and single-host launches)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


_next_listen_port = 21000 + (__import__("os").getpid() % 400) * 20


def free_listen_port() -> int:
    """A free port *below* the OS ephemeral range (Linux default
    32768-60999). Ports from ``free_port`` can be stolen between probe and
    listener bind by a peer's outbound connection, whose OS-assigned
    source port comes from that same ephemeral range; handing processes
    listen ports outside it removes the race. Probes the wildcard
    address (listeners bind wildcard)."""
    sock, port = reserve_listen_port()
    sock.close()
    return port


def reserve_listen_port() -> Tuple[socket.socket, int]:
    """A scan-range port returned WITH its bound socket, so the caller
    can hold the reservation across a slow rendezvous and close it right
    before the real listener binds — without the hold, two same-host
    processes scanning from the same pid-seeded slot can be handed one
    port. Binds the WILDCARD address: listeners bind wildcard too, and
    an addr-specific reservation would not block a sibling's loopback
    probe of the same port."""
    global _next_listen_port
    while True:
        port = _next_listen_port
        _next_listen_port += 1
        if _next_listen_port >= 32700:
            _next_listen_port = 21000
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            sock.bind(("", port))
        except OSError:
            sock.close()
            continue
        return sock, port
