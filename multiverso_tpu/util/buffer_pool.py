"""Size-classed receive-buffer pool with refcounted frame leases.

TPU-native equivalent of the reference's pooled ``Allocator``
(ref: include/multiverso/util/allocator.h:40-61, src/util/allocator.cpp):
the reference hands ref-counted memory chunks to Blobs from a free list
so the steady-state hot path never malloc/frees; here the transport's
receive path leases a pooled ``bytearray`` per inbound frame, fills it
with ``recv_into``, and the deserializer builds Blobs as ZERO-COPY numpy
views into the leased buffer. The lease (one per frame) rides every Blob
cut from the frame; when the last Blob dies, CPython refcounting fires
``FrameLease.__del__`` and the buffer returns to the pool — the
reference's ``MemoryBlock`` refcount collapsed onto the interpreter's.

Safety over recycling (docs/MEMORY.md "Lease / ownership rules"):

- a buffer is only re-pooled when NO buffer export is live on it. A
  caller that extracted ``blob.as_array(...)`` and outlived the Blob
  still holds a live export on the ``bytearray`` (numpy keeps the
  buffer protocol export for the array's lifetime), and CPython refuses
  to resize an exported ``bytearray`` — the pool probes with a
  1-byte append/trim and, on ``BufferError``, parks the buffer on a
  bounded pending list re-probed on later leases (or abandons it to GC
  past the cap). A recycled frame can therefore never alias live data.
- pool-backed views are READ-ONLY; mutation raises, and the few wire
  consumers that legitimately need to write call ``Blob.materialize()``
  (the copy-on-write contract).

Capacity (``-buffer_pool_mb``) bounds what the pool RETAINS, never what
it lends: ``lease`` always succeeds (allocating fresh on a miss), so the
pool can never deadlock the reader threads; buffers returned above the
cap are simply dropped to GC. Size classes are powers of two from 4 KB
(``-buffer_pool_classes`` of them); oversized frames get an unpooled
buffer with a no-op lease.
"""

from __future__ import annotations

import collections
import itertools
from typing import Optional

from .configure import define_int, get_flag
from .dashboard import count, samples
from .lock_witness import named_lock

define_int("buffer_pool_mb", 32,
           "receive-buffer pool retained-capacity cap (MB): the "
           "transport leases frame buffers here and recv_into fills "
           "them in place, so steady-state receive traffic stops "
           "allocating. Caps what the pool KEEPS between frames, never "
           "what it lends (lease always succeeds); 0 disables pooling "
           "(frames still deserialize as zero-copy views, into "
           "GC-owned buffers)")
define_int("buffer_pool_classes", 12,
           "number of power-of-two buffer size classes, starting at "
           "4 KB (12 classes = 4 KB .. 8 MB); frames above the largest "
           "class ride unpooled GC-owned buffers")

#: Smallest size class (bytes); class i holds buffers of _MIN_CLASS<<i.
_MIN_CLASS = 4096

#: Bound on buffers parked awaiting export release (a Blob's array
#: outlived its lease): past this they are abandoned to GC instead.
_PENDING_CAP = 64

_pool_seq = itertools.count()


class FrameLease:
    """One leased frame buffer. Every Blob cut from the frame holds a
    reference; the LAST holder's death returns the buffer to the pool
    (``__del__`` → ``release``). ``release`` is idempotent; a lease
    from a disabled/oversized allocation simply drops its buffer."""

    __slots__ = ("_pool", "_buf")

    def __init__(self, pool: Optional["BufferPool"], buf: bytearray):
        self._pool = pool
        self._buf = buf

    def view(self, nbytes: int) -> memoryview:
        """Writable view of the first ``nbytes`` (the recv_into target;
        size-classed buffers are usually larger than the frame)."""
        return memoryview(self._buf)[:nbytes]

    @property
    def nbytes(self) -> int:
        return len(self._buf) if self._buf is not None else 0

    def release(self) -> None:
        buf, self._buf = self._buf, None
        pool, self._pool = self._pool, None
        if buf is not None and pool is not None:
            pool._give_back(buf)

    def __del__(self) -> None:
        self.release()


class BufferPool:
    """Per-transport free list of receive buffers (see module doc)."""

    def __init__(self, capacity_mb: Optional[int] = None,
                 classes: Optional[int] = None):
        cap = int(get_flag("buffer_pool_mb")) if capacity_mb is None \
            else int(capacity_mb)
        ncls = int(get_flag("buffer_pool_classes")) if classes is None \
            else int(classes)
        self._enabled = cap > 0 and ncls > 0
        self._cap_bytes = max(cap, 0) << 20
        self._classes = [_MIN_CLASS << i for i in range(max(ncls, 0))]
        self._lock = named_lock(f"buffer_pool[{next(_pool_seq)}]")
        self._free = {size: collections.deque()  # guarded_by: _lock
                      for size in self._classes}
        self._resident = 0  # guarded_by: _lock
        self._pending: collections.deque = collections.deque()  # guarded_by: _lock

    @property
    def enabled(self) -> bool:
        return self._enabled

    @property
    def resident_bytes(self) -> int:
        """Bytes retained on the free lists right now."""
        with self._lock:
            return self._resident

    @property
    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def lease(self, nbytes: int) -> FrameLease:
        """A buffer of at least ``nbytes``. Never blocks, never fails:
        a pool miss (or a disabled pool, or an oversized frame)
        allocates fresh."""
        if not self._enabled or nbytes > self._classes[-1]:
            if self._enabled:
                count("POOL_MISS")
            # Unpooled: the lease owns nothing to return — plain GC.
            return FrameLease(None, bytearray(max(nbytes, 1)))
        size = self._class_for(nbytes)
        buf = None
        with self._lock:
            self._reclaim_pending_locked()
            dq = self._free[size]
            if dq:
                buf = dq.popleft()
                self._resident -= size
        if buf is None:
            count("POOL_MISS")
            buf = bytearray(size)
        else:
            count("POOL_HIT")
        return FrameLease(self, buf)

    def _class_for(self, nbytes: int) -> int:
        for size in self._classes:
            if nbytes <= size:
                return size
        return self._classes[-1]

    @staticmethod
    def _exports_released(buf: bytearray) -> bool:
        """True when no live buffer export pins ``buf`` (a resize probe:
        CPython refuses to resize an exported bytearray). The guard that
        makes recycling safe against blob-outlives-frame callers."""
        try:
            buf.append(0)
            del buf[-1]
            return True
        except BufferError:
            return False

    def _give_back(self, buf: bytearray) -> None:
        with self._lock:
            if not self._exports_released(buf):
                # A view into the frame is still alive somewhere
                # (e.g. a caller kept blob.as_array past the Blob):
                # recycling now would alias live data. Park it for a
                # later re-probe; past the cap, abandon to GC —
                # correctness never depends on reclaiming.
                if len(self._pending) < _PENDING_CAP:
                    self._pending.append(buf)
                return
            self._store_locked(buf)

    def _store_locked(self, buf: bytearray) -> None:
        size = len(buf)
        if size not in self._free \
                or self._resident + size > self._cap_bytes:
            return  # over capacity (or alien size): drop to GC
        self._free[size].append(buf)
        self._resident += size
        samples("POOL_RESIDENT_KB").add(self._resident / 1024.0)

    def _reclaim_pending_locked(self) -> None:
        for _ in range(len(self._pending)):
            buf = self._pending.popleft()
            if self._exports_released(buf):
                self._store_locked(buf)
            else:
                self._pending.append(buf)
