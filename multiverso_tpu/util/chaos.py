"""Flag-gated fault injection: frame drop/delay/reorder + scripted kills.

The elastic-resharding protocol (docs/SHARDING.md) claims every
migration either completes or rolls back to a consistent epoch under
message loss and process death. This module makes those claims
TESTABLE instead of aspirational: the transports call
:func:`filter_frames` on every outbound message (one flag probe and a
falsy check when disarmed — nothing else runs), and protocol
code marks named points with :func:`kill_point` so a test can SIGKILL
a process at an exact protocol instant.

``-chaos_frames`` spec (comma-separated ``key=value``):

    drop=0.3        drop matching frames with this probability
    delay_ms=25     sleep this long before sending a matching frame
    reorder=0.2     hold a matching frame and release it AFTER the
                    next matching frame to the same destination
    classes=shard   which frames match: ``shard`` (migration + shard
                    map control), ``ctrl`` (everything outside the
                    get/add data plane), ``data``, ``all``
    dst=2           additionally restrict to one destination rank
    for_s=5         faults only fire for this long after the FIRST
                    matching frame (a healing partition); 0 = forever
    seed=7          deterministic RNG

``-chaos_kill_on=point[:n]`` SIGKILLs this process the ``n``-th time
the named :func:`kill_point` is reached (default n=1). Points are
documented where they are placed (grep ``chaos.kill_point``).

Test/bench harness only — never enable in production. Everything here
is process-local and thread-safe via one small lock.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from . import log
from .configure import define_string, get_flag
from .dashboard import count as count_event

define_string("chaos_frames", "",
              "fault-injection spec for outbound frames "
              "(drop=/delay_ms=/reorder=/classes=/dst=/for_s=/seed=; "
              "empty = off). Test harness only — docs/SHARDING.md "
              "chaos matrix")
define_string("chaos_kill_on", "",
              "SIGKILL this process at a named protocol point "
              "('point' or 'point:n' for the n-th hit); empty = off. "
              "Test harness only")

#: Dashboard counters (util/dashboard.py METRIC_NAMES).
CHAOS_DROPPED = "CHAOS_DROPPED"
CHAOS_DELAYED = "CHAOS_DELAYED"


class _FrameChaos:
    def __init__(self, spec: str):
        import random
        kv = {}
        for part in spec.split(","):
            part = part.strip()
            if part and "=" in part:
                k, v = part.split("=", 1)
                kv[k.strip()] = v.strip()
        self.drop = float(kv.get("drop", 0.0))
        self.delay_ms = float(kv.get("delay_ms", 0.0))
        self.reorder = float(kv.get("reorder", 0.0))
        self.classes = kv.get("classes", "all")
        self.dst = int(kv.get("dst", -1))
        self.for_s = float(kv.get("for_s", 0.0))
        self.rng = random.Random(int(kv.get("seed", 1)))
        self.armed_at: Optional[float] = None
        self.lock = threading.Lock()
        #: per-destination 1-slot hold for reorder
        self.held: Dict[int, object] = {}

    def matches(self, msg) -> bool:
        if self.dst >= 0 and msg.dst != self.dst:
            return False
        t = int(msg.type_int)
        if self.classes == "all":
            return True
        is_shard = t in _SHARD_TYPES
        if self.classes == "shard":
            return is_shard
        is_data = -32 < t < 32 and t != 0 and not is_shard
        if self.classes == "data":
            return is_data
        if self.classes == "ctrl":
            return not is_data
        return True

    def window_open(self) -> bool:
        if self.for_s <= 0:
            return True
        if self.armed_at is None:
            self.armed_at = time.monotonic()
        return time.monotonic() - self.armed_at <= self.for_s


_SHARD_TYPES: set = set()


def _init_shard_types() -> None:
    # Lazy: core.message imports nothing from util, so this is safe,
    # but keep the import out of module load (chaos is imported by the
    # transports, which core code imports early).
    from ..core.message import MsgType
    _SHARD_TYPES.update(int(t) for t in (
        MsgType.Request_ShardData, MsgType.Request_ShardAck,
        MsgType.Request_ShardBegin, MsgType.Request_ShardAbort,
        MsgType.Request_FwdGet, MsgType.Request_FwdAdd,
        MsgType.Control_Shard_Done, MsgType.Control_Shard_Map,
        MsgType.Control_Shard_Request))


_frames: Optional[_FrameChaos] = None
_frames_spec: Optional[str] = None
_kill_lock = threading.Lock()
_kill_counts: Dict[str, int] = {}


def _frame_state() -> Optional[_FrameChaos]:
    """The active frame-fault config, rebuilt when the flag changes
    (tests flip it between cluster runs). The disarmed common path is
    one flag probe and a falsy check — no str()/parse work per
    frame."""
    global _frames, _frames_spec
    spec = get_flag("chaos_frames", "")
    if not spec:
        if _frames is not None:
            _frames, _frames_spec = None, ""
        return None
    spec = str(spec)
    if spec != _frames_spec:
        _frames_spec = spec
        _init_shard_types()
        _frames = _FrameChaos(spec)
        log.info("chaos: frame faults armed (%s)", spec)
    return _frames


def filter_frames(msg) -> Optional[List]:
    """Transport hook: returns the list of messages to actually send
    now (possibly empty — dropped/held; possibly two — a held frame
    released ahead of schedule), or None meaning "no chaos, send as
    is" (the zero-cost common path)."""
    state = _frame_state()
    if state is None:
        return None
    if not state.matches(msg) or not state.window_open():
        return None
    out: List = []
    with state.lock:
        if state.drop > 0 and state.rng.random() < state.drop:
            count_event(CHAOS_DROPPED)
            log.debug("chaos: dropped %r", msg)
            return out  # dropped (plus anything held stays held)
        if state.reorder > 0:
            held = state.held.pop(msg.dst, None)
            if held is not None:
                out.append(msg)      # the newer frame jumps the queue
                out.append(held)
                return out
            if state.rng.random() < state.reorder:
                state.held[msg.dst] = msg
                return out           # held for the next matching frame
        delay = state.delay_ms
    if delay > 0:
        count_event(CHAOS_DELAYED)
        time.sleep(delay / 1e3)
    out.append(msg)
    return out


def kill_point(name: str) -> None:
    """SIGKILL this process if ``-chaos_kill_on`` names this point
    (optionally its n-th occurrence). Placed at protocol instants the
    chaos matrix needs deterministic deaths at (docs/SHARDING.md)."""
    spec = str(get_flag("chaos_kill_on", ""))
    if not spec:
        return
    target, _, nth = spec.partition(":")
    if target != name:
        return
    want = int(nth) if nth else 1
    with _kill_lock:
        _kill_counts[name] = _kill_counts.get(name, 0) + 1
        hit = _kill_counts[name]
    if hit < want:
        return
    import os
    import signal
    log.error("chaos: kill point %r reached (hit %d) — SIGKILL",
              name, hit)
    import sys
    sys.stderr.flush()
    os.kill(os.getpid(), signal.SIGKILL)
