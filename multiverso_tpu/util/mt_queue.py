"""Blocking multi-producer/multi-consumer queue with explicit exit.

TPU-native equivalent of the reference's ``MtQueue``
(ref: include/multiverso/util/mt_queue.h:19-147). ``pop`` blocks until an
item arrives or ``exit()`` is called; after exit, ``pop``/``try_pop`` return
``None``/False immediately. Built on a deque + condition variable, like the
reference's mutex+condvar design.
"""

from __future__ import annotations

import collections
import itertools
from typing import (Callable, Deque, Generic, List, Optional, Tuple,
                    TypeVar)

from .configure import get_flag
from .dashboard import samples
from .lock_witness import monotonic, named_condition, named_lock

T = TypeVar("T")

_serial = itertools.count()


def depth_sampling_enabled() -> bool:
    """Whether actor mailboxes should pay the per-push depth SAMPLE
    (reservoir lock + append per message on hot paths): only when
    something actually consumes the samples — the serving tier's
    pressure surface (-serving_port) or the metrics exporter
    (-metrics_interval_s). The high watermark alone is one compare and
    stays tracked unconditionally. Read at actor construction, after
    flag parsing (the -sparse_compress precedent)."""
    return (int(get_flag("serving_port", 0)) > 0
            or float(get_flag("metrics_interval_s", 0.0)) > 0)


class MtQueue(Generic[T]):
    def __init__(self, name: str = "") -> None:
        name = name or f"mt_queue[{next(_serial)}]"
        self._mutex = named_lock(name)
        self._cond = named_condition(f"{name}.cond", self._mutex)
        # _cond shares _mutex, so holding either satisfies the guard
        # (the mvlint guarded-by alias group).
        self._buffer: Deque[T] = collections.deque()  # guarded_by: _mutex
        self._exit = False  # guarded_by: _mutex
        # Depth observability (docs/SERVING.md admission control +
        # bench mailbox-pressure reporting): the high watermark is
        # always tracked (one compare per push); per-push depth
        # SAMPLES (p50/p99 via util/dashboard.py Samples) only when a
        # metric name was opted in via track_depth — the reservoir's
        # lock + append per push is real cost on a hot mailbox.
        self._depth_high = 0  # guarded_by: _mutex
        # Set once by track_depth before any producer thread runs;
        # read lock-free per push on purpose.
        self._depth_metric: Optional[str] = None

    def track_depth(self, metric_name: str) -> None:
        """Record every post-push depth into the named Dashboard
        ``Samples`` reservoir (``MAILBOX_DEPTH[*]`` family). The server
        and worker actors opt their mailboxes in: admission-control
        decisions and the serving bench both read mailbox pressure."""
        self._depth_metric = metric_name

    def push(self, item: T) -> None:
        with self._cond:
            self._buffer.append(item)
            depth = len(self._buffer)
            if depth > self._depth_high:
                self._depth_high = depth
            self._cond.notify()
        if self._depth_metric is not None:
            # Outside the queue lock: the reservoir has its own, and a
            # sampler must never extend this queue's critical section.
            # Re-resolved per push (not cached) so a bench-phase
            # reset_samples() cannot orphan the writer (the
            # dashboard.monitor re-resolve precedent).
            samples(self._depth_metric).add(depth)

    @property
    def depth_high_watermark(self) -> int:
        """Deepest the queue has ever been (monotonic; cheap enough to
        track unconditionally)."""
        with self._mutex:
            return self._depth_high

    def reset_depth_watermark(self) -> None:
        """Re-anchor the watermark at the current depth (bench windows
        measure per-phase pressure, not lifetime)."""
        with self._mutex:
            self._depth_high = len(self._buffer)

    def pop(self, timeout: Optional[float] = None) -> Optional[T]:
        """Block until an item is available; None once exited (or timeout)."""
        deadline = None if timeout is None else monotonic() + timeout
        with self._cond:
            while not self._buffer and not self._exit:
                remaining = None if deadline is None \
                    else deadline - monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                if not self._cond.wait(timeout=remaining):
                    return None
            if self._buffer:
                return self._buffer.popleft()
            return None

    def pop_batch(self, max_items: int = 64,
                  max_bytes: Optional[int] = None,
                  size_of: Optional[Callable[[T], int]] = None,
                  timeout: Optional[float] = None) -> List[T]:
        """Bounded atomic drain (server request fusion,
        docs/SERVER_ENGINE.md): block like ``pop`` for the FIRST item,
        then take whatever else is already queued — no further waiting
        — up to ``max_items`` and, when ``size_of`` is given, up to
        ``max_bytes`` of summed item size. The first item is always
        taken regardless of its size (the one-message fallback: an
        oversized request must still make progress), so the byte cap
        bounds the batch TAIL, not a single message. Returns ``[]``
        only on exit/timeout.

        Depth semantics match ``pop``: the high watermark is a
        push-side property and is untouched here, and ``track_depth``
        sampling stays push-only — a drain never writes the reservoir.
        """
        max_items = max(int(max_items), 1)
        deadline = None if timeout is None else monotonic() + timeout
        with self._cond:
            while not self._buffer and not self._exit:
                remaining = None if deadline is None \
                    else deadline - monotonic()
                if remaining is not None and remaining <= 0:
                    return []
                if not self._cond.wait(timeout=remaining):
                    return []
            if not self._buffer:
                return []
            batch: List[T] = [self._buffer.popleft()]
            budget = None
            if max_bytes is not None and size_of is not None:
                budget = max(int(max_bytes), 0) - size_of(batch[0])
            while self._buffer and len(batch) < max_items:
                if budget is not None:
                    nxt = size_of(self._buffer[0])
                    if budget - nxt < 0:
                        break
                    budget -= nxt
                batch.append(self._buffer.popleft())
            return batch

    def try_pop(self) -> Tuple[bool, Optional[T]]:
        with self._mutex:
            if self._buffer:
                return True, self._buffer.popleft()
            return False, None

    def front(self) -> Optional[T]:
        """Block until an item is available and peek it without removing."""
        with self._cond:
            while not self._buffer and not self._exit:
                self._cond.wait()
            return self._buffer[0] if self._buffer else None

    def empty(self) -> bool:
        with self._mutex:
            return not self._buffer

    def size(self) -> int:
        with self._mutex:
            return len(self._buffer)

    def exit(self) -> None:
        with self._cond:
            self._exit = True
            self._cond.notify_all()

    @property
    def alive(self) -> bool:
        with self._mutex:
            return not self._exit
