"""Blocking multi-producer/multi-consumer queue with explicit exit.

TPU-native equivalent of the reference's ``MtQueue``
(ref: include/multiverso/util/mt_queue.h:19-147). ``pop`` blocks until an
item arrives or ``exit()`` is called; after exit, ``pop``/``try_pop`` return
``None``/False immediately. Built on a deque + condition variable, like the
reference's mutex+condvar design.
"""

from __future__ import annotations

import collections
import itertools
import time
from typing import Deque, Generic, Optional, Tuple, TypeVar

from .lock_witness import named_condition, named_lock

T = TypeVar("T")

_serial = itertools.count()


class MtQueue(Generic[T]):
    def __init__(self, name: str = "") -> None:
        self._buffer: Deque[T] = collections.deque()
        name = name or f"mt_queue[{next(_serial)}]"
        self._mutex = named_lock(name)
        self._cond = named_condition(f"{name}.cond", self._mutex)
        self._exit = False

    def push(self, item: T) -> None:
        with self._cond:
            self._buffer.append(item)
            self._cond.notify()

    def pop(self, timeout: Optional[float] = None) -> Optional[T]:
        """Block until an item is available; None once exited (or timeout)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while not self._buffer and not self._exit:
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                if not self._cond.wait(timeout=remaining):
                    return None
            if self._buffer:
                return self._buffer.popleft()
            return None

    def try_pop(self) -> Tuple[bool, Optional[T]]:
        with self._mutex:
            if self._buffer:
                return True, self._buffer.popleft()
            return False, None

    def front(self) -> Optional[T]:
        """Block until an item is available and peek it without removing."""
        with self._cond:
            while not self._buffer and not self._exit:
                self._cond.wait()
            return self._buffer[0] if self._buffer else None

    def empty(self) -> bool:
        with self._mutex:
            return not self._buffer

    def size(self) -> int:
        with self._mutex:
            return len(self._buffer)

    def exit(self) -> None:
        with self._cond:
            self._exit = True
            self._cond.notify_all()

    @property
    def alive(self) -> bool:
        with self._mutex:
            return not self._exit
