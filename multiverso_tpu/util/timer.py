"""Millisecond stopwatch (ref: include/multiverso/util/timer.h:9-24)."""

from __future__ import annotations

import time


class Timer:
    def __init__(self) -> None:
        self._start = time.perf_counter()

    def start(self) -> None:
        self._start = time.perf_counter()

    def elapse(self) -> float:
        """Elapsed milliseconds since construction or last start()."""
        return (time.perf_counter() - self._start) * 1e3
