"""Typed flag/configuration registry.

TPU-native re-design of the reference's gflags-like system
(ref: include/multiverso/util/configure.h:11-114, src/util/configure.cpp:9-54).
Semantics preserved:

- flags are registered with a name, default value and description;
- ``parse_cmd_flags(argv)`` consumes ``-key=value`` entries (leaving every
  other entry in place, compacting the list) and returns the remaining argv;
- values are readable/writable at any time (``get_flag`` / ``set_flag``,
  the reference's ``MV_CONFIG_<name>`` / ``MV_SetFlag``).

Unlike the reference there is one registry keyed by name (the reference keeps
one static registry per C++ type); type is enforced by the registered default.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List


class _Flag:
    __slots__ = ("name", "value", "default", "type", "description")

    def __init__(self, name: str, default: Any, description: str = ""):
        self.name = name
        self.default = default
        self.value = default
        self.type = type(default)
        self.description = description


class FlagRegister:
    """Process-wide flag registry (singleton)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}

    @classmethod
    def get(cls) -> "FlagRegister":
        with cls._lock:
            if cls._instance is None:
                cls._instance = FlagRegister()
            return cls._instance

    def define(self, name: str, default: Any, description: str = "") -> None:
        if name in self._flags:
            # Re-definition keeps the current value (module reloads in tests).
            return
        self._flags[name] = _Flag(name, default, description)

    def has(self, name: str) -> bool:
        return name in self._flags

    def get_value(self, name: str) -> Any:
        if name not in self._flags:
            raise KeyError(f"unknown flag: {name}")
        return self._flags[name].value

    def set_value(self, name: str, value: Any) -> None:
        if name not in self._flags:
            # Mirrors reference behavior: SetCMDFlag on an unregistered flag
            # registers it implicitly (string-typed if value is a string).
            self._flags[name] = _Flag(name, value)
            return
        flag = self._flags[name]
        try:
            flag.value = _coerce(value, flag.type)
        except (TypeError, ValueError) as exc:
            raise ValueError(
                f"bad value for flag -{name} "
                f"(expected {flag.type.__name__}): {value!r}") from exc

    def reset(self) -> None:
        for flag in self._flags.values():
            flag.value = flag.default

    def all_flags(self) -> Dict[str, Any]:
        return {k: f.value for k, f in self._flags.items()}


def _coerce(value: Any, typ: type) -> Any:
    if isinstance(value, typ) and not (typ is int and isinstance(value, bool)):
        return value
    if typ is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes", "on")
        return bool(value)
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return str(value)


def define_int(name: str, default: int, description: str = "") -> None:
    FlagRegister.get().define(name, int(default), description)


def define_bool(name: str, default: bool, description: str = "") -> None:
    FlagRegister.get().define(name, bool(default), description)


def define_string(name: str, default: str, description: str = "") -> None:
    FlagRegister.get().define(name, str(default), description)


def define_double(name: str, default: float, description: str = "") -> None:
    FlagRegister.get().define(name, float(default), description)


def get_flag(name: str, default: Any = None) -> Any:
    reg = FlagRegister.get()
    if not reg.has(name):
        if default is not None:
            return default
        raise KeyError(f"unknown flag: {name}")
    return reg.get_value(name)


def set_flag(name: str, value: Any) -> None:
    FlagRegister.get().set_value(name, value)


def reset_flags() -> None:
    FlagRegister.get().reset()


def parse_cmd_flags(argv: List[str]) -> List[str]:
    """Consume ``-key=value`` entries matching registered flags.

    Returns the compacted argv with consumed entries removed — the same
    contract as the reference's ``ParseCMDFlags`` (configure.cpp:19-53):
    only entries that match a registered flag are consumed; everything else
    (including unknown ``-key=value`` pairs) is left for downstream parsers.
    """
    if argv is None:
        return []
    remaining: List[str] = []
    reg = FlagRegister.get()
    for arg in argv:
        if isinstance(arg, bytes):
            arg = arg.decode()
        if arg.startswith("-") and "=" in arg:
            key, _, value = arg.lstrip("-").partition("=")
            if reg.has(key):
                reg.set_value(key, value)
                continue
        remaining.append(arg)
    return remaining
