"""Typed flag/configuration registry.

TPU-native re-design of the reference's gflags-like system
(ref: include/multiverso/util/configure.h:11-114, src/util/configure.cpp:9-54).
Semantics preserved:

- flags are registered with a name, default value and description;
- ``parse_cmd_flags(argv)`` consumes ``-key=value`` entries (leaving every
  other entry in place, compacting the list) and returns the remaining argv;
- values are readable/writable at any time (``get_flag`` / ``set_flag``,
  the reference's ``MV_CONFIG_<name>`` / ``MV_SetFlag``).

Unlike the reference there is one registry keyed by name (the reference keeps
one static registry per C++ type); type is enforced by the registered default.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Callable, Dict, List, Optional

#: CENTRAL FLAG REGISTRY — the one canonical (default, description) per
#: flag name, for the whole tree. ``define_*`` calls scattered across
#: modules keep working (a flag only becomes *parseable* once its module
#: imports), but every name and default they register must match this
#: table: ``tools/mvlint``'s flag-lint pass reads the literal below and
#: fails CI on any ``get_flag``/``set_flag``/``define_*`` site naming an
#: unlisted flag or registering a drifted default. Keep the literal
#: plain (no computed values) — the lint parses it without importing.
CANONICAL_FLAGS: Dict[str, Any] = {
    # -- runtime / transport (runtime/tcp.py, runtime/zoo.py) --
    "machine_file": "",
    "port": 55555,
    "rank": -1,
    "send_queue_mb": 32,
    "net_pace_mbps": 0.0,
    # -- zero-copy wire path (runtime/tcp.py, util/buffer_pool.py;
    #    docs/MEMORY.md) --
    "zero_copy": True,
    "buffer_pool_mb": 32,
    "buffer_pool_classes": 12,
    # -- shared-memory transport for co-located ranks (runtime/shm.py;
    #    docs/MEMORY.md "Below the socket") --
    "shm": True,
    "shm_ring_slots": 16,
    "shm_slot_kb": 512,
    "ps_role": "default",
    "ma": False,
    "sync": False,
    # -- server / worker actors --
    "backup_worker_ratio": 0.0,
    "server_fuse_max": 16,
    "server_fuse_bytes": 16777216,
    "coalesce_adds": True,
    "coalesce_max_msgs": 64,
    "coalesce_max_kb": 4096,
    # -- sharding / scale-out (runtime/replica.py; docs/SHARDING.md) --
    "replica_hot_rows": 0,
    "replica_report_gets": 256,
    "replica_min_gets": 8,
    "replica_sync_rows": 8192,
    "replica_sync_every": 8,
    # -- fault tolerance (runtime/snapshot.py, runtime/controller.py,
    #    runtime/zoo.py, runtime/worker.py, runtime/tcp.py) --
    "snapshot_interval_s": 0.0,
    "snapshot_dir": "",
    "rejoin": False,
    "rpc_retry_max": 0,
    "rpc_backoff_ms": 50.0,
    "rpc_timeout_s": 0.0,
    "heartbeat_interval_s": 0.0,
    "heartbeat_timeout_s": 5.0,
    "rejoin_grace_s": 30.0,
    "connect_timeout_s": 30.0,
    # -- elastic resharding + chaos harness (runtime/shard_map.py,
    #    util/chaos.py; docs/SHARDING.md) --
    "reshard_chunk_rows": 4096,
    "reshard_auto": False,
    "reshard_skew": 2.0,
    "shard_initial_servers": 0,
    "chaos_frames": "",
    "chaos_kill_on": "",
    # -- allreduce engine (runtime/allreduce_engine.py) --
    "allreduce_algo": "auto",
    "allreduce_chunk_kb": 512,
    "allreduce_window": 4,
    "allreduce_ring_kb": 256,
    "allreduce_timeout_s": 120.0,
    "allreduce_stash_cap": 4096,
    "allreduce_lossy": False,
    "allreduce_sparse_density": 0.25,
    "allreduce_sparse_idx_budget": 8388608,
    # -- wire codec (util/wire_codec.py) --
    "wire_codec": True,
    "wire_codec_lossy": False,
    "wire_codec_density": 0.5,
    # -- tables (tables/matrix_table.py, tables/client_cache.py) --
    "sparse_compress": True,
    "verify_device_ids": False,
    "one_bit_push": False,
    "max_get_staleness": 0,
    "client_cache_rows": 65536,
    # -- updater --
    "updater_type": "default",
    # -- diagnostics (util/lock_witness.py,
    #    runtime/thread_roles.py) --
    "debug_locks": False,
    "role_block_budget_ms": 250.0,
    # -- observability (util/tracing.py, runtime/metrics.py,
    #    io/metrics_http.py; docs/OBSERVABILITY.md) --
    "trace_sample_rate": 0.0,
    "trace_slow_ms": 0.0,
    "trace_buffer": 4096,
    "metrics_interval_s": 0.0,
    "metrics_port": 0,
    # -- closed-loop self-tuning (runtime/autotune.py;
    #    docs/AUTOTUNE.md) --
    "autotune_interval_s": 0.0,
    "autotune_slo_p99_ms": 50.0,
    "autotune_pin": "",
    # -- online serving tier (serving/frontend.py,
    #    serving/admission.py; docs/SERVING.md) --
    "serving_port": 0,
    "serving_max_rows": 4096,
    "serving_max_inflight": 64,
    "serving_shed_depth": 256,
    "serving_retry_after_s": 0.05,
    "serving_drain_s": 5.0,
    "serving_scatter": True,
    "serving_batch_window_ms": 2.0,
    "serving_batch_max_rows": 1024,
    "serving_hot_rows": 4096,
    "serving_fleet_interval_s": 2.0,
    "ann_nlist": 0,
    "ann_nprobe": 8,
    # -- wordembedding model (models/wordembedding/) --
    "train_file": "",
    "output_file": "vectors.txt",
    "vocab_file": "",
    "save_vocab_file": "",
    "sw_file": "",
    "stopwords": "",
    "size": 100,
    "window": 5,
    "negative": 5,
    "epoch": 1,
    "min_count": 5,
    "sample": 1e-3,
    "init_learning_rate": 0.025,
    "cbow": False,
    "hs": False,
    "use_ps": False,
    "batch_size": 4096,
    "neg_block": 1,
    "per_pair": False,
    "is_pipeline": True,
    "device_pipeline": True,
}

#: LIVE-RETUNABLE FLAG REGISTRY — the subset of ``CANONICAL_FLAGS`` the
#: closed-loop autotune layer (runtime/autotune.py, docs/AUTOTUNE.md)
#: may change on a RUNNING cluster via an epoch-stamped
#: ``Control_Config`` broadcast. Every entry must (a) name a canonical
#: flag and (b) have at least one ``register_tunable_hook(...)`` call
#: site somewhere in the tree, so hot paths that cached the value at
#: construction (admission watermarks, cache bounds/capacities, batch
#: caps) actually pick the change up — ``tools/mvlint``'s tunable-lint
#: pass enforces both, parsing this literal without importing. A flag
#: NOT listed here is rejected at broadcast time (``apply_config``
#: raises), so a typo'd or genuinely-static knob can never be mutated
#: mid-run. Keep the literal plain (no computed values); the value is
#: a one-line note on how the new value lands.
TUNABLE_FLAGS: Dict[str, str] = {
    "max_get_staleness": "RowCache hook rebinds the live bound "
                         "(0 deactivates and clears)",
    "client_cache_rows": "RowCache hook resizes; eviction on next "
                         "store",
    "coalesce_max_msgs": "worker-actor hook re-caps staged-batch "
                         "message flushes",
    "coalesce_max_kb": "worker-actor hook re-caps staged-batch byte "
                       "flushes",
    "serving_max_inflight": "AdmissionController hook re-knobs the "
                            "per-endpoint in-flight cap",
    "serving_shed_depth": "AdmissionController hook re-knobs the "
                          "mailbox-depth shed watermark",
    "serving_batch_window_ms": "BatchedTableReader hook rewrites the "
                               "live batch window",
    "serving_batch_max_rows": "BatchedTableReader hook rewrites the "
                              "live batch row cap",
    "serving_hot_rows": "HotRowCache hook resizes the rendered-"
                        "response capacity",
    "replica_hot_rows": "controller reads live per report; reporter "
                        "hook re-sizes its report window",
    "allreduce_chunk_kb": "read per collective call; hook logs the "
                          "handoff",
    "wire_codec_density": "read per encoded frame; hook logs the "
                          "handoff",
}


#: Registered apply hooks per tunable flag. Bound methods are held as
#: ``weakref.WeakMethod`` so a dead owner (a table dropped between
#: bench phases) silently unregisters instead of leaking or firing on
#: a corpse; plain functions are held strongly. Guarded by
#: ``_tunable_lock`` together with the applied-epoch watermark.
_tunable_hooks: Dict[str, List] = {}
_tunable_lock = threading.Lock()
_applied_config_epoch = 0


def register_tunable_hook(name: str,
                          hook: Callable[[Any], None]) -> None:
    """Declare how a live config change to tunable flag ``name`` lands
    in a hot path that cached the value (docs/AUTOTUNE.md). The hook is
    called with the freshly-coerced value after every ``apply_tunable``
    / ``apply_config`` touching the flag; it must be idempotent and
    cheap (it runs on the communicator's receive thread). Raises
    ``KeyError`` for a flag not in ``TUNABLE_FLAGS`` — declaring a hook
    for a non-tunable flag is a registration bug, not a no-op."""
    if name not in TUNABLE_FLAGS:
        raise KeyError(
            f"register_tunable_hook({name!r}): not in TUNABLE_FLAGS "
            f"(util/configure.py) — only declared-tunable flags take "
            f"live apply hooks")
    ref: Any
    try:
        # Bound methods are held weakly so a dead owner (a table
        # dropped between bench phases) unregisters itself; plain
        # functions and builtin bound methods hold strongly.
        ref = weakref.WeakMethod(hook)
    except TypeError:
        ref = hook
    with _tunable_lock:
        # Prune dead weak refs HERE too, not only on fire: with
        # autotune off no broadcast ever fires the hooks, and a
        # process that repeatedly constructs/drops tables and
        # frontends would otherwise grow the list without bound.
        hooks = _tunable_hooks.setdefault(name, [])
        hooks[:] = [r for r in hooks
                    if not (isinstance(r, weakref.WeakMethod)
                            and r() is None)]
        hooks.append(ref)


def _fire_tunable_hooks(name: str, value: Any) -> None:
    with _tunable_lock:
        refs = list(_tunable_hooks.get(name, ()))
    live = []
    for ref in refs:
        fn = ref() if isinstance(ref, weakref.WeakMethod) else ref
        if fn is None:
            continue  # owner collected: pruned below
        live.append(ref)
        try:
            fn(value)
        except Exception as exc:  # noqa: BLE001 - one mis-behaving
            # hook must not stop the rest of the config from landing
            from . import log
            log.error("tunable hook for -%s failed on value %r: %s",
                      name, value, exc)
    if len(live) != len(refs):
        with _tunable_lock:
            current = _tunable_hooks.get(name)
            if current is not None:
                _tunable_hooks[name] = [
                    r for r in current
                    if not (isinstance(r, weakref.WeakMethod)
                            and r() is None)]


def is_tunable(name: str) -> bool:
    return name in TUNABLE_FLAGS


def apply_tunable(name: str, value: Any) -> Any:
    """``set_flag`` + fire the flag's apply hooks with the coerced
    value. The ONLY sanctioned way to change a tunable flag on a live
    cluster — a bare ``set_flag`` would leave construction-time caches
    (admission watermarks, batch caps, cache bounds) on the old value.
    Raises ``KeyError`` for non-tunable flags."""
    if name not in TUNABLE_FLAGS:
        raise KeyError(
            f"apply_tunable({name!r}): not in TUNABLE_FLAGS "
            f"(util/configure.py) — non-tunable flags are rejected at "
            f"broadcast time")
    set_flag(name, value)
    coerced = get_flag(name)
    _fire_tunable_hooks(name, coerced)
    return coerced


def _coerce_tunable(name: str, value: Any) -> Any:
    """Coerce ``value`` to the flag's registered (or canonical) type,
    raising ``ValueError`` on a bad value — the pre-validation step
    that keeps ``apply_config`` atomic."""
    reg = FlagRegister.get()
    typ = reg._flags[name].type if reg.has(name) \
        else type(CANONICAL_FLAGS[name])
    try:
        return _coerce(value, typ)
    except (TypeError, ValueError) as exc:
        raise ValueError(
            f"bad value for tunable flag -{name} "
            f"(expected {typ.__name__}): {value!r}") from exc


def apply_config(epoch: int, flags: Dict[str, Any]) -> bool:
    """Apply one epoch-stamped ``Control_Config`` broadcast
    (runtime/autotune.py). Returns False — applying NOTHING — when
    ``epoch`` does not advance the process's applied-config watermark
    (a replayed or reordered broadcast must not roll knobs backward).
    Raises — before touching ANY flag or the watermark — ``KeyError``
    if any flag is non-tunable and ``ValueError`` if any value fails
    type coercion: a broadcast naming an undeclared flag or carrying a
    garbage value is a controller bug and the whole update is refused,
    never half-applied (and the consumed epoch never burned on a
    refusal, so a corrected re-broadcast at the same epoch lands)."""
    global _applied_config_epoch
    bad = sorted(n for n in flags if n not in TUNABLE_FLAGS)
    if bad:
        raise KeyError(
            f"config broadcast (epoch {epoch}) names non-tunable "
            f"flag(s) {bad} — not in TUNABLE_FLAGS (util/configure.py)")
    # Pre-coerce EVERYTHING before the watermark moves or any flag is
    # set: a mid-loop coercion failure would otherwise leave the
    # config half-applied with the epoch permanently consumed.
    coerced = {name: _coerce_tunable(name, flags[name])
               for name in sorted(flags)}
    with _tunable_lock:
        if int(epoch) <= _applied_config_epoch:
            return False
        _applied_config_epoch = int(epoch)
    for name, value in coerced.items():
        set_flag(name, value)
        _fire_tunable_hooks(name, value)
    return True


def applied_config_epoch() -> int:
    """The last config-broadcast epoch this process applied (0 =
    none yet)."""
    with _tunable_lock:
        return _applied_config_epoch


class _Flag:
    __slots__ = ("name", "value", "default", "type", "description")

    def __init__(self, name: str, default: Any, description: str = ""):
        self.name = name
        self.default = default
        self.value = default
        self.type = type(default)
        self.description = description


class FlagRegister:
    """Process-wide flag registry (singleton)."""

    _instance = None
    _lock = threading.Lock()

    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}

    @classmethod
    def get(cls) -> "FlagRegister":
        with cls._lock:
            if cls._instance is None:
                cls._instance = FlagRegister()
            return cls._instance

    def define(self, name: str, default: Any, description: str = "") -> None:
        if name in CANONICAL_FLAGS and (
                default != CANONICAL_FLAGS[name]
                # Type drift changes coercion semantics even when ==
                # holds (55555.0 == 55555 but -port would parse float).
                or type(default) is not type(CANONICAL_FLAGS[name])):
            # Default drift: two call sites disagree about a flag's
            # default. mvlint fails CI on this statically; warn loudly
            # at runtime too (dynamic define paths bypass the lint).
            from . import log
            log.error("flag -%s registered with default %r but the "
                      "canonical default (util/configure.py "
                      "CANONICAL_FLAGS) is %r — fix the call site or "
                      "the registry", name, default,
                      CANONICAL_FLAGS[name])
        if name in self._flags:
            # Re-definition keeps the current value (module reloads in tests).
            return
        self._flags[name] = _Flag(name, default, description)

    def has(self, name: str) -> bool:
        return name in self._flags

    def get_value(self, name: str) -> Any:
        if name not in self._flags:
            raise KeyError(f"unknown flag: {name}")
        return self._flags[name].value

    def set_value(self, name: str, value: Any) -> None:
        if name not in self._flags:
            # Mirrors reference behavior: SetCMDFlag on an unregistered flag
            # registers it implicitly (string-typed if value is a string).
            self._flags[name] = _Flag(name, value)
            return
        flag = self._flags[name]
        try:
            flag.value = _coerce(value, flag.type)
        except (TypeError, ValueError) as exc:
            raise ValueError(
                f"bad value for flag -{name} "
                f"(expected {flag.type.__name__}): {value!r}") from exc

    def reset(self) -> None:
        for flag in self._flags.values():
            flag.value = flag.default

    def all_flags(self) -> Dict[str, Any]:
        return {k: f.value for k, f in self._flags.items()}


def _coerce(value: Any, typ: type) -> Any:
    if isinstance(value, typ) and not (typ is int and isinstance(value, bool)):
        return value
    if typ is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("true", "1", "yes", "on")
        return bool(value)
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    return str(value)


def define_int(name: str, default: int, description: str = "") -> None:
    FlagRegister.get().define(name, int(default), description)


def define_bool(name: str, default: bool, description: str = "") -> None:
    FlagRegister.get().define(name, bool(default), description)


def define_string(name: str, default: str, description: str = "") -> None:
    FlagRegister.get().define(name, str(default), description)


def define_double(name: str, default: float, description: str = "") -> None:
    FlagRegister.get().define(name, float(default), description)


#: Unknown flag names already warned about (one loud line per process —
#: a typo'd flag read on a hot path must not flood the log).
_warned_unknown: set = set()


def _warn_unknown_flag(name: str) -> None:
    """A ``get_flag`` name that is neither registered nor canonical is
    almost always a typo — and the old behavior (silently return the
    caller's default) made such typos invisible: the flag the operator
    set on the command line simply never took effect. Warn ONCE per
    process per name, with the nearest registered flag (difflib) so the
    fix is one copy-paste away."""
    if name in _warned_unknown:
        return
    _warned_unknown.add(name)
    import difflib
    candidates = set(CANONICAL_FLAGS) | set(FlagRegister.get()._flags)
    close = difflib.get_close_matches(name, sorted(candidates), n=1)
    hint = f"; did you mean -{close[0]}?" if close else ""
    from . import log
    log.error("get_flag(%r): not a registered or canonical flag — "
              "returning the caller's default, so -%s=... on the "
              "command line would be IGNORED%s", name, name, hint)


def get_flag(name: str, default: Any = None) -> Any:
    reg = FlagRegister.get()
    if not reg.has(name):
        # A canonical flag whose defining module simply is not imported
        # yet reads as its caller default silently (legitimate late
        # binding); anything else is a likely typo and warns loudly.
        if name not in CANONICAL_FLAGS:
            _warn_unknown_flag(name)
        if default is not None:
            return default
        raise KeyError(f"unknown flag: {name}")
    return reg.get_value(name)


def set_flag(name: str, value: Any) -> None:
    FlagRegister.get().set_value(name, value)


def reset_flags() -> None:
    FlagRegister.get().reset()


def parse_cmd_flags(argv: List[str]) -> List[str]:
    """Consume ``-key=value`` entries matching registered flags.

    Returns the compacted argv with consumed entries removed — the same
    contract as the reference's ``ParseCMDFlags`` (configure.cpp:19-53):
    only entries that match a registered flag are consumed; everything else
    (including unknown ``-key=value`` pairs) is left for downstream parsers.
    """
    if argv is None:
        return []
    remaining: List[str] = []
    reg = FlagRegister.get()
    for arg in argv:
        if isinstance(arg, bytes):
            arg = arg.decode()
        if arg.startswith("-") and "=" in arg:
            key, _, value = arg.lstrip("-").partition("=")
            if reg.has(key):
                reg.set_value(key, value)
                continue
        remaining.append(arg)
    return remaining
