"""Leveled logging + CHECK macros.

TPU-native equivalent of the reference logger
(ref: include/multiverso/util/log.h:22-142, src/util/log.cpp). Levels
Debug/Info/Error/Fatal, ``[LEVEL] [TIME]`` prefix, optional file tee, and
``CHECK`` / ``CHECK_NOTNULL`` that raise (the reference's Fatal optionally
kills the process; here it raises ``FatalError`` so tests can assert on it,
with ``set_kill_fatal(True)`` restoring abort semantics).
"""

from __future__ import annotations

import enum
import os
import sys
import threading
import time
from typing import Optional


class LogLevel(enum.IntEnum):
    Debug = 0
    Info = 1
    Error = 2
    Fatal = 3


class FatalError(RuntimeError):
    pass


class Logger:
    def __init__(self, level: LogLevel = LogLevel.Info):
        self._level = level
        self._file = None
        self._kill_fatal = False
        self._lock = threading.Lock()

    def reset_log_file(self, filename: Optional[str]) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
            if filename:
                self._file = open(filename, "a")

    def reset_log_level(self, level: LogLevel) -> None:
        self._level = LogLevel(level)

    def reset_kill_fatal(self, is_kill: bool) -> None:
        self._kill_fatal = bool(is_kill)

    @property
    def level(self) -> LogLevel:
        return self._level

    def write(self, level: LogLevel, fmt: str, *args) -> None:
        if level < self._level and level != LogLevel.Fatal:
            return
        msg = (fmt % args) if args else fmt
        stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime())
        line = f"[{level.name.upper()}] [{stamp}] {msg}"
        if not line.endswith("\n"):
            line += "\n"
        with self._lock:
            stream = sys.stderr if level >= LogLevel.Error else sys.stdout
            stream.write(line)
            stream.flush()
            if self._file is not None:
                self._file.write(line)
                self._file.flush()
        if level == LogLevel.Fatal:
            if self._kill_fatal:
                os._exit(1)
            raise FatalError(msg)

    def debug(self, fmt: str, *args) -> None:
        self.write(LogLevel.Debug, fmt, *args)

    def info(self, fmt: str, *args) -> None:
        self.write(LogLevel.Info, fmt, *args)

    def error(self, fmt: str, *args) -> None:
        self.write(LogLevel.Error, fmt, *args)

    def fatal(self, fmt: str, *args) -> None:
        self.write(LogLevel.Fatal, fmt, *args)


def _env_level() -> LogLevel:
    raw = os.environ.get("MV_LOG_LEVEL", "")
    try:
        return LogLevel(int(raw))
    except (ValueError, KeyError):
        by_name = {l.name.lower(): l for l in LogLevel}
        return by_name.get(raw.strip().lower(), LogLevel.Info)


_logger = Logger(_env_level())


def logger() -> Logger:
    return _logger


def debug(fmt: str, *args) -> None:
    _logger.debug(fmt, *args)


def info(fmt: str, *args) -> None:
    _logger.info(fmt, *args)


def error(fmt: str, *args) -> None:
    _logger.error(fmt, *args)


def fatal(fmt: str, *args) -> None:
    _logger.fatal(fmt, *args)


def set_log_level(level: LogLevel) -> None:
    _logger.reset_log_level(level)


def set_log_file(filename: Optional[str]) -> None:
    _logger.reset_log_file(filename)


def set_kill_fatal(is_kill: bool) -> None:
    _logger.reset_kill_fatal(is_kill)


def CHECK(condition, msg: str = "") -> None:
    """ref: include/multiverso/util/log.h:10-13."""
    if not condition:
        fatal("Check failed: %s", msg or "<condition>")


def CHECK_NOTNULL(pointer, name: str = "pointer"):
    """ref: include/multiverso/util/log.h:15-17."""
    if pointer is None:
        fatal("%s must not be None", name)
    return pointer
