"""Countdown latch used by async table requests.

TPU-native equivalent of the reference's ``Waiter``
(ref: include/multiverso/util/waiter.h:9-33): ``wait()`` blocks until
``notify()`` has been called ``num_wait`` times; ``reset(n)`` re-arms.
"""

from __future__ import annotations

import itertools

from .lock_witness import monotonic, named_condition, named_lock

_serial = itertools.count()


class Waiter:
    def __init__(self, num_wait: int = 1, name: str = ""):
        name = name or f"waiter[{next(_serial)}]"
        self._mutex = named_lock(name)
        self._cond = named_condition(f"{name}.cond", self._mutex)
        self._num_wait = num_wait

    def wait(self, timeout=None) -> bool:
        deadline = None if timeout is None else monotonic() + timeout
        with self._cond:
            while self._num_wait > 0:
                remaining = None if deadline is None \
                    else deadline - monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                if not self._cond.wait(timeout=remaining):
                    return False
            return True

    def notify(self) -> None:
        with self._cond:
            self._num_wait -= 1
            if self._num_wait <= 0:
                self._cond.notify_all()

    def add_waits(self, k: int) -> None:
        """Raise the pending count by ``k`` — the replica-repair path:
        one shard reply is being REPLACED by ``k+1`` follow-up shards
        (the worker actor suppresses that reply's notify and sends the
        follow-ups), so the waiter must expect the extras. Only valid
        while at least one notify is still outstanding and only from
        the thread that would have delivered it (the worker actor):
        a completed waiter must never be re-armed this way."""
        with self._cond:
            if self._num_wait <= 0:
                # Completed (an abort's release raced the repair):
                # re-arming would strand the releaser — drop the
                # extension; the repair replies land as no-ops.
                return
            self._num_wait += k

    def release(self) -> None:
        """Force-complete: wake every waiter regardless of pending count
        (abort path — the caller records why)."""
        with self._cond:
            self._num_wait = 0
            self._cond.notify_all()

    @property
    def done(self) -> bool:
        with self._mutex:
            return self._num_wait <= 0

    @property
    def pending(self) -> int:
        """Outstanding notifies (diagnostic: how many shard replies a
        timed-out request was still missing)."""
        with self._mutex:
            return max(self._num_wait, 0)

    def reset(self, num_wait: int) -> None:
        with self._cond:
            self._num_wait = num_wait
            if self._num_wait <= 0:
                # Re-arming to zero must release anyone already blocked
                # (e.g. a request whose partition produced no shards).
                self._cond.notify_all()
