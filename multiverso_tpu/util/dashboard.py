"""Named performance counters (tracing/profiling subsystem).

TPU-native equivalent of the reference's ``Dashboard``/``Monitor``
(ref: include/multiverso/dashboard.h:16-74, src/dashboard.cpp:14-49): global
registry of named monitors, each accumulating call count and elapsed ms;
``Dashboard.display()`` dumps all. The MONITOR_BEGIN/END macro pair becomes a
context manager (``with monitor("name"):``); on TPU, ``jax.profiler`` traces
can be layered on via ``trace=True`` which opens a profiler ``TraceAnnotation``
so monitored regions show up in xprof.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .lock_witness import named_lock


class Monitor:
    def __init__(self, name: str):
        self.name = name
        self._count = 0
        self._elapsed_ms = 0.0
        self._local = threading.local()  # per-thread begin time
        self._lock = named_lock(f"dashboard.monitor[{name}]")

    def begin(self) -> None:
        self._local.begin = time.perf_counter()

    def end(self) -> None:
        begin = getattr(self._local, "begin", None)
        if begin is None:
            return
        elapsed = (time.perf_counter() - begin) * 1e3
        with self._lock:
            self._count += 1
            self._elapsed_ms += elapsed
        self._local.begin = None

    def add(self, elapsed_ms: float) -> None:
        with self._lock:
            self._count += 1
            self._elapsed_ms += elapsed_ms

    @property
    def count(self) -> int:
        return self._count

    @property
    def elapse(self) -> float:
        return self._elapsed_ms

    @property
    def average(self) -> float:
        return self._elapsed_ms / self._count if self._count else 0.0

    def __str__(self) -> str:
        return (f"[{self.name}] count = {self._count} "
                f"elapse = {self._elapsed_ms:.2f}ms "
                f"average = {self.average:.3f}ms")


class Dashboard:
    _monitors: Dict[str, Monitor] = {}
    # Module-level singleton: witnessed only when -debug_locks was set
    # before the first dashboard import (util/lock_witness.py).
    _lock = named_lock("dashboard.registry")

    @classmethod
    def get(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = Monitor(name)
                cls._monitors[name] = mon
            return mon

    @classmethod
    def add_monitor(cls, monitor: Monitor) -> None:
        with cls._lock:
            cls._monitors[monitor.name] = monitor

    @classmethod
    def watch(cls, name: str) -> str:
        with cls._lock:
            mon = cls._monitors.get(name)
            return str(mon) if mon else f"[{name}] <unregistered>"

    @classmethod
    def display(cls) -> str:
        with cls._lock:
            lines = [str(m) for m in cls._monitors.values()]
        report = "\n".join(lines)
        return report

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._monitors.clear()


class monitor:
    """Context manager replacing MONITOR_BEGIN/END macro pair.

    With ``trace=True`` also emits a jax.profiler TraceAnnotation so the
    region is visible in xprof traces captured on TPU.
    """

    def __init__(self, name: str, trace: bool = False):
        self._monitor = Dashboard.get(name)
        self._trace_ctx = None
        if trace:
            import jax.profiler
            self._trace_ctx = jax.profiler.TraceAnnotation(name)

    def __enter__(self) -> Monitor:
        if self._trace_ctx is not None:
            self._trace_ctx.__enter__()
        self._monitor.begin()
        return self._monitor

    def __exit__(self, *exc) -> None:
        self._monitor.end()
        if self._trace_ctx is not None:
            self._trace_ctx.__exit__(*exc)
        return None


def count(name: str) -> None:
    """Bump a named counter — a Monitor used purely for its call count
    (elapsed stays 0). The client cache's hit/miss/join counters ride
    the same registry as the timing monitors so ``Dashboard.display()``
    shows them side by side."""
    Dashboard.get(name).add(0.0)


def trace_to(log_dir: str):
    """Whole-program xprof capture: everything inside the block —
    including ``monitor(..., trace=True)`` annotations — lands in a
    TensorBoard-loadable trace under ``log_dir``. The TPU-native
    counterpart of reading Dashboard.display() next to an MPI profile
    (SURVEY.md section 5.1). Thin lazy-import alias of
    ``jax.profiler.trace`` so future jax trace features are inherited.

        with trace_to("/tmp/xprof"):
            model.train_batches(loader)
    """
    import jax.profiler
    return jax.profiler.trace(log_dir)
