"""Named performance counters (tracing/profiling subsystem).

TPU-native equivalent of the reference's ``Dashboard``/``Monitor``
(ref: include/multiverso/dashboard.h:16-74, src/dashboard.cpp:14-49): global
registry of named monitors, each accumulating call count and elapsed ms;
``Dashboard.display()`` dumps all. The MONITOR_BEGIN/END macro pair becomes a
context manager (``with monitor("name"):``); on TPU, ``jax.profiler`` traces
can be layered on via ``trace=True`` which opens a profiler ``TraceAnnotation``
so monitored regions show up in xprof.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from .lock_witness import named_lock


class Monitor:
    def __init__(self, name: str):
        self.name = name
        self._count = 0
        self._elapsed_ms = 0.0
        self._local = threading.local()  # per-thread begin time
        self._lock = named_lock(f"dashboard.monitor[{name}]")

    def begin(self) -> None:
        self._local.begin = time.perf_counter()

    def end(self) -> None:
        begin = getattr(self._local, "begin", None)
        if begin is None:
            return
        elapsed = (time.perf_counter() - begin) * 1e3
        with self._lock:
            self._count += 1
            self._elapsed_ms += elapsed
        self._local.begin = None

    def add(self, elapsed_ms: float) -> None:
        with self._lock:
            self._count += 1
            self._elapsed_ms += elapsed_ms

    def add_count(self, n: int) -> None:
        """Bulk count bump with no elapsed time (row-granular event
        counters — replica hit/miss rows per reply)."""
        with self._lock:
            self._count += n

    @property
    def count(self) -> int:
        return self._count

    @property
    def elapse(self) -> float:
        return self._elapsed_ms

    @property
    def average(self) -> float:
        return self._elapsed_ms / self._count if self._count else 0.0

    def __str__(self) -> str:
        return (f"[{self.name}] count = {self._count} "
                f"elapse = {self._elapsed_ms:.2f}ms "
                f"average = {self.average:.3f}ms")


class Dashboard:
    _monitors: Dict[str, Monitor] = {}
    # Module-level singleton: witnessed only when -debug_locks was set
    # before the first dashboard import (util/lock_witness.py).
    _lock = named_lock("dashboard.registry")

    @classmethod
    def get(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = Monitor(name)
                cls._monitors[name] = mon
            return mon

    @classmethod
    def add_monitor(cls, monitor: Monitor) -> None:
        with cls._lock:
            cls._monitors[monitor.name] = monitor

    @classmethod
    def watch(cls, name: str) -> str:
        with cls._lock:
            mon = cls._monitors.get(name)
            return str(mon) if mon else f"[{name}] <unregistered>"

    @classmethod
    def display(cls) -> str:
        with cls._lock:
            lines = [str(m) for m in cls._monitors.values()]
        report = "\n".join(lines)
        return report

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._monitors.clear()


class monitor:
    """Context manager replacing MONITOR_BEGIN/END macro pair.

    With ``trace=True`` also emits a jax.profiler TraceAnnotation so the
    region is visible in xprof traces captured on TPU.
    """

    def __init__(self, name: str, trace: bool = False):
        self._monitor = Dashboard.get(name)
        self._trace_ctx = None
        if trace:
            import jax.profiler
            self._trace_ctx = jax.profiler.TraceAnnotation(name)

    def __enter__(self) -> Monitor:
        if self._trace_ctx is not None:
            self._trace_ctx.__enter__()
        self._monitor.begin()
        return self._monitor

    def __exit__(self, *exc) -> None:
        self._monitor.end()
        if self._trace_ctx is not None:
            self._trace_ctx.__exit__(*exc)
        return None


class Samples:
    """Bounded reservoir of per-op scalar samples (latencies, queue
    depths) with percentile readout — the p50/p99 companion to the
    cumulative ``Monitor``. Ring-buffer overwrite past ``cap`` keeps the
    cost O(1) per sample and the memory bounded; percentiles are then
    over the most recent ``cap`` observations, which is what a bench
    window wants anyway."""

    def __init__(self, name: str, cap: int = 8192):
        self.name = name
        self._cap = int(cap)
        self._buf: list = []
        self._next = 0
        self._total = 0
        self._lock = named_lock(f"dashboard.samples[{name}]")

    def add(self, value: float) -> None:
        with self._lock:
            if len(self._buf) < self._cap:
                self._buf.append(float(value))
            else:
                self._buf[self._next] = float(value)
                self._next = (self._next + 1) % self._cap
            self._total += 1

    @property
    def count(self) -> int:
        return self._total

    def percentile(self, p: float) -> float:
        """The p-th percentile (0-100) of the retained window; 0.0 when
        empty."""
        with self._lock:
            data = sorted(self._buf)
        if not data:
            return 0.0
        idx = min(int(len(data) * p / 100.0), len(data) - 1)
        return data[idx]

    def snapshot(self) -> dict:
        """Bench-friendly summary: count + p50/p90/p99/max."""
        with self._lock:
            data = sorted(self._buf)
            total = self._total
        if not data:
            return {"count": total}

        def pick(p):
            return data[min(int(len(data) * p / 100.0), len(data) - 1)]

        return {"count": total, "p50": pick(50), "p90": pick(90),
                "p99": pick(99), "max": data[-1]}


_samples: Dict[str, Samples] = {}
_samples_lock = named_lock("dashboard.samples_registry")


def samples(name: str, cap: int = 8192) -> Samples:
    """Registry accessor for ``Samples`` (mirrors ``Dashboard.get``)."""
    with _samples_lock:
        s = _samples.get(name)
        if s is None:
            s = Samples(name, cap)
            _samples[name] = s
        return s


def reset_samples() -> None:
    with _samples_lock:
        _samples.clear()


def count(name: str, n: int = 1) -> None:
    """Bump a named counter by ``n`` — a Monitor used purely for its
    call count (elapsed stays 0). The client cache's hit/miss/join
    counters ride the same registry as the timing monitors so
    ``Dashboard.display()`` shows them side by side. ``n`` > 1 serves
    row-granular counters (replica hit/miss rows per reply) without a
    per-row Python loop."""
    if n > 0:
        Dashboard.get(name).add_count(n)


def trace_to(log_dir: str):
    """Whole-program xprof capture: everything inside the block —
    including ``monitor(..., trace=True)`` annotations — lands in a
    TensorBoard-loadable trace under ``log_dir``. The TPU-native
    counterpart of reading Dashboard.display() next to an MPI profile
    (SURVEY.md section 5.1). Thin lazy-import alias of
    ``jax.profiler.trace`` so future jax trace features are inherited.

        with trace_to("/tmp/xprof"):
            model.train_batches(loader)
    """
    import jax.profiler
    return jax.profiler.trace(log_dir)
