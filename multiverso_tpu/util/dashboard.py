"""Named performance counters (tracing/profiling subsystem).

TPU-native equivalent of the reference's ``Dashboard``/``Monitor``
(ref: include/multiverso/dashboard.h:16-74, src/dashboard.cpp:14-49): global
registry of named monitors, each accumulating call count and elapsed ms;
``Dashboard.display()`` dumps all. The MONITOR_BEGIN/END macro pair becomes a
context manager (``with monitor("name"):``); on TPU, ``jax.profiler`` traces
can be layered on via ``trace=True`` which opens a profiler ``TraceAnnotation``
so monitored regions show up in xprof.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List, Optional

from .lock_witness import named_lock

#: CANONICAL METRIC-NAME REGISTRY — the one name-and-meaning table for
#: every ``monitor("X")`` / ``samples("X")`` / ``count("X")`` literal
#: in the tree. ``tools/mvlint``'s metric-name pass parses this literal
#: (never imports) and fails CI on any call site naming an unlisted
#: metric, and cross-checks the table against the metric table in
#: ``docs/OBSERVABILITY.md`` in both directions. A trailing ``*``
#: matches a per-destination / per-table FAMILY suffix
#: (``DISPATCH_MS[d*]`` covers ``DISPATCH_MS[d0]``, ``DISPATCH_MS[d7]``,
#: ...). Keep the literal plain (no computed values).
METRIC_NAMES: Dict[str, str] = {
    # -- worker actor / table layer --
    "WORKER_PROCESS_GET": "worker actor Get partition+send handling",
    "WORKER_PROCESS_ADD": "worker actor Add partition+send handling",
    "WORKER_COALESCE_FLUSH": "coalesced BatchAdd flushes packed",
    "WORKER_TABLE_SYNC_GET": "blocking table get_raw issue-to-reply",
    "WORKER_TABLE_SYNC_ADD": "blocking table add_raw issue-to-ack",
    # -- server actor --
    "SERVER_PROCESS_GET": "server-side Get table op + reply",
    "SERVER_PROCESS_ADD": "server-side Add apply + ack",
    "SERVER_PROCESS_BATCH_ADD": "server-side coalesced batch apply",
    # -- server request fusion (runtime/fusion.py; docs/SERVER_ENGINE.md) --
    "SERVER_FUSE_BATCH": "fused mailbox batch sizes (messages drained "
                         "per dispatch; sampled only when > 1)",
    "SERVER_DEVICE_DISPATCHES": "device programs dispatched by server "
                                "table ops (serial + fused paths)",
    "SERVER_FUSE_DEDUP_ROWS": "cross-request duplicate rows gathered "
                              "once by a fused Get",
    # -- model / collective stalls --
    "PS_GET_STALL": "trainer blocked on a parameter Get (prefetch miss)",
    "MA_COMM_STALL": "model-average blocked on the collective",
    # -- sparse collective tier (runtime/allreduce_engine.py) --
    "SPARSE_FILL[*]": "sparse collective fill-in: union density per "
                      "merge hop ([reduce]) and probed input density "
                      "([input])",
    # -- snapshotter --
    "SNAPSHOT_CAPTURE": "consistent state cut under the table lock",
    "SNAPSHOT_WRITE": "snapshot serialize+write off the lock",
    # -- wire transport --
    "tcp_serialize": "message -> wire frame serialize",
    "tcp_send": "blocking socket send of one frame",
    "tcp_recv": "socket read of one inbound frame body",
    "tcp_deserialize": "wire frame -> message parse",
    # -- zero-copy wire path (runtime/tcp.py, util/buffer_pool.py;
    #    docs/MEMORY.md) --
    "WIRE_BYTES_COPIED": "payload+framing bytes memcpy'd by "
                         "serialize/deserialize (the zero-copy "
                         "bench signal)",
    "WIRE_PAYLOAD_BYTES": "payload bytes that crossed "
                          "serialize/deserialize (the copy-ratio "
                          "denominator)",
    "POOL_HIT": "receive-frame leases served from the buffer pool",
    "POOL_MISS": "receive-frame leases that allocated fresh",
    "POOL_RESIDENT_KB": "buffer-pool retained free bytes (KB) at "
                        "each return",
    # -- shared-memory transport (runtime/shm.py; docs/MEMORY.md
    #    "Below the socket") --
    "shm_send": "ring-slot copy of one outbound frame (the shm data "
                "path's single copy)",
    "shm_recv": "in-place parse (or chunk reassembly) of one "
                "ring-borne frame",
    "SHM_FRAMES": "frames sent through shm rings",
    "SHM_BYTES": "frame bytes sent through shm rings",
    "SHM_RING_FULL_WAITS": "ring-full backpressure episodes on shm "
                           "writer threads (slow-reader signal)",
    "SHM_CHUNKED_FRAMES": "frames larger than one ring slot, streamed "
                          "as CONT chunks",
    "SHM_BYTES_COPIED": "bytes copied out of ring slots reassembling "
                        "chunked frames (single-slot frames parse in "
                        "place and count nothing here)",
    "SHM_SLOT_PARKED": "ring slots parked because a Blob view "
                       "outlived its message (freed on re-probe)",
    "SHM_PIN_COPIES": "frames copied off the ring because consumer-"
                      "held frames pinned half the slots (the anti-"
                      "deadlock pressure valve)",
    # -- client cache (tables/client_cache.py) --
    "CLIENT_CACHE_HIT": "cache lookups served locally",
    "CLIENT_CACHE_MISS": "cache lookups that crossed the wire",
    "CLIENT_CACHE_JOIN": "gets joined onto an in-flight prefetch",
    "CLIENT_CACHE_PREFETCH": "prefetch requests issued",
    # -- hot-shard replication (runtime/replica.py) --
    "REPLICA_HIT": "rows served from a replica store",
    "REPLICA_MISS": "replicated rows a holder could not serve",
    "REPLICA_REPAIR": "repair requests issued to row owners",
    "REPLICA_STALE": "replica groups rejected below a RYW floor",
    "REPLICA_SYNC": "write-through refreshes fanned out",
    # -- elastic resharding + chaos harness (runtime/shard_map.py,
    #    util/chaos.py; docs/SHARDING.md) --
    "SHARD_MIGRATE_ROWS": "rows/buckets streamed between servers by "
                          "live migrations",
    "SHARD_FWD": "requests routed through a dual-read/forwarding "
                 "window",
    "SHARD_RETRANSMIT": "migration chunks re-sent after a detected "
                        "seq gap",
    "CHAOS_DROPPED": "frames dropped by the -chaos_frames harness",
    "CHAOS_DELAYED": "frames delayed by the -chaos_frames harness",
    # -- event-loop transport core (runtime/tcp.py; docs/THREADS.md) --
    "DISPATCH_MS[d*]": "per-destination submit-to-wire-complete "
                       "latency (ms) on the event loop",
    "DISPATCH_QUEUE_DEPTH[d*]": "per-destination outbound frame-queue "
                                "depth at submit",
    "EVENTLOOP_TICK_MS": "event-loop tick duration (ms): one "
                         "select-wake's worth of handler+timer work",
    "EVENTLOOP_READY_FDS": "fds reported ready per selector wake",
    "NET_PEER_STATE[*]": "peer state-machine transitions entered "
                         "(CONNECTING/HANDSHAKE/READY/DRAINING/DEAD)",
    "TRANSPORT_THREADS": "live transport threads per rank (EVENTLOOP "
                         "+ shm WRITER); the O(1)-in-peers invariant",
    # -- observability export (runtime/metrics.py) --
    "METRICS_REPORT": "per-rank metrics snapshots shipped",
    "METRICS_DROPPED_STALE": "out-of-order/stale rank reports the "
                             "controller aggregation dropped",
    # -- closed-loop self-tuning (runtime/autotune.py) --
    "AUTOTUNE_DECISION": "knob changes broadcast by the autotune "
                         "controller",
    # -- actor mailboxes (util/mt_queue.py track_depth) --
    "MAILBOX_DEPTH[*]": "actor mailbox depth at each push",
    # -- thread-role blocking watchdog (runtime/thread_roles.py;
    #    docs/THREADS.md) --
    "ROLE_BLOCKED_MS[*]": "wall-clock ms a DISPATCH/LIVENESS/"
                          "EVENTLOOP thread sat blocked past "
                          "-role_block_budget_ms (per role, "
                          "-debug_locks watchdog)",
    # -- online serving tier (serving/; docs/SERVING.md) --
    "SERVING_REQUESTS": "serving-frontend requests admitted and served",
    "SERVING_SHED": "serving-frontend requests rejected by admission",
    "SERVING_LATENCY_MS": "serving-frontend request latency (ms)",
    "SERVING_BATCH_SIZE": "requests folded into one serving read batch",
    "SERVING_CACHE_HIT": "requests served whole from the hot-response "
                         "cache",
    "ANN_PROBE_MS": "IVF neighbors probe latency (ms)",
}

#: Version stamp on serialized metrics snapshots
#: (``metrics_snapshot()``): consumers reject a snapshot whose version
#: they do not understand instead of mis-merging it.
#: Family matching against the registry (trailing-``*`` entries) lives
#: in ``tools/mvlint/metric_lint.py family_match`` — the one
#: implementation, used by the lint that enforces this registry.
METRICS_SNAPSHOT_VERSION = 1


class Monitor:
    def __init__(self, name: str):
        self.name = name
        self._count = 0
        self._elapsed_ms = 0.0
        self._local = threading.local()  # per-thread begin time
        self._lock = named_lock(f"dashboard.monitor[{name}]")

    def begin(self) -> None:
        self._local.begin = time.perf_counter()

    def end(self) -> None:
        begin = getattr(self._local, "begin", None)
        if begin is None:
            return
        elapsed = (time.perf_counter() - begin) * 1e3
        with self._lock:
            self._count += 1
            self._elapsed_ms += elapsed
        self._local.begin = None

    def add(self, elapsed_ms: float) -> None:
        with self._lock:
            self._count += 1
            self._elapsed_ms += elapsed_ms

    def add_count(self, n: int) -> None:
        """Bulk count bump with no elapsed time (row-granular event
        counters — replica hit/miss rows per reply)."""
        with self._lock:
            self._count += n

    @property
    def count(self) -> int:
        return self._count

    @property
    def elapse(self) -> float:
        return self._elapsed_ms

    @property
    def average(self) -> float:
        return self._elapsed_ms / self._count if self._count else 0.0

    def __str__(self) -> str:
        return (f"[{self.name}] count = {self._count} "
                f"elapse = {self._elapsed_ms:.2f}ms "
                f"average = {self.average:.3f}ms")


class Dashboard:
    _monitors: Dict[str, Monitor] = {}
    # Module-level singleton: witnessed only when -debug_locks was set
    # before the first dashboard import (util/lock_witness.py).
    _lock = named_lock("dashboard.registry")

    @classmethod
    def get(cls, name: str) -> Monitor:
        with cls._lock:
            mon = cls._monitors.get(name)
            if mon is None:
                mon = Monitor(name)
                cls._monitors[name] = mon
            return mon

    @classmethod
    def add_monitor(cls, monitor: Monitor) -> None:
        with cls._lock:
            cls._monitors[monitor.name] = monitor

    @classmethod
    def watch(cls, name: str) -> str:
        with cls._lock:
            mon = cls._monitors.get(name)
            return str(mon) if mon else f"[{name}] <unregistered>"

    @classmethod
    def display(cls) -> str:
        """Full registry report: monitors AND sample reservoirs, each
        section sorted by name so successive dumps diff cleanly (dict
        insertion order made the report depend on which code path ran
        first)."""
        with cls._lock:
            lines = [str(m) for _, m in sorted(cls._monitors.items())]
        with _samples_lock:
            reservoirs = sorted(_samples.items())
        for name, s in reservoirs:
            snap = s.snapshot()
            if snap.get("count"):
                lines.append(
                    f"[{name}] count = {snap['count']} "
                    f"p50 = {snap.get('p50', 0.0):.3f} "
                    f"p90 = {snap.get('p90', 0.0):.3f} "
                    f"p99 = {snap.get('p99', 0.0):.3f} "
                    f"max = {snap.get('max', 0.0):.3f}")
        return "\n".join(lines)

    @classmethod
    def reset(cls) -> None:
        with cls._lock:
            cls._monitors.clear()


class monitor:
    """Context manager replacing MONITOR_BEGIN/END macro pair.

    With ``trace=True`` also emits a jax.profiler TraceAnnotation so the
    region is visible in xprof traces captured on TPU.
    """

    def __init__(self, name: str, trace: bool = False):
        self._name = name
        self._monitor: Optional[Monitor] = None
        self._trace_ctx = None
        if trace:
            import jax.profiler
            self._trace_ctx = jax.profiler.TraceAnnotation(name)

    def __enter__(self) -> Monitor:
        if self._trace_ctx is not None:
            self._trace_ctx.__enter__()
        # Re-resolved per entry, NOT cached at construction: a
        # ``Dashboard.reset()`` (every bench phase does one) replaces
        # the registry, and a long-lived ``monitor(...)`` instance
        # caching its Monitor would keep writing to an unregistered
        # orphan that no display()/snapshot ever sees.
        self._monitor = Dashboard.get(self._name)
        self._monitor.begin()
        return self._monitor

    def __exit__(self, *exc) -> None:
        if self._monitor is not None:
            self._monitor.end()
        if self._trace_ctx is not None:
            self._trace_ctx.__exit__(*exc)
        return None


class Samples:
    """Bounded reservoir of per-op scalar samples (latencies, queue
    depths) with percentile readout — the p50/p99 companion to the
    cumulative ``Monitor``. Ring-buffer overwrite past ``cap`` keeps the
    cost O(1) per sample and the memory bounded; percentiles are then
    over the most recent ``cap`` observations, which is what a bench
    window wants anyway."""

    def __init__(self, name: str, cap: int = 8192):
        self.name = name
        self._cap = int(cap)
        self._buf: list = []
        self._next = 0
        self._total = 0
        self._lock = named_lock(f"dashboard.samples[{name}]")

    def add(self, value: float) -> None:
        with self._lock:
            if len(self._buf) < self._cap:
                self._buf.append(float(value))
            else:
                self._buf[self._next] = float(value)
                self._next = (self._next + 1) % self._cap
            self._total += 1

    @property
    def count(self) -> int:
        return self._total

    @staticmethod
    def _nearest_rank(data: list, p: float) -> float:
        """Nearest-rank percentile over sorted ``data``: the
        ceil(p/100 * n)-th smallest value (1-indexed), so p50 of a
        2-element window is the LOWER value and a 1-element window
        answers every p with its only value."""
        idx = max(math.ceil(len(data) * min(max(p, 0.0), 100.0)
                            / 100.0), 1) - 1
        return data[min(idx, len(data) - 1)]

    def percentile(self, p: float) -> float:
        """The p-th percentile (0-100, nearest-rank) of the retained
        window; 0.0 when empty."""
        with self._lock:
            data = sorted(self._buf)
        if not data:
            return 0.0
        return self._nearest_rank(data, p)

    def snapshot(self) -> dict:
        """Bench-friendly summary: count + p50/p90/p99/max."""
        with self._lock:
            data = sorted(self._buf)
            total = self._total
        if not data:
            return {"count": total}
        return {"count": total,
                "p50": self._nearest_rank(data, 50),
                "p90": self._nearest_rank(data, 90),
                "p99": self._nearest_rank(data, 99),
                "max": data[-1]}

    def export_recent(self, limit: int = 256) -> List[float]:
        """Up to ``limit`` of the most recent retained values, oldest
        first — the raw window the controller merges cluster-wide
        percentiles from (summary snapshots cannot be merged without
        the underlying samples; docs/OBSERVABILITY.md)."""
        with self._lock:
            if len(self._buf) < self._cap or self._next == 0:
                ordered = list(self._buf)
            else:  # ring wrapped: oldest sits at _next
                ordered = self._buf[self._next:] + self._buf[:self._next]
        return ordered[-max(int(limit), 1):]


_samples: Dict[str, Samples] = {}
_samples_lock = named_lock("dashboard.samples_registry")


def samples(name: str, cap: int = 8192) -> Samples:
    """Registry accessor for ``Samples`` (mirrors ``Dashboard.get``)."""
    with _samples_lock:
        s = _samples.get(name)
        if s is None:
            s = Samples(name, cap)
            _samples[name] = s
        return s


def reset_samples() -> None:
    with _samples_lock:
        _samples.clear()


def metrics_snapshot(max_samples: int = 256) -> dict:
    """Serialize the whole registry (monitors + sample reservoirs) into
    a versioned plain dict — the per-rank payload of the
    ``Control_Metrics`` export (runtime/metrics.py) and the local half
    of every ``/metrics`` scrape. ``max_samples`` caps the raw window
    shipped per reservoir (the controller merges these into cluster
    percentiles)."""
    with Dashboard._lock:
        monitors = list(Dashboard._monitors.items())
    with _samples_lock:
        reservoirs = list(_samples.items())
    return {
        "v": METRICS_SNAPSHOT_VERSION,
        "monitors": {name: {"count": m.count,
                            "elapsed_ms": round(m.elapse, 3)}
                     for name, m in monitors},
        "samples": {name: {"count": s.count,
                           "recent": s.export_recent(max_samples)}
                    for name, s in reservoirs},
    }


def count(name: str, n: int = 1) -> None:
    """Bump a named counter by ``n`` — a Monitor used purely for its
    call count (elapsed stays 0). The client cache's hit/miss/join
    counters ride the same registry as the timing monitors so
    ``Dashboard.display()`` shows them side by side. ``n`` > 1 serves
    row-granular counters (replica hit/miss rows per reply) without a
    per-row Python loop."""
    if n > 0:
        Dashboard.get(name).add_count(n)


def trace_to(log_dir: str):
    """Whole-program xprof capture: everything inside the block —
    including ``monitor(..., trace=True)`` annotations — lands in a
    TensorBoard-loadable trace under ``log_dir``. The TPU-native
    counterpart of reading Dashboard.display() next to an MPI profile
    (SURVEY.md section 5.1). Thin lazy-import alias of
    ``jax.profiler.trace`` so future jax trace features are inherited.

        with trace_to("/tmp/xprof"):
            model.train_batches(loader)
    """
    import jax.profiler
    return jax.profiler.trace(log_dir)
