"""Distributed request tracing (docs/OBSERVABILITY.md).

Flag-gated per-request tracing across the PS runtime: with
``-trace_sample_rate > 0`` a request issued at a worker table draws a
cluster-unique trace id (rank in the high bits), which travels in wire
header slot 9 (``TRACE_SLOT``, core/message.py) on every shard, batch
and reply message the request spawns. Each hop — worker issue, coalesce
flush, event-loop submit, tcp serialize/send, server table op, waiter
notify — records a span event into a bounded process-local ring buffer;
``chrome_trace`` merges per-rank buffers into one Chrome-trace/Perfetto
JSON where spans from different ranks pair under the request's trace id
(pid = rank, tid = thread name).

Timestamps are ``time.time_ns()`` — the WALL clock, so spans recorded
on different ranks of a same-host cluster nest correctly in the merged
view; cross-host skew shifts a rank's lane without breaking the
per-trace grouping. Durations are wall-clock too.

Default (``-trace_sample_rate=0``) is a no-op: ``new_trace`` returns 0
after one flag read, every ``span(0, ...)`` hands back a shared inert
context manager, and the wire stays byte-identical to an untraced build
everywhere except the declared header-length bump
(docs/WIRE_FORMAT.md). The ``-trace_slow_ms`` watchdog logs any sampled
request whose root span exceeds the threshold, with the full locally
recorded span timeline for its trace id.
"""

from __future__ import annotations

import collections
import itertools
import random
import threading
import time
from typing import Dict, Iterable, List, Optional

from . import log
from .configure import define_double, define_int, get_flag
from .lock_witness import named_lock

define_double("trace_sample_rate", 0.0,
              "fraction of worker table requests that record a "
              "distributed trace (0 = tracing off, the default: no "
              "ids are drawn, no spans are recorded, and the wire "
              "carries 0 in the trace header slot — byte-identical to "
              "an untraced build modulo the declared header-length "
              "bump). 1.0 traces every request; sampled requests pay "
              "~a dict append per hop (docs/OBSERVABILITY.md)")
define_double("trace_slow_ms", 0.0,
              "slow-request watchdog: a SAMPLED request whose "
              "issue-to-completion root span exceeds this many "
              "milliseconds is logged with its full locally-recorded "
              "span timeline (queue vs wire vs table attribution "
              "without scraping /trace.json). 0 (default) disables "
              "the watchdog")
define_int("trace_buffer", 4096,
           "per-process span-event ring buffer capacity: the newest "
           "this many events are retained for export/merge; older "
           "events are overwritten (bounded memory under 100% "
           "sampling)")

#: Trace id layout: [7 bits rank | 23 bits counter], always > 0 (the
#: counter starts at 1), always < 2^30 so the id rides a signed-int32
#: wire header slot with room to spare. Ranks beyond 127 wrap — ids
#: stay unique per rank window, merely less attributable by eye.
_COUNTER_BITS = 23
_COUNTER_MASK = (1 << _COUNTER_BITS) - 1

_counter = itertools.count(1)
_seq = itertools.count(1)
_lock = named_lock("tracing.events")
_events: Optional[collections.deque] = None


def trace_rank(trace_id: int) -> int:
    """The issuing rank encoded in a trace id."""
    return (int(trace_id) >> _COUNTER_BITS) & 0x7F


def new_trace(rank: int) -> int:
    """Sampling decision at request issue: a fresh cluster-unique trace
    id, or 0 (untraced — the common, near-free path)."""
    rate = float(get_flag("trace_sample_rate"))
    if rate <= 0.0:
        return 0
    if rate < 1.0 and random.random() >= rate:
        return 0
    counter = next(_counter) & _COUNTER_MASK
    return ((int(rank) & 0x7F) << _COUNTER_BITS) | (counter or 1)


def now_ns() -> int:
    return time.time_ns()


def _record(entry: Dict) -> None:
    global _events
    with _lock:
        if _events is None:
            _events = collections.deque(
                maxlen=max(int(get_flag("trace_buffer")), 16))
        entry["seq"] = next(_seq)
        _events.append(entry)


def add_span(trace_id: int, name: str, rank: int, t0_ns: int,
             dur_ns: int, args: Optional[Dict] = None) -> None:
    """Record one completed span with an externally measured window
    (e.g. a queue wait whose start was stamped at enqueue)."""
    if not trace_id:
        return
    entry = {"trace": int(trace_id), "name": name, "ph": "X",
             "rank": int(rank), "ts": int(t0_ns), "dur": int(dur_ns),
             "thread": threading.current_thread().name}
    if args:
        entry["args"] = dict(args)
    _record(entry)


def event(trace_id: int, name: str, rank: int,
          args: Optional[Dict] = None) -> None:
    """Record one instant event (a hop marker with no duration)."""
    if not trace_id:
        return
    entry = {"trace": int(trace_id), "name": name, "ph": "i",
             "rank": int(rank), "ts": now_ns(),
             "thread": threading.current_thread().name}
    if args:
        entry["args"] = dict(args)
    _record(entry)


class _NullSpan:
    """Shared inert context manager for the untraced path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return None


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_trace", "_name", "_rank", "_args", "_t0")

    def __init__(self, trace_id: int, name: str, rank: int,
                 args: Optional[Dict]):
        self._trace = trace_id
        self._name = name
        self._rank = rank
        self._args = args
        self._t0 = 0

    def __enter__(self):
        self._t0 = now_ns()
        return self

    def __exit__(self, *exc):
        add_span(self._trace, self._name, self._rank, self._t0,
                 now_ns() - self._t0, self._args)
        return None


def span(trace_id: int, name: str, rank: int,
         args: Optional[Dict] = None):
    """Span context manager; inert (shared no-op) when ``trace_id`` is
    0, so untraced hot paths pay one truthiness check."""
    if not trace_id:
        return _NULL_SPAN
    return _Span(trace_id, name, rank, args)


def end_root(trace_id: int, name: str, rank: int, t0_ns: int,
             args: Optional[Dict] = None) -> None:
    """Close a request's ROOT span (issue -> waiter completion) and run
    the ``-trace_slow_ms`` watchdog: a root over the threshold logs its
    full locally-recorded timeline."""
    if not trace_id:
        return
    dur_ns = now_ns() - t0_ns
    add_span(trace_id, name, rank, t0_ns, dur_ns, args)
    slow_ms = float(get_flag("trace_slow_ms"))
    if slow_ms > 0 and dur_ns > slow_ms * 1e6:
        log.error("slow request: trace %d (%s, rank %d) took %.2f ms "
                  "(> -trace_slow_ms=%.1f); timeline:\n%s",
                  trace_id, name, rank, dur_ns / 1e6, slow_ms,
                  format_timeline(trace_id))


def format_timeline(trace_id: int) -> str:
    """Human-readable span timeline of one trace from the local buffer
    (the slow-request watchdog's payload), oldest first, offsets
    relative to the first event."""
    entries = [e for e in snapshot_events() if e["trace"] == trace_id]
    if not entries:
        return "  (no local span events retained)"
    entries.sort(key=lambda e: e["ts"])
    base = entries[0]["ts"]
    lines = []
    for e in entries:
        off_ms = (e["ts"] - base) / 1e6
        dur = f" dur={e['dur'] / 1e6:.3f}ms" if e.get("ph") == "X" \
            else ""
        lines.append(f"  +{off_ms:9.3f}ms r{e['rank']} "
                     f"{e['name']}{dur} [{e.get('thread', '?')}]")
    return "\n".join(lines)


def snapshot_events() -> List[Dict]:
    """Copy of the process-local event buffer (export / tests)."""
    with _lock:
        return list(_events) if _events is not None else []


def drain_since(last_seq: int) -> List[Dict]:
    """Events recorded after ``last_seq`` (incremental export: the
    metrics reporter ships only what the controller has not seen).
    Events that aged out of the ring before a drain are simply lost —
    the buffer bounds memory, not completeness."""
    with _lock:
        if _events is None:
            return []
        return [e for e in _events if e["seq"] > last_seq]


def reset() -> None:
    """Drop buffered events (tests / bench phase isolation); the next
    record re-reads -trace_buffer."""
    global _events
    with _lock:
        _events = None


def chrome_trace(event_lists: Iterable[List[Dict]]) -> Dict:
    """Merge per-rank event dumps into one Chrome-trace/Perfetto JSON
    object: ``pid`` = rank, ``tid`` = recording thread name, ``ts``/
    ``dur`` in microseconds, each event's ``args.trace`` carrying the
    request's trace id so cross-rank spans group under it."""
    out = []
    for events in event_lists:
        for e in events:
            entry = {"name": e["name"], "ph": e.get("ph", "X"),
                     "ts": e["ts"] / 1e3, "pid": int(e["rank"]),
                     "tid": str(e.get("thread", "?")),
                     "args": {"trace": int(e["trace"]),
                              **e.get("args", {})}}
            if entry["ph"] == "X":
                entry["dur"] = e.get("dur", 0) / 1e3
            else:
                entry["s"] = "p"  # instant scope: process
            out.append(entry)
    out.sort(key=lambda e: e["ts"])
    return {"traceEvents": out, "displayTimeUnit": "ms"}
