from . import configure, log, wire_codec
from .async_buffer import ASyncBuffer
from .dashboard import Dashboard, Monitor, monitor, trace_to
from .mt_queue import MtQueue
from .quantization import OneBitFilter, SparseFilter
from .timer import Timer
from .waiter import Waiter

__all__ = [
    "configure", "log", "wire_codec", "ASyncBuffer", "Dashboard",
    "Monitor", "monitor", "MtQueue", "OneBitFilter", "SparseFilter",
    "Timer", "Waiter", "trace_to",
]
