"""Double-buffer prefetcher.

TPU-native equivalent of the reference's ``ASyncBuffer``
(ref: include/multiverso/util/async_buffer.h:11-116): a background thread
fills the idle buffer via a user-provided fill function while the caller
consumes the ready one; ``get()`` waits for the in-flight fill then
immediately kicks off the next prefetch. This is the host-side overlap
primitive used by the data pipelines (the reference apps' ``-is_pipeline``
mode); on TPU it composes with jax async dispatch so host fill overlaps
device compute.
"""

from __future__ import annotations

import threading
from typing import Callable, Generic, TypeVar

T = TypeVar("T")


class ASyncBuffer(Generic[T]):
    def __init__(self, buffer0: T, buffer1: T, fill: Callable[[T], None]):
        self._buffers = [buffer0, buffer1]
        self._fill = fill
        self._ready_idx = 0
        self._pending: "threading.Thread | None" = None
        self._fill_error: "BaseException | None" = None
        self._stopped = False
        self._prefetch(0)

    def _prefetch(self, idx: int) -> None:
        def run() -> None:
            try:
                self._fill(self._buffers[idx])
            except BaseException as exc:  # re-raised in get()
                self._fill_error = exc
        # Local import: util must not pull the runtime package (and
        # its actor/zoo import chain) at module load.
        from ..runtime import thread_roles
        self._pending = thread_roles.spawn(
            thread_roles.BACKGROUND, target=run,
            name="mv-asyncbuffer-fill")
        self._pending_idx = idx

    def get(self) -> T:
        """Wait for the in-flight fill, return that buffer, prefetch the other."""
        if self._pending is not None:
            self._pending.join()
            self._pending = None
        if self._fill_error is not None:
            err, self._fill_error = self._fill_error, None
            raise err
        ready = self._pending_idx
        if not self._stopped:
            self._prefetch(1 - ready)
        return self._buffers[ready]

    def stop(self) -> None:
        self._stopped = True
        if self._pending is not None:
            self._pending.join()
            self._pending = None
