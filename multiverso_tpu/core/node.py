"""Per-process role record.

TPU-native equivalent of the reference's ``Node``/``Role``
(ref: include/multiverso/node.h:6-27, src/node.cpp:5-12). On TPU the natural
deployment is role=ALL on every process (each host both computes and owns a
shard of the tables in its devices' HBM), but WORKER/SERVER-only roles are
preserved for API parity with the reference's ``-ps_role`` flag.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field


class Role(enum.IntFlag):
    NONE = 0
    WORKER = 1
    SERVER = 2
    ALL = 3


@dataclass
class Node:
    rank: int = -1
    role: int = int(Role.ALL)
    worker_id: int = -1
    server_id: int = -1


def is_worker(role: int) -> bool:
    return bool(role & Role.WORKER)


def is_server(role: int) -> bool:
    return bool(role & Role.SERVER)


def role_from_string(name: str) -> Role:
    """Parse the -ps_role flag value (default/worker/server/all)."""
    name = name.strip().lower()
    if name in ("default", "all"):
        return Role.ALL
    if name == "worker":
        return Role.WORKER
    if name == "server":
        return Role.SERVER
    if name == "none":
        return Role.NONE
    raise ValueError(f"unknown ps_role: {name}")
