"""Wire message: 8-int header + list of payload blobs.

TPU-native equivalent of the reference's ``Message``
(ref: include/multiverso/message.h:13-66). Header layout and ``MsgType``
values are preserved exactly (src, dst, type, table_id, msg_id in
header[0..4]; requests positive, replies negative, control types >32) so the
routing rules in the communicator (ref: src/communicator.cpp:93-105) carry
over and a future cross-language transport can interoperate.
"""

from __future__ import annotations

import enum
from typing import List, Optional

import numpy as np

from .blob import Blob


class MsgType(enum.IntEnum):
    """ref: include/multiverso/message.h:13-24."""
    Default = 0
    Request_Get = 1
    Request_Add = 2
    # Coalesced Add: several pending Adds to the same server ride ONE
    # wire message (extension — the reference sends one message per
    # shard; value chosen inside the server-bound request band).
    Request_BatchAdd = 3
    # Hot-shard read replication (extension, docs/SHARDING.md): an
    # OWNER server pushes refreshed values + its shard version for
    # promoted rows to a replica-holding server. Fire-and-forget —
    # no requester waiter exists, so no reply type pairs with it
    # (value inside the server-bound request band).
    Request_ReplicaSync = 4
    # Live elastic resharding (extension, docs/SHARDING.md "Elastic
    # resharding"): all in the server-bound request band so they route
    # to the server actor. ShardData streams a migrating range's rows
    # source→destination (seq-numbered chunks; the FINAL chunk flips
    # the source into its dual-read/forwarding window); ShardAck is
    # the destination's retransmit request for seqs lost in flight;
    # ShardBegin/ShardAbort are the controller's move start/rollback
    # orders; FwdGet is a source-forwarded Get whose piggybacked
    # source-served rows ride the reply as a REPLICA_SLOT group
    # attributed to the source shard (the PR-7 reply contract reused
    # verbatim — no new reply format).
    Request_ShardData = 5
    Request_ShardAck = 6
    Request_ShardBegin = 7
    Request_ShardAbort = 8
    Request_FwdGet = 9
    Request_FwdAdd = 10
    #: LOCAL-ONLY (server actor self-nudge, never on the wire): stream
    #: the next migration chunk, then re-enqueue — serving traffic
    #: interleaves between chunks.
    Server_Shard_Pump = 30
    Reply_Get = -1
    Reply_Add = -2
    Reply_BatchAdd = -3
    Server_Finish_Train = 31
    Control_Barrier = 33
    Control_Reply_Barrier = -33
    Control_Register = 34
    Control_Reply_Register = -34
    # Fault-tolerance control plane (extension — the reference has no
    # failure detection at all, SURVEY.md section 5.3). Heartbeats ride
    # the controller band (>32 routes to the controller actor); the
    # reply and the dead-peer fanout use values below the worker band
    # (<= -33) and are intercepted by name in the communicator's
    # routing (they must NOT fall through to the Zoo mailbox, where a
    # blocked barrier would consume them).
    Control_Heartbeat = 35
    Control_Reply_Heartbeat = -35
    Control_Dead_Peer = -36
    #: Local-only nudge (HeartbeatMonitor -> controller actor, never
    #: on the wire): re-check whether a declared-dead rank has
    #: overstayed -rejoin_grace_s and pending barriers must fail.
    Control_Check_Barriers = 36
    # Hot-shard replication control plane (docs/SHARDING.md): servers
    # report per-row Get rates to the controller (controller band,
    # >32); the controller broadcasts the promoted-row map to every
    # rank with a value below the worker band, intercepted BY NAME in
    # the communicator's routing (like Control_Dead_Peer — it must not
    # fall through to the Zoo mailbox where a blocked barrier would
    # consume it).
    Control_Replica_Report = 37
    Control_Replica_Map = -37
    # Observability control plane (docs/OBSERVABILITY.md): each rank
    # ships its Dashboard/Samples snapshot (+ new trace events) to the
    # controller every -metrics_interval_s. Controller band (>32),
    # fire-and-forget — no reply type pairs with it.
    Control_Metrics = 38
    # Elastic-resharding control plane (docs/SHARDING.md): the
    # migration destination commits (or refuses) a move toward the
    # controller (Shard_Done, re-announced on traffic until the
    # committed map broadcast confirms it landed); applications ask
    # for a respread (Shard_Request, fire-and-forget — callers poll
    # the table's adopted epoch); the controller broadcasts the
    # epoch-stamped map (Shard_Map, below the worker band and
    # intercepted BY NAME in the communicator like
    # Control_Replica_Map — cloned to the worker AND server actors).
    # Shard_Tick is LOCAL-ONLY (HeartbeatMonitor -> controller actor,
    # never on the wire): re-send a possibly-lost Begin, re-broadcast
    # maps, check the in-flight move against declared-dead ranks.
    Control_Shard_Done = 39
    Control_Shard_Request = 40
    Control_Shard_Tick = 41
    Control_Shard_Map = -39
    # Serving-fleet pressure exchange (docs/SERVING.md fleet section):
    # each serving frontend periodically reports its admission stats
    # ([rank, admitted, shed, inflight] int64 blob) to the controller
    # (controller band, >32); the controller answers the reporter with
    # the fleet-aggregate view as a JSON blob (below the worker band,
    # intercepted BY NAME in the communicator's routing like
    # Control_Reply_Heartbeat — it must not fall through to the Zoo
    # mailbox where a blocked barrier would consume it). Both
    # directions ride net.send_async (the liveness-frame discipline —
    # mvlint pass 6).
    Control_Serving_Report = 42
    Control_Reply_Serving = -42
    # Closed-loop self-tuning control plane (runtime/autotune.py,
    # docs/AUTOTUNE.md): the controller's AutotuneManager broadcasts
    # epoch-stamped live-config updates (JSON blob
    # {"epoch": N, "flags": {...}}, every flag declared in
    # util/configure.py TUNABLE_FLAGS) to every rank — below the
    # worker band and intercepted BY NAME in the communicator's
    # routing like Control_Shard_Map (it must not fall through to the
    # Zoo mailbox where a blocked barrier would consume it). The
    # receiving rank acks with Control_Reply_Config (int64
    # [rank, applied_epoch, applied]; the type negation of the
    # broadcast, riding the controller band) so the controller's
    # gauges can show per-rank config convergence. Both directions
    # ride net.send_async (the liveness-frame discipline —
    # mvlint pass 6).
    Control_Reply_Config = 43
    Control_Config = -43
    # Shared-memory transport announce (runtime/shm.py, docs/MEMORY.md
    # "Below the socket"): the sender of a freshly created shm ring
    # segment tells the receiver to attach, carrying int64
    # [nonce, token]. Controller band by VALUE, but intercepted below
    # the communicator (ShmNet.recv consumes it before routing ever
    # sees it) — it rides TCP so it orders after every frame already
    # queued toward the destination, fencing the transport switch.
    Control_Shm_Announce = 44

HEADER_SIZE = 10  # ints (8 in the reference; slot 8 added for
#                   replication, slot 9 for request tracing)


class Message:
    __slots__ = ("header", "data")

    def __init__(self, src: int = -1, dst: int = -1,
                 msg_type: MsgType = MsgType.Default,
                 table_id: int = -1, msg_id: int = -1):
        self.header = [0] * HEADER_SIZE
        self.header[0] = src
        self.header[1] = dst
        self.header[2] = int(msg_type)
        self.header[3] = table_id
        self.header[4] = msg_id
        self.data: List[Blob] = []

    # -- header accessors (ref: message.h:28-38) --
    @property
    def src(self) -> int:
        return self.header[0]

    @src.setter
    def src(self, v: int) -> None:
        self.header[0] = v

    @property
    def dst(self) -> int:
        return self.header[1]

    @dst.setter
    def dst(self, v: int) -> None:
        self.header[1] = v

    @property
    def type(self) -> MsgType:
        return MsgType(self.header[2])

    @type.setter
    def type(self, v: MsgType) -> None:
        self.header[2] = int(v)

    @property
    def table_id(self) -> int:
        return self.header[3]

    @table_id.setter
    def table_id(self, v: int) -> None:
        self.header[3] = v

    @property
    def msg_id(self) -> int:
        return self.header[4]

    @msg_id.setter
    def msg_id(self, v: int) -> None:
        self.header[4] = v

    @property
    def type_int(self) -> int:
        """The raw type header int. Unlike ``.type`` this never raises
        on a value outside ``MsgType`` (a newer peer's message type must
        be loggable/routable as a plain int, not a ValueError) — actor
        dispatch and wire routing read this."""
        return self.header[2]

    def push(self, blob) -> None:
        if not isinstance(blob, Blob):
            blob = Blob(np.ascontiguousarray(blob))
        self.data.append(blob)

    def text_payload(self, index: int = 0,
                     errors: str = "replace") -> str:
        """UTF-8 text of payload blob ``index``, decoded straight from
        the blob's uint8 view — no intermediate ``bytes(...)`` copy.
        THE reader for every JSON/error-text payload on the wire
        (error replies, serving-fleet aggregates, Control_Config
        broadcasts, metrics snapshots): one helper instead of five
        scattered ``bytes(blob.as_array(np.uint8)).decode()`` sites,
        and the one place the decode policy (``errors``) lives."""
        arr = np.ascontiguousarray(self.data[index].as_array(np.uint8))
        return str(memoryview(arr), "utf-8", errors)

    def size(self) -> int:
        return len(self.data)

    def create_reply_message(self) -> "Message":
        """Reply with src/dst swapped and type negated (ref: message.h:51-59)."""
        reply = Message(src=self.dst, dst=self.src,
                        msg_type=MsgType(-self.header[2]),
                        table_id=self.table_id, msg_id=self.msg_id)
        # The reply leg belongs to the same sampled request: carrying
        # the trace id back lets the requester's rank pair reply-side
        # spans under one trace (0 = unsampled, the common case).
        reply.header[TRACE_SLOT] = self.header[TRACE_SLOT]
        return reply

    def __repr__(self) -> str:
        return (f"Message(src={self.src}, dst={self.dst}, type={self.type.name}, "
                f"table={self.table_id}, msg_id={self.msg_id}, blobs={len(self.data)})")


# Header slot 5 carries an error flag on replies (0 = ok). The reference
# leaves slots 5-7 unused (message.h:28-38); using one lets a server-side
# failure travel back to the requester instead of degrading to an empty
# reply, so the caller's wait() can raise rather than return garbage.
ERROR_SLOT = 5


def mark_error(reply: "Message", exc: BaseException) -> None:
    """Flag a reply as failed and replace its payload with the error text
    (utf-8 bytes in a single blob)."""
    reply.header[ERROR_SLOT] = 1
    text = f"{type(exc).__name__}: {exc}".encode(errors="replace")
    reply.data = [Blob(np.frombuffer(text, np.uint8).copy())]


def take_error(msg: "Message") -> Optional[str]:
    """The error text of a failed reply, or None for a normal one."""
    if msg.header[ERROR_SLOT] == 0:
        return None
    if msg.data:
        return msg.text_payload()
    return "remote table operation failed"


#: Marker carried inside error-reply text when the failure is a LOST
#: PEER rather than table logic: the wire to the serving rank broke, or
#: the controller declared it dead. Requests failed this way are
#: RETRYABLE (the peer may restart and rejoin) — ``WorkerTable.wait``
#: raises ``PeerLostError`` instead of ``TableRequestError`` when the
#: recorded error carries this marker, and the sync-call retry loop
#: keys off that type. Travels as plain text so it survives the
#: mark_error/take_error round trip unchanged across builds.
PEER_LOST_MARK = "[peer-lost]"


# Header slot 6 marks a codec-encoded payload (see util/wire_codec.py):
# the communicator's filter stage sets it on encode and the receive path
# decodes before routing, so frames stay self-describing on the wire.
CODEC_SLOT = 6


def is_wire_encoded(msg: "Message") -> bool:
    return bool(msg.header[CODEC_SLOT])


# Header slot 7 carries the serving table shard's VERSION on replies
# (client-cache staleness tracking, tables/client_cache.py): servers
# bump a per-shard counter once per applied Add and stamp every reply.
# The wire value is version+1 so that 0 — the header default, and what
# a pre-version peer sends — reads as "unstamped" (-1), never as a real
# version.
VERSION_SLOT = 7


#: WIRE-SLOT REGISTRY — the single source of truth for the reserved
#: header slots (5-7). Everything outside this module must index
#: ``msg.header`` through these names (or the 0-4 property accessors),
#: never a raw int literal: ``tools/mvlint``'s wire-slot pass enforces
#: that, and cross-checks this literal against the slot table in
#: ``docs/WIRE_FORMAT.md`` so the doc cannot silently drift from the
#: wire. Keep the values literal (the lint parses, it does not import).
WIRE_SLOTS: dict = {
    "ERROR_SLOT": 5,
    "CODEC_SLOT": 6,
    "VERSION_SLOT": 7,
    "REPLICA_SLOT": 8,
    "TRACE_SLOT": 9,
}

assert ERROR_SLOT == WIRE_SLOTS["ERROR_SLOT"]
assert CODEC_SLOT == WIRE_SLOTS["CODEC_SLOT"]
assert VERSION_SLOT == WIRE_SLOTS["VERSION_SLOT"]


# Header slot 8 marks a Get reply that carries REPLICA-SERVED rows
# (hot-shard read replication, docs/SHARDING.md): the wire value is
# n_replica_rows + 1 (0 = header default = no replica content, the only
# value pre-replication builds ever send). A marked reply's LAST payload
# blob is an int32 replica descriptor
#   [n_groups, (owner_sid, floor_version+1, n_rows) * n_groups]
# and the reply's key vector is ordered [owned rows..., group 0 rows...,
# group n-1 rows...]: the serving server attributes each replica group
# to the shard that OWNS the rows, with the group's version floor (the
# oldest owner version among the served rows). Growing the header from
# 8 to 9 ints is a declared WIRE BREAK for mixed-build clusters
# (docs/WIRE_FORMAT.md).
REPLICA_SLOT = 8

assert REPLICA_SLOT == WIRE_SLOTS["REPLICA_SLOT"]


def mark_replica_reply(reply: "Message", n_replica_rows: int) -> None:
    reply.header[REPLICA_SLOT] = int(n_replica_rows) + 1


def replica_row_count(msg: "Message") -> int:
    """Replica-served rows a Get reply carries (0 = none / pre-replica
    peer)."""
    raw = int(msg.header[REPLICA_SLOT])
    return raw - 1 if raw > 0 else 0


# Header slot 9 carries the DISTRIBUTED TRACE ID of a sampled request
# (util/tracing.py, docs/OBSERVABILITY.md): 0 — the header default, and
# the only value a -trace_sample_rate=0 build (or a pre-trace peer)
# ever sends — means "unsampled"; a nonzero id is carried verbatim on
# every shard/batch/reply message the request spawns so span events
# recorded on different ranks pair under one trace. Growing the header
# from 9 to 10 ints is a declared WIRE BREAK for mixed-build TCP
# clusters (docs/WIRE_FORMAT.md), the same class as the PR-7 slot-8
# bump.
TRACE_SLOT = 9

assert TRACE_SLOT == WIRE_SLOTS["TRACE_SLOT"]


def stamp_trace(msg: "Message", trace_id: int) -> None:
    msg.header[TRACE_SLOT] = int(trace_id)


def trace_of(msg: "Message") -> int:
    """The trace id a message carries (0 = unsampled / pre-trace
    peer)."""
    return int(msg.header[TRACE_SLOT])


def stamp_version(reply: "Message", version: int) -> None:
    reply.header[VERSION_SLOT] = int(version) + 1


def reply_version(msg: "Message") -> int:
    """The shard version stamped on a reply, or -1 when the peer didn't
    stamp one (legacy build / error reply)."""
    return int(msg.header[VERSION_SLOT]) - 1


# -- Add coalescing (Request_BatchAdd / Reply_BatchAdd) --
#
# Batch request layout: blob 0 is an int32 descriptor
#   [n_sub, table_id_0, msg_id_0, n_blobs_0, ..., table_id_{n-1}, ...]
# followed by every sub-message's blobs in order. Batch reply layout:
# blob 0 is int32 [n_sub, table_id_0, msg_id_0, err_0, version_0, ...]
# followed by one utf-8 error-text blob per err_i != 0 (in sub order);
# version_i is the shard version after the sub was applied (-1 when the
# server could not resolve the table), the batched twin of the
# VERSION_SLOT stamp on per-message replies.

def pack_add_batch(subs: List["Message"]) -> "Message":
    """Coalesce several Request_Add shard messages (same src, same dst)
    into one Request_BatchAdd wire message."""
    first = subs[0]
    batch = Message(src=first.src, dst=first.dst,
                    msg_type=MsgType.Request_BatchAdd)
    for sub in subs:
        # The batch inherits the first SAMPLED sub's trace id: a trace
        # that lands in a coalesced flush keeps its wire spans (the
        # batch is that sub's wire message; sibling sampled subs are
        # attributed by their own issue/reply spans).
        if sub.header[TRACE_SLOT]:
            batch.header[TRACE_SLOT] = sub.header[TRACE_SLOT]
            break
    desc = [len(subs)]
    for sub in subs:
        desc.extend((sub.table_id, sub.msg_id, len(sub.data)))
    batch.push(Blob(np.asarray(desc, dtype=np.int32)))
    for sub in subs:
        batch.data.extend(sub.data)
    return batch


def unpack_add_batch(batch: "Message") -> List["Message"]:
    """Reverse ``pack_add_batch`` into per-table Request_Add messages."""
    desc = batch.data[0].as_array(np.int32)
    n = int(desc[0])
    subs: List[Message] = []
    off = 1
    blob_off = 1
    for _ in range(n):
        table_id, msg_id, n_blobs = (int(v) for v in desc[off:off + 3])
        off += 3
        sub = Message(src=batch.src, dst=batch.dst,
                      msg_type=MsgType.Request_Add,
                      table_id=table_id, msg_id=msg_id)
        sub.data = list(batch.data[blob_off:blob_off + n_blobs])
        blob_off += n_blobs
        subs.append(sub)
    if blob_off != len(batch.data):
        raise ValueError(
            f"batch add: descriptor claims {blob_off - 1} blobs, "
            f"message carries {len(batch.data) - 1}")
    return subs


def is_server_bound(msg_type: int) -> bool:
    """Request types route to the server actor (ref: communicator.cpp:93-105)."""
    return 0 < msg_type < 32


def is_worker_bound(msg_type: int) -> bool:
    """Reply types route to the worker actor."""
    return -32 < msg_type < 0


def is_controller_bound(msg_type: int) -> bool:
    """Control requests route to the controller actor."""
    return msg_type > 32
