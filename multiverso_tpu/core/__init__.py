from .blob import Blob, typed_blob
from .message import HEADER_SIZE, Message, MsgType
from .node import Node, Role, is_server, is_worker, role_from_string

__all__ = [
    "Blob", "typed_blob", "HEADER_SIZE", "Message", "MsgType",
    "Node", "Role", "is_server", "is_worker", "role_from_string",
]
