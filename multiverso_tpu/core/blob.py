"""Shared byte buffer with typed views.

TPU-native equivalent of the reference's ``Blob``
(ref: include/multiverso/blob.h:13-53, src/blob.cpp:8-46). The reference is
a ref-counted byte chunk whose copies share memory and whose ``As<T>(i)``
reinterpret-casts. In Python the natural carrier is a numpy array: numpy
views already give zero-copy sharing with refcounting (the Allocator/refcount
machinery of the reference collapses into CPython's GC), and ``as_array``
gives the reinterpret-cast view. A Blob can also wrap a ``jax.Array``
lazily — device blobs defer transfer until host bytes are demanded, which is
what lets table replies stay on-device end to end.

Two zero-copy carrier forms beyond the plain host array
(docs/MEMORY.md):

- **parted** (``Blob.from_parts``): the payload is the concatenation of
  several buffers that are never joined on the send side — the
  scatter-gather framer (``tcp.serialize_views``) reads each part as its
  own vectored-write view, so a codec frame's ``(header, payload)`` pair
  crosses the wire without the ``head + payload.tobytes()`` concat copy.
  Materialized (one concatenate) only if something demands the flat
  payload locally.
- **pool-backed** (``Blob.from_lease``): a READ-ONLY view into a leased
  receive-frame buffer (``util/buffer_pool.py``). The lease rides the
  Blob; when the last Blob cut from a frame dies, the frame returns to
  the pool. Pool views must never be written — a recycled buffer would
  be scribbled — so mutation raises and the rare consumer that needs a
  writable payload calls ``materialize()`` first (copy-on-write).
"""

from __future__ import annotations

from typing import Any, List

import numpy as np


def is_device_array(x: Any) -> bool:
    """True for jax.Array-like payloads (duck-typed so core stays
    jax-import-free)."""
    return not isinstance(x, np.ndarray) and hasattr(x, "addressable_shards")


class Blob:
    # Slot order matters for the pool: on deallocation CPython clears
    # slots in definition order, so the payload view (_data) drops its
    # buffer export before the lease's __del__ probes the frame for
    # reuse — the common single-owner case re-pools immediately instead
    # of parking on the pending list.
    __slots__ = ("_data", "_parts", "_lease")

    def __init__(self, data: Any = None, size: int = None):
        """Wrap existing data (zero-copy for numpy/bytes/memoryview
        inputs) or allocate.

        ``Blob(size=n)`` allocates ``n`` bytes; ``Blob(array)`` wraps.
        """
        self._parts = None
        self._lease = None
        if data is None:
            if size is None:
                raise ValueError("Blob needs data or size")
            self._data = np.zeros(size, dtype=np.uint8)
        elif isinstance(data, Blob):
            # Shallow share, like the reference copy-ctor: payload,
            # pending parts and frame lease all ride along.
            self._data = data._data
            self._parts = data._parts
            self._lease = data._lease
        elif isinstance(data, np.ndarray):
            # Zero-copy only holds for contiguous input; a non-contiguous
            # array is copied here so as_array views stay writable+attached.
            self._data = np.ascontiguousarray(data)
        elif isinstance(data, bytes):
            # Zero-copy wrap: bytes is immutable, so the view is
            # read-only and can alias the caller's object safely
            # (the old frombuffer(bytes(..)).copy() paid two copies).
            self._data = np.frombuffer(data, dtype=np.uint8)
        elif isinstance(data, memoryview):
            # Zero-copy wrap; writability (and the no-alias discipline)
            # is the caller's — the wire path hands out read-only
            # pool views through from_lease, never through here.
            self._data = np.frombuffer(data, dtype=np.uint8)
        elif isinstance(data, bytearray):
            # ONE copy (down from two): the caller may keep mutating
            # its bytearray, so aliasing it would let later writes
            # bleed into the blob.
            self._data = np.frombuffer(data, dtype=np.uint8).copy()
        else:
            # jax.Array and anything else exposing __array__ kept as-is;
            # converted to host bytes only on demand.
            self._data = data

    @classmethod
    def from_parts(cls, parts: List[Any]) -> "Blob":
        """Scatter-gather blob: the payload is the concatenation of
        ``parts`` (bytes / contiguous arrays), kept separate so
        ``wire_views`` can hand each to a vectored write with no join
        copy. Anything that needs the flat payload (``data``,
        ``as_array``) materializes it lazily — once."""
        blob = cls.__new__(cls)
        blob._data = None
        blob._lease = None
        norm = []
        for part in parts:
            if isinstance(part, np.ndarray):
                norm.append(np.ascontiguousarray(part)
                            .view(np.uint8).reshape(-1))
            else:
                norm.append(np.frombuffer(part, dtype=np.uint8))
        blob._parts = norm
        return blob

    @classmethod
    def from_lease(cls, view: np.ndarray, lease: Any) -> "Blob":
        """Pool-backed blob: ``view`` is a (read-only) uint8 view into a
        leased receive-frame buffer; the blob keeps ``lease`` alive so
        the frame cannot be recycled under it (util/buffer_pool.py)."""
        blob = cls.__new__(cls)
        blob._data = view
        blob._parts = None
        blob._lease = lease
        return blob

    @property
    def data(self) -> Any:
        if self._parts is not None:
            self._materialize_parts()
        return self._data

    @property
    def pool_backed(self) -> bool:
        """True while the payload views a pooled receive frame (and is
        therefore read-only; see ``materialize``)."""
        return self._lease is not None

    def _materialize_parts(self) -> None:
        parts = self._parts
        self._data = parts[0] if len(parts) == 1 \
            else np.concatenate(parts)
        self._parts = None

    @property
    def on_device(self) -> bool:
        """True when the payload is a device array (jax.Array) that has not
        been materialized to host bytes. Device blobs flow through the PS
        stack with zero host copies."""
        return self._parts is None and is_device_array(self._data)

    def typed(self, dtype=np.float32) -> Any:
        """Typed payload without forcing a host transfer: the device array
        itself when on device, else the host view."""
        return self._data if self.on_device else self.as_array(dtype)

    def _host(self) -> np.ndarray:
        if self._parts is not None:
            self._materialize_parts()
        if not isinstance(self._data, np.ndarray):
            self._data = np.asarray(self._data)
        return self._data

    @property
    def size(self) -> int:
        """Size in bytes (the reference's ``size()``). Computed from
        shape/dtype for device payloads — materializing here would silently
        defeat the zero-copy device path — and summed over pending parts
        for scatter-gather blobs."""
        if self._parts is not None:
            return sum(p.nbytes for p in self._parts)
        if self.on_device:
            return int(np.prod(self._data.shape)) \
                * np.dtype(self._data.dtype).itemsize
        return self._host().nbytes

    def count(self, dtype=np.float32) -> int:
        """Element count under a typed view (the reference's ``size<T>()``)."""
        return self.size // np.dtype(dtype).itemsize

    def as_array(self, dtype=np.float32) -> np.ndarray:
        """Typed zero-copy view (the reference's ``As<T>``). Pool-backed
        payloads yield READ-ONLY views — ``materialize()`` first for a
        writable private copy (the copy-on-write contract,
        docs/MEMORY.md)."""
        arr = self._host()
        if arr.dtype == np.dtype(dtype) and arr.ndim == 1:
            return arr
        return arr.reshape(-1).view(dtype)

    def materialize(self) -> "Blob":
        """Copy-on-write escape hatch: replace a pool-backed (or
        otherwise read-only) payload with a private writable copy and
        drop the frame lease, so the buffer can recycle. The few wire
        consumers that mutate a received payload in place call this
        once; everything else reads through the zero-copy view."""
        arr = self._host()
        if self._lease is not None or not arr.flags.writeable:
            self._data = arr.copy()
        self._lease = None
        return self

    def wire_bytes(self) -> np.ndarray:
        """Flat uint8 view of the payload for wire serialization
        (materializes device arrays — this IS the host boundary). The
        single place the byte layout of an outgoing blob is defined:
        the TCP framer and the wire-codec filter both read through it,
        so a filtered and an unfiltered serialization path cannot
        disagree on what the raw bytes are."""
        arr = np.asarray(self.data)
        return np.ascontiguousarray(arr).view(np.uint8).reshape(-1)

    def wire_views(self) -> List[memoryview]:
        """The payload as buffer views for scatter-gather serialization
        (``tcp.serialize_views``): one view per pending part — never
        joined — or a single view of the flat payload. Zero-copy for
        host payloads; device arrays materialize exactly as in
        ``wire_bytes``."""
        if self._parts is not None:
            return [memoryview(p) for p in self._parts]
        return [memoryview(self.wire_bytes())]

    def __getitem__(self, i: int) -> int:
        return int(self._host().reshape(-1).view(np.uint8)[i])

    def copy(self) -> "Blob":
        """Deep copy (the reference's CopyFrom)."""
        return Blob(self._host().copy())

    def __len__(self) -> int:
        return self.size


def typed_blob(arr: np.ndarray) -> Blob:
    """Wrap a typed array as a Blob without byte-flattening."""
    return Blob(np.ascontiguousarray(arr))
