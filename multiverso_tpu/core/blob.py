"""Shared byte buffer with typed views.

TPU-native equivalent of the reference's ``Blob``
(ref: include/multiverso/blob.h:13-53, src/blob.cpp:8-46). The reference is
a ref-counted byte chunk whose copies share memory and whose ``As<T>(i)``
reinterpret-casts. In Python the natural carrier is a numpy array: numpy
views already give zero-copy sharing with refcounting (the Allocator/refcount
machinery of the reference collapses into CPython's GC), and ``as_array``
gives the reinterpret-cast view. A Blob can also wrap a ``jax.Array``
lazily — device blobs defer transfer until host bytes are demanded, which is
what lets table replies stay on-device end to end.
"""

from __future__ import annotations

from typing import Any

import numpy as np


def is_device_array(x: Any) -> bool:
    """True for jax.Array-like payloads (duck-typed so core stays
    jax-import-free)."""
    return not isinstance(x, np.ndarray) and hasattr(x, "addressable_shards")


class Blob:
    __slots__ = ("_data",)

    def __init__(self, data: Any = None, size: int = None):
        """Wrap existing data (zero-copy for numpy inputs) or allocate.

        ``Blob(size=n)`` allocates ``n`` bytes; ``Blob(array)`` wraps.
        """
        if data is None:
            if size is None:
                raise ValueError("Blob needs data or size")
            self._data = np.zeros(size, dtype=np.uint8)
        elif isinstance(data, Blob):
            self._data = data._data  # shallow share, like ref copy-ctor
        elif isinstance(data, np.ndarray):
            # Zero-copy only holds for contiguous input; a non-contiguous
            # array is copied here so as_array views stay writable+attached.
            self._data = np.ascontiguousarray(data)
        elif isinstance(data, (bytes, bytearray, memoryview)):
            self._data = np.frombuffer(bytes(data), dtype=np.uint8).copy()
        else:
            # jax.Array and anything else exposing __array__ kept as-is;
            # converted to host bytes only on demand.
            self._data = data

    @property
    def data(self) -> Any:
        return self._data

    @property
    def on_device(self) -> bool:
        """True when the payload is a device array (jax.Array) that has not
        been materialized to host bytes. Device blobs flow through the PS
        stack with zero host copies."""
        return is_device_array(self._data)

    def typed(self, dtype=np.float32) -> Any:
        """Typed payload without forcing a host transfer: the device array
        itself when on device, else the host view."""
        return self._data if self.on_device else self.as_array(dtype)

    def _host(self) -> np.ndarray:
        if not isinstance(self._data, np.ndarray):
            self._data = np.asarray(self._data)
        return self._data

    @property
    def size(self) -> int:
        """Size in bytes (the reference's ``size()``). Computed from
        shape/dtype for device payloads — materializing here would silently
        defeat the zero-copy device path."""
        if self.on_device:
            return int(np.prod(self._data.shape)) \
                * np.dtype(self._data.dtype).itemsize
        return self._host().nbytes

    def count(self, dtype=np.float32) -> int:
        """Element count under a typed view (the reference's ``size<T>()``)."""
        return self.size // np.dtype(dtype).itemsize

    def as_array(self, dtype=np.float32) -> np.ndarray:
        """Typed zero-copy view (the reference's ``As<T>``)."""
        arr = self._host()
        if arr.dtype == np.dtype(dtype) and arr.ndim == 1:
            return arr
        return arr.reshape(-1).view(dtype)

    def wire_bytes(self) -> np.ndarray:
        """Flat uint8 view of the payload for wire serialization
        (materializes device arrays — this IS the host boundary). The
        single place the byte layout of an outgoing blob is defined:
        the TCP framer and the wire-codec filter both read through it,
        so a filtered and an unfiltered serialization path cannot
        disagree on what the raw bytes are."""
        arr = np.asarray(self._data)
        return np.ascontiguousarray(arr).view(np.uint8).reshape(-1)

    def __getitem__(self, i: int) -> int:
        return int(self._host().reshape(-1).view(np.uint8)[i])

    def copy(self) -> "Blob":
        """Deep copy (the reference's CopyFrom)."""
        return Blob(self._host().copy())

    def __len__(self) -> int:
        return self.size


def typed_blob(arr: np.ndarray) -> Blob:
    """Wrap a typed array as a Blob without byte-flattening."""
    return Blob(np.ascontiguousarray(arr))
