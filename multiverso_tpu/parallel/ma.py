"""Model-average (MA) mode: the PS-bypass training path.

The reference's ``-ma`` flag skips the parameter server entirely and the
app calls ``MV_Aggregate`` (MPI allreduce) on its parameter buffer each
step (ref: src/zoo.cpp:49, src/multiverso.cpp:53-56,
Test/test_allreduce.cpp:10-19). On TPU the equivalent has two layers:

- control plane (host, cross-rank): ``model_average`` — transport
  allreduce of a host array divided by the worker count — plus its
  overlapped form: ``model_average_async`` / ``MAAverager`` stream the
  allreduce of step i's parameters chunk-by-chunk on the transport's
  writer threads while step i+1's local compute runs on device, with
  the ``MA_COMM_STALL`` dashboard monitor recording only the time the
  trainer actually blocked (the sync path's whole duration is a stall;
  the async path's stall is the residual after compute hid the rest);
- data plane (device mesh): ``MASGDStep`` — one jitted SPMD step where each
  device computes gradients on its microbatch and ``lax.pmean`` merges them
  over ICI, which is the collapsed form of train-locally-then-average.
"""

from __future__ import annotations

import functools
import threading
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
try:  # jax >= 0.4.31 exports it at the top level
    from jax import shard_map
except ImportError:  # older jax: the experimental module is the API
    from jax.experimental.shard_map import shard_map

from ..runtime import thread_roles
from ..runtime.zoo import current_zoo
from ..sharding import mesh as meshlib
from ..util.dashboard import monitor


def model_average(data: np.ndarray, zoo=None) -> np.ndarray:
    """Cross-rank parameter average: allreduce / num_ranks
    (ref usage: binding apps divide MV_Aggregate output by worker count).
    Blocking — the whole wall time is communication the caller could
    not hide, so it all lands on the MA_COMM_STALL monitor (the async
    path below only charges its residual wait there). Collectives are
    FIFO-ordered per endpoint inside ``net.allreduce``, so mixing this
    with ``model_average_async`` (or ``mv.aggregate``) keeps them
    paired positionally across ranks."""
    zoo = zoo if zoo is not None else current_zoo()
    with monitor("MA_COMM_STALL"):
        total = zoo.net.allreduce(np.asarray(data))
    return total / zoo.net.size


class MAFuture:
    """Handle for one in-flight background model average."""

    def __init__(self) -> None:
        self._event = threading.Event()
        self._result: Optional[np.ndarray] = None
        self._error: Optional[BaseException] = None

    def _set(self, result: np.ndarray) -> None:
        self._result = result
        self._event.set()

    def _set_error(self, exc: BaseException) -> None:
        self._error = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """The averaged array; blocks until the background allreduce
        lands. Only the BLOCKED time is charged to MA_COMM_STALL — a
        call after the collective already finished records ~0, which is
        exactly the overlap win being measured."""
        if not self._event.is_set():
            with monitor("MA_COMM_STALL"):
                if not self._event.wait(timeout=timeout):
                    raise TimeoutError(
                        "model_average_async: collective did not "
                        f"complete within {timeout}s")
        if self._error is not None:
            raise RuntimeError(
                "model_average_async failed in background") from self._error
        return self._result

    wait = result


def model_average_async(data: np.ndarray, zoo=None, *,
                        copy: bool = True) -> MAFuture:
    """Start a cross-rank parameter average in a background thread and
    return immediately.

    The input is snapshotted (``copy=False`` skips that for callers
    that hand over a buffer they will not touch again, e.g.
    ``MAAverager`` passing its own private snapshot), so the caller
    keeps training on its live buffer while the allreduce streams on
    the transport's writer threads. Submissions execute in CALL order:
    the endpoint's FIFO slot is reserved HERE on the calling thread
    and the worker runs its collective in that slot — without this,
    two freshly spawned workers could enter the endpoint in swapped
    order on one rank only, cross-pairing same-generation collectives
    across ranks. Every rank must still start the SAME averages in the
    SAME order (they are matched positionally, as with the blocking
    form)."""
    zoo = zoo if zoo is not None else current_zoo()
    snapshot = np.array(data, copy=True) if copy else np.asarray(data)
    future = MAFuture()
    slot = zoo.net.reserve_collective_slot()

    def run() -> None:
        try:
            future._set(zoo.net.allreduce(snapshot, slot=slot)
                        / zoo.net.size)
        except BaseException as exc:  # noqa: BLE001 - delivered to result()
            future._set_error(exc)

    try:
        thread_roles.spawn(thread_roles.BACKGROUND, target=run,
                           name=f"mv-ma-avg-r{zoo.net.rank}")
    except BaseException:
        # The reserved slot must not leak: an unserved ticket would
        # block every later collective on this endpoint forever. Serve
        # it in turn as a no-op (waits for predecessors, then advances
        # the line) before re-raising the spawn failure.
        zoo.net._run_collective(lambda: None, slot)
        raise
    return future


class MAAverager:
    """Double-buffered model averaging: one average in flight while the
    trainer computes the next block.

    Protocol (both modes apply the average at the SAME point, so a sync
    and an overlapped run are bit-identical when ``-allreduce_lossy``
    is off — only where the wall-clock stall lands differs):

        submit(params_i)        # allreduce starts streaming
        ... train block i+1 ...     # device compute hides the wire
        avg = collect(current=params_now)
        # avg + (params_now - params_i): the cross-rank average plus
        # the local progress made while it streamed (BMUF-style block
        # continuation, degenerating to plain averaging when collect
        # follows submit immediately)
    """

    def __init__(self, zoo=None):
        self._zoo = zoo if zoo is not None else current_zoo()
        self._future: Optional[MAFuture] = None
        self._snapshot: Optional[np.ndarray] = None

    @property
    def busy(self) -> bool:
        return self._future is not None

    def submit(self, data: np.ndarray) -> MAFuture:
        if self._future is not None:
            raise RuntimeError(
                "MAAverager: collect() the in-flight average before "
                "submitting the next one (double-buffer depth is 1)")
        self._snapshot = np.array(data, copy=True)
        # copy=False: the snapshot above is already private to this
        # averager (it is only read again in collect's delta), so a
        # second O(model) copy inside the async submit would be waste.
        self._future = model_average_async(self._snapshot, self._zoo,
                                           copy=False)
        return self._future

    def collect(self, current: Optional[np.ndarray] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        """Block for the in-flight average (residual wait lands on
        MA_COMM_STALL). With ``current``, returns the average corrected
        by the local progress since ``submit``; bare, returns the
        average itself."""
        if self._future is None:
            raise RuntimeError("MAAverager: nothing submitted")
        # Resolve BEFORE clearing state: a timeout must leave the
        # averager busy (the collective is still in flight and peers
        # WILL apply it), so the caller can retry collect() instead of
        # silently diverging from the other replicas.
        avg = self._future.result(timeout=timeout)
        snapshot = self._snapshot
        self._future = None
        self._snapshot = None
        if current is None:
            return avg
        return avg + (np.asarray(current) - snapshot)


def sharded_model_average(data: np.ndarray, zoo=None) -> np.ndarray:
    """Blocking cross-rank average through the sharded collective:
    reduce-scatter of sparse codec frames, shard-local divide,
    allgather (``net.sharded_average``). Same MA_COMM_STALL accounting
    and positional-matching contract as ``model_average``."""
    zoo = zoo if zoo is not None else current_zoo()
    with monitor("MA_COMM_STALL"):
        return zoo.net.sharded_average(np.asarray(data))


def sharded_model_average_async(data: np.ndarray, zoo=None, *,
                                copy: bool = True) -> MAFuture:
    """``model_average_async`` over the sharded collective: snapshots
    the input (unless ``copy=False`` hands over a private buffer),
    reserves the endpoint's FIFO slot on the calling thread, and
    resolves the future with the averaged array — the divide already
    applied shard-locally inside the collective."""
    zoo = zoo if zoo is not None else current_zoo()
    snapshot = np.array(data, copy=True) if copy else np.asarray(data)
    future = MAFuture()
    slot = zoo.net.reserve_collective_slot()

    def run() -> None:
        try:
            future._set(zoo.net.sharded_average(snapshot, slot=slot))
        except BaseException as exc:  # noqa: BLE001 - delivered to result()
            future._set_error(exc)

    try:
        thread_roles.spawn(thread_roles.BACKGROUND, target=run,
                           name=f"mv-ma-shavg-r{zoo.net.rank}")
    except BaseException:
        # Serve the reserved ticket as a no-op before re-raising, or
        # every later collective on this endpoint blocks forever.
        zoo.net._run_collective(lambda: None, slot)
        raise
    return future


class MAShardedAverager(MAAverager):
    """Delta-vs-last-average MA over the sharded sparse collective.

    ``MAAverager`` ships the FULL parameter buffer every round — dense
    by construction, so the wire codec can never shrink it. This
    variant keeps a reference copy of the last cross-rank average
    (bit-identical on every rank, since it is rebuilt from collective
    results) and ships only ``params - reference``: once training
    localizes, most entries are exactly zero and the delta rides the
    codec's sparse index+value streams through
    ``net.sharded_average`` — reduce-scatter of sparse frames,
    shard-local divide, allgather (docs/ALLREDUCE.md).

    Round protocol (same call points as ``MAAverager``, so
    ``MACorpusTrainer`` swaps it in unchanged and sync/overlap runs
    stay bit-identical):

        submit(params_i):  delta_i = params_i - ref   (ref None on the
                           first round: the delta IS params_i and ref
                           starts at the first average — dense once,
                           exact regardless of how far replicas have
                           already diverged)
        collect(current):  ref += mean(delta)  (identical on all ranks)
                           returns ref + (current - params_i)

    Memory: one extra full-size reference buffer per rank (constant in
    world size); the collective itself holds only a 1/world shard of
    reduce state."""

    def __init__(self, zoo=None):
        super().__init__(zoo)
        self._ref: Optional[np.ndarray] = None

    def submit(self, data: np.ndarray) -> MAFuture:
        if self._future is not None:
            raise RuntimeError(
                "MAShardedAverager: collect() the in-flight average "
                "before submitting the next one (double-buffer depth "
                "is 1)")
        self._snapshot = np.array(data, dtype=np.float32, copy=True)
        delta = self._snapshot if self._ref is None \
            else self._snapshot - self._ref
        # copy=False: the snapshot (and therefore the first-round
        # delta) is already private to this averager, and a fresh
        # ``snapshot - ref`` array is private too.
        self._future = sharded_model_average_async(delta, self._zoo,
                                                   copy=False)
        return self._future

    def collect(self, current: Optional[np.ndarray] = None,
                timeout: Optional[float] = None) -> np.ndarray:
        if self._future is None:
            raise RuntimeError("MAShardedAverager: nothing submitted")
        # Resolve BEFORE clearing state: a timeout must leave the
        # averager busy and the reference untouched (peers WILL apply
        # this round), so the caller can retry collect().
        avg_delta = self._future.result(timeout=timeout)
        snapshot = self._snapshot
        self._future = None
        self._snapshot = None
        self._ref = avg_delta if self._ref is None \
            else self._ref + avg_delta
        if current is None:
            # Copy: the reference must stay pristine — it is the
            # shared baseline every rank's next delta subtracts.
            return self._ref.copy()
        return self._ref + (np.asarray(current) - snapshot)


class MASGDStep:
    """Data-parallel SGD step over the device mesh.

    ``loss_fn(params, batch) -> scalar``; batches arrive with the leading
    axis split over the mesh. One jit: forward, backward, pmean(grads)
    over ICI, SGD update. Params stay replicated; the collective is the
    only cross-device traffic — the TPU-native fusion of Multiverso's
    train-then-MV_Aggregate loop.
    """

    def __init__(self, loss_fn: Callable, mesh=None, lr: float = 0.01):
        self.mesh = mesh if mesh is not None else meshlib.local_mesh()
        self.lr = lr
        axes = tuple(self.mesh.axis_names)

        def device_step(params, batch, lr_arr):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axes), grads)
            loss = jax.lax.pmean(loss, axes)
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr_arr * g, params, grads)
            return new_params, loss

        batch_spec = P(axes)
        self._step = jax.jit(shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(), batch_spec, P()),
            out_specs=(P(), P()),
        ), donate_argnums=(0,))

    def __call__(self, params, batch):
        lr_arr = jnp.asarray(self.lr, dtype=jnp.float32)
        params, loss = self._step(params, batch, lr_arr)
        return params, float(np.asarray(loss))
