"""Model-average (MA) mode: the PS-bypass training path.

The reference's ``-ma`` flag skips the parameter server entirely and the
app calls ``MV_Aggregate`` (MPI allreduce) on its parameter buffer each
step (ref: src/zoo.cpp:49, src/multiverso.cpp:53-56,
Test/test_allreduce.cpp:10-19). On TPU the equivalent has two layers:

- control plane (host, cross-rank): ``model_average`` — transport
  allreduce of a host array divided by the worker count;
- data plane (device mesh): ``MASGDStep`` — one jitted SPMD step where each
  device computes gradients on its microbatch and ``lax.pmean`` merges them
  over ICI, which is the collapsed form of train-locally-then-average.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
try:  # jax >= 0.4.31 exports it at the top level
    from jax import shard_map
except ImportError:  # older jax: the experimental module is the API
    from jax.experimental.shard_map import shard_map

from ..runtime.zoo import current_zoo
from ..sharding import mesh as meshlib


def model_average(data: np.ndarray, zoo=None) -> np.ndarray:
    """Cross-rank parameter average: allreduce / num_ranks
    (ref usage: binding apps divide MV_Aggregate output by worker count)."""
    zoo = zoo if zoo is not None else current_zoo()
    total = zoo.net.allreduce(np.asarray(data))
    return total / zoo.net.size


class MASGDStep:
    """Data-parallel SGD step over the device mesh.

    ``loss_fn(params, batch) -> scalar``; batches arrive with the leading
    axis split over the mesh. One jit: forward, backward, pmean(grads)
    over ICI, SGD update. Params stay replicated; the collective is the
    only cross-device traffic — the TPU-native fusion of Multiverso's
    train-then-MV_Aggregate loop.
    """

    def __init__(self, loss_fn: Callable, mesh=None, lr: float = 0.01):
        self.mesh = mesh if mesh is not None else meshlib.local_mesh()
        self.lr = lr
        axes = tuple(self.mesh.axis_names)

        def device_step(params, batch, lr_arr):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            grads = jax.tree_util.tree_map(
                lambda g: jax.lax.pmean(g, axes), grads)
            loss = jax.lax.pmean(loss, axes)
            new_params = jax.tree_util.tree_map(
                lambda p, g: p - lr_arr * g, params, grads)
            return new_params, loss

        batch_spec = P(axes)
        self._step = jax.jit(shard_map(
            device_step, mesh=self.mesh,
            in_specs=(P(), batch_spec, P()),
            out_specs=(P(), P()),
        ), donate_argnums=(0,))

    def __call__(self, params, batch):
        lr_arr = jnp.asarray(self.lr, dtype=jnp.float32)
        params, loss = self._step(params, batch, lr_arr)
        return params, float(np.asarray(loss))
