"""XLA collectives over the device mesh.

This is the TPU-native replacement for the reference's entire network layer
(ref: include/multiverso/net/, SURVEY.md §2.2): where Multiverso hand-rolls
Bruck allgather and recursive-halving reduce-scatter over MPI/ZMQ
point-to-point sends, the TPU data plane declares a ``lax.psum`` inside a
``shard_map`` over the mesh and lets XLA pick ICI-optimal collective
algorithms. ``net::Allreduce`` (ref: include/multiverso/net.h:51-57) maps
to ``allreduce_mesh``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.31 exports it at the top level
    from jax import shard_map
except ImportError:  # older jax: the experimental module is the API
    from jax.experimental.shard_map import shard_map

from ..sharding import mesh as meshlib


@functools.lru_cache(maxsize=None)
def _allreduce_fn(mesh, ndim: int):
    """Sum-allreduce over every mesh axis; input arrives replicated
    per-device (each device holds a full copy = one 'rank contribution')."""
    axes = tuple(mesh.axis_names)
    spec = P(axes, *([None] * (ndim - 1))) if ndim else P()

    def body(x):
        return jax.lax.psum(x, axes)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=spec,
                             out_specs=spec))


def allreduce_mesh(x, mesh=None):
    """Sum contributions laid shard-wise along the leading dim: the array's
    leading dim is split over the mesh, every shard is summed, and each
    shard of the result holds the total. For the common 'every chip has a
    full gradient' case, stack the per-chip arrays on axis 0."""
    mesh = mesh if mesh is not None else meshlib.local_mesh()
    x = jnp.asarray(x)
    return _allreduce_fn(mesh, x.ndim)(x)


@functools.lru_cache(maxsize=None)
def _psum_scalar_fn(mesh):
    axes = tuple(mesh.axis_names)
    return jax.jit(shard_map(lambda x: jax.lax.psum(x, axes),
                             mesh=mesh, in_specs=P(axes), out_specs=P(axes)))


def psum_scalar(value: float, mesh=None) -> float:
    """Each device contributes ``value``; returns value * n_devices. The
    tiniest ICI collective — used as a device-level barrier probe."""
    mesh = mesh if mesh is not None else meshlib.local_mesh()
    n = meshlib.device_count(mesh)
    contrib = jnp.full((n,), value, dtype=jnp.float32)
    return float(np.asarray(_psum_scalar_fn(mesh)(contrib))[0])


def pmean_mesh(x, mesh=None):
    """Mean-allreduce (model averaging over the mesh)."""
    mesh = mesh if mesh is not None else meshlib.local_mesh()
    n = meshlib.device_count(mesh)
    return allreduce_mesh(x, mesh) / n
