"""Device-mesh collectives and model-average training (the ICI data plane)."""

from .collective import (allreduce_mesh, pmean_mesh, psum_scalar)  # noqa: F401
from .ma import (MAAverager, MAFuture, MASGDStep,  # noqa: F401
                 MAShardedAverager, model_average, model_average_async,
                 sharded_model_average, sharded_model_average_async)
