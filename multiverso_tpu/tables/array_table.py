"""1-D dense distributed tensor table.

TPU-native equivalent of the reference's ``ArrayWorker/ArrayServer``
(ref: include/multiverso/table/array_table.h:13-73,
src/table/array_table.cpp:10-156). Semantics preserved:

- element-range partition over servers: server i owns
  ``[i*length, (i+1)*length)`` with the last server absorbing the
  remainder (ref: array_table.cpp:14-20, 98-108);
- Get uses the whole-table sentinel key -1 (ref: array_table.cpp:29-35);
- Get replies are ``[server_id, values]`` and land at the server's offset
  (ref: array_table.cpp:95-106, 130-141).

The TPU redesign is on the server side: the shard is a ``jax.Array``
sharded over the local device mesh (padded to the shard count), and the
updater is a jit-compiled donated-buffer op — the reference's OpenMP
element loop (ref: src/updater/updater.cpp:24-31) becomes one fused XLA
update in HBM.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import numpy as np

from ..core.blob import Blob, is_device_array
from ..core.message import MsgType
from ..runtime import device_lock
from ..sharding import mesh as meshlib
from ..updater import AddOption, UpdateEngine, create_rule
from ..util.log import CHECK
from . import client_cache
from .client_cache import BlobCache
from .table_interface import ServerTable, WorkerTable

_ALL_KEY = np.array([-1], dtype=np.int32)


def server_offsets(size: int, num_servers: int) -> List[int]:
    """Element ranges per server (ref: array_table.cpp:14-20)."""
    length = size // num_servers
    offsets = [i * length for i in range(num_servers)]
    offsets.append(size)
    return offsets


class ArrayWorker(WorkerTable):
    def __init__(self, size: int, dtype=np.float32, zoo=None):
        super().__init__(zoo=zoo)
        CHECK(size >= self._zoo.num_servers,
              "array table smaller than server count")
        self.size = int(size)
        self.dtype = np.dtype(dtype)
        self._num_server = self._zoo.num_servers
        self._offsets = server_offsets(self.size, self._num_server)
        # One outstanding Get per table, same as the reference's shared
        # row_index_/data_ destination registers (ref: matrix_table.cpp:
        # 66-76). _dest xor _device_shards names the reply destination.
        self._dest: Optional[np.ndarray] = None
        self._device_shards: Optional[Dict[int, object]] = None
        # Client cache (-max_get_staleness > 0): whole-blob — one entry
        # per server shard, a hit requires every shard fresh (array Gets
        # are whole-table). Device gets bypass (live jax.Array replies).
        bound = client_cache.staleness_bound()
        self._blob_cache: Optional[BlobCache] = None
        if bound > 0:
            self._blob_cache = BlobCache(bound, self._num_server,
                                         self._version_tracker)
            self._caches.append(self._blob_cache)
        self._pf_id: Optional[int] = None  # in-flight whole-table prefetch

    # -- public API (ref: array_table.cpp:29-66) --
    def get(self, out: Optional[np.ndarray] = None) -> np.ndarray:
        self.retrying_wait(lambda: self.get_async(out))
        return self._dest

    def get_async(self, out: Optional[np.ndarray] = None) -> int:
        if out is None:
            out = np.empty(self.size, self.dtype)
        CHECK(out.size == self.size, "output buffer size mismatch")
        self._dest, self._device_shards = out, None
        if self._blob_cache is not None:
            shards = self._blob_cache.fetch_all()
            if shards is not None:
                # Same write form as the uncached reply path
                # (_dest[lo:hi] = values): reshape(-1) would silently
                # COPY a non-contiguous buffer and drop the fill.
                for sid, values in shards.items():
                    out[self._offsets[sid]:self._offsets[sid + 1]] = \
                        values
                return self._local_done()
        return self.get_async_raw(Blob(_ALL_KEY.view(np.uint8)))

    def prefetch_async(self) -> int:
        """Warm the whole-blob client cache without touching the Get
        destination registers; identical in-flight prefetches dedup to
        one wire request. No-op when the cache is disabled."""
        if self._blob_cache is None:
            return self._local_done()
        if self._pf_id is not None:
            return self._pf_id  # dedup: join the outstanding fetch
        if self._blob_cache.fresh_all():  # counter-free planning check
            return self._local_done()
        msg_id = self._new_request()
        self._pf_id = msg_id
        self.add_completion(msg_id, self._on_prefetch_done)
        self._send_request(MsgType.Request_Get,
                           [Blob(_ALL_KEY.view(np.uint8))], msg_id)
        return msg_id

    def _on_prefetch_done(self, msg_id: int) -> None:
        if self._pf_id == msg_id:
            self._pf_id = None

    def add(self, delta: np.ndarray,
            option: Optional[AddOption] = None) -> None:
        self.retrying_wait(lambda: self.add_async(delta, option))

    def add_async(self, delta, option: Optional[AddOption] = None) -> int:
        """Accepts host or device arrays; a device delta rides the whole
        stack without touching the host (the TPU-native hot path)."""
        if not is_device_array(delta):
            delta = np.ascontiguousarray(delta,
                                         dtype=self.dtype).reshape(-1)
        CHECK(int(np.prod(delta.shape)) == self.size, "delta size mismatch")
        delta_blob = Blob(delta.reshape(-1))
        if self._blob_cache is not None:
            # Self-invalidation: block the cache until the ack's version
            # stamp resolves it (read-your-writes).
            self._blob_cache.begin_add()
        mid = self.add_async_raw(
            Blob(_ALL_KEY.view(np.uint8)), delta_blob,
            option.to_blob() if option is not None else None)
        if self._blob_cache is not None:
            self.add_completion(
                mid, lambda _mid: self._blob_cache.finish_add())
        return mid

    # -- partition (ref: array_table.cpp:68-86) --
    def partition(self, blobs, msg_type) -> Dict[int, List[Blob]]:
        out: Dict[int, List[Blob]] = {}
        # typed() keeps device payloads on device — the per-server slice is
        # then a lazy device slice, not a host copy.
        values = blobs[1].typed(self.dtype) if len(blobs) >= 2 else None
        for server_id in range(self._num_server):
            shard = [blobs[0]]
            if values is not None:
                lo, hi = self._offsets[server_id], self._offsets[server_id + 1]
                shard.append(Blob(values[lo:hi]))
                if len(blobs) == 3:
                    shard.append(blobs[2])
            out[server_id] = shard
        return out

    # -- device-resident Get: shards stay in HBM end to end --
    def get_device(self):
        """Whole-table Get returning a device array (no host transfer).
        The reply shards are the servers' jitted snapshots in HBM."""
        self._dest, self._device_shards = None, {}
        msg_id = self.get_async_raw(Blob(_ALL_KEY.view(np.uint8)))
        self.wait(msg_id)
        shards = [self._device_shards[sid]
                  for sid in range(len(self._device_shards))]
        self._device_shards = None
        if len(shards) == 1:
            return shards[0]
        import jax.numpy as jnp
        # Worker-thread reassembly dispatch: guarded like any other
        # multi-device program (multi-zoo mode only; no-op otherwise).
        with device_lock.guard():
            return device_lock.settle(jnp.concatenate(shards))

    # -- reply (ref: array_table.cpp:95-106) --
    def process_reply_get(self, reply_blobs: List[Blob]) -> None:
        server_id = int(reply_blobs[0].as_array(np.int32)[0])
        if self._reply_msg_id >= 0 and self._reply_msg_id == self._pf_id:
            # Prefetch reply shard: cache only — the destination
            # registers belong to whatever real Get is in flight.
            if self._blob_cache is not None:
                self._blob_cache.store(
                    server_id, reply_blobs[1].as_array(self.dtype),
                    self._reply_version)
            return
        if self._device_shards is not None:  # device-resident get
            self._device_shards[server_id] = reply_blobs[1].typed(self.dtype)
            return
        CHECK(self._dest is not None,
              "Get reply with no outstanding destination — only one Get "
              "may be in flight per table (as in the reference)")
        values = reply_blobs[1].as_array(self.dtype)
        lo, hi = self._offsets[server_id], self._offsets[server_id + 1]
        CHECK(values.size == hi - lo, "reply shard size mismatch")
        self._dest[lo:hi] = values
        if self._blob_cache is not None:
            # Wire-path population: real Gets refresh the cache too.
            self._blob_cache.store(server_id, values,
                                   self._reply_version)


class ArrayServer(ServerTable):
    def __init__(self, size: int, dtype=np.float32, zoo=None,
                 updater_type: Optional[str] = None):
        super().__init__(zoo=zoo)
        self.dtype = np.dtype(dtype)
        num_servers = self._zoo.num_servers
        server_id = self._zoo.server_id
        # ref: array_table.cpp:98-108 — size/num_servers, last takes the
        # remainder.
        my_size = size // num_servers
        if server_id == num_servers - 1:
            my_size += size % num_servers
        self.size = my_size
        self.server_id = server_id
        mesh = meshlib.local_mesh()
        self._sharding = meshlib.sharded_1d(mesh)
        padded = meshlib.padded_size(my_size, meshlib.device_count(mesh))
        self._data = meshlib.zeros_sharded((padded,), self.dtype,
                                           self._sharding)
        rule = None if updater_type is None \
            else create_rule(updater_type, dtype)
        self._engine = UpdateEngine(
            rule, (padded,), self.dtype, max(self._zoo.num_workers, 1),
            self._sharding)
        # Host twin of the rule's linearity: only a stateless rule
        # lets fused adds fold deltas before ONE apply
        # (docs/SERVER_ENGINE.md; the MatrixServer precedent). No rule
        # means plain accumulation — linear by construction.
        self._updater_stateless = True if rule is None else rule.stateless

    # -- server logic (ref: array_table.cpp:116-141) --
    def process_add(self, blobs: List[Blob]) -> None:
        CHECK(len(blobs) in (2, 3), "add needs [keys, values(, option)]")
        option = AddOption.from_blob(blobs[2]) if len(blobs) == 3 else None
        delta = blobs[1].typed(self.dtype)  # device deltas stay on device
        CHECK(int(np.prod(delta.shape)) == self.size,
              "add delta shard size mismatch")
        self._data = self._engine.apply_dense(self._data, delta, option)

    def process_get(self, blobs: List[Blob]) -> List[Blob]:
        key = int(blobs[0].as_array(np.int32)[0])
        CHECK(key == -1, "array table only serves whole-table gets")
        return [Blob(np.array([self.server_id], dtype=np.int32)),
                Blob(self._values())]

    # -- server-side request fusion (runtime/fusion.py,
    #    docs/SERVER_ENGINE.md; always entered under Server._lock_for)
    def fuse_eligible(self, blobs: List[Blob], is_get: bool) -> bool:
        """Whole-table host requests only: a Get must carry the -1
        sentinel (anything else raises in process_get — keep that on
        the serial path), an Add must carry a host delta and a
        stateless rule (fused adds FOLD deltas before one apply, which
        is only sum-equivalent for linear updates)."""
        if not blobs or blobs[0].on_device:
            return False
        if is_get:
            return blobs[0].size >= 4 \
                and int(blobs[0].as_array(np.int32)[0]) == -1
        if len(blobs) not in (2, 3) or blobs[1].on_device:
            return False
        return self._updater_stateless

    def process_fused_get(self, requests: List[List[Blob]]
                          ) -> List[List[Blob]]:
        """N whole-table Gets, ONE snapshot program: every reply
        shares the fresh copy (read-only on the reply path).
        Bit-identical to serial — the serial loop copies the same
        device state N times."""
        values = self._values()
        return [[Blob(np.array([self.server_id], dtype=np.int32)),
                 Blob(values)] for _ in requests]

    def process_fused_add(self, requests: List[List[Blob]]) -> None:
        """N dense Adds, ONE apply per option sub-group: left-fold the
        host deltas in arrival order, then apply once — linear for
        stateless rules, so sum-equivalent to the serial loop.
        Parse-first contract (table_interface.py): every delta is
        validated before the first apply."""
        runs: List[tuple] = []  # (option bytes, option, [deltas])
        for blobs in requests:
            CHECK(len(blobs) in (2, 3),
                  "add needs [keys, values(, option)]")
            option = AddOption.from_blob(blobs[2]) \
                if len(blobs) == 3 else None
            okey = blobs[2].as_array(np.uint8).tobytes() \
                if len(blobs) == 3 else None
            delta = np.asarray(blobs[1].typed(self.dtype)).ravel()
            CHECK(delta.size == self.size,
                  "add delta shard size mismatch")
            if not runs or runs[-1][0] != okey:
                runs.append((okey, option, []))
            runs[-1][2].append(delta)
        applied = 0
        for _, option, deltas in runs:
            try:
                acc = deltas[0].astype(self.dtype, copy=True)
                for d in deltas[1:]:
                    acc += d
                self._data = self._engine.apply_dense(self._data, acc,
                                                      option)
            except Exception as exc:  # noqa: BLE001
                from ..runtime.fusion import PartialFuseError
                raise PartialFuseError(applied, exc) from exc
            applied += len(deltas)

    def _values(self):
        """Logical-size snapshot of the padded device shard. Always a fresh
        buffer (jitted copy): the live storage gets donated away by the next
        update, which would invalidate a reply still holding a reference."""
        return self._snapshot(self._data)

    @functools.cached_property
    def _snapshot(self):
        n = self.size
        return jax.jit(lambda x: jax.numpy.copy(x[:n]))

    # -- checkpoint (ref: array_table.cpp:143-151) --
    def store(self, stream) -> None:
        stream.write(np.asarray(self._values()).tobytes())

    # -- async snapshot split (runtime/snapshot.py) --
    def snapshot_state(self):
        """Consistent capture under the caller's table lock: a jitted
        copy into a FRESH device buffer. Holding the live ``self._data``
        reference is NOT enough — the updater donates it away on the
        next add (``donate_argnums``), deleting the captured buffer
        under the snapshotter's feet. The copy stays on device; the
        host transfer + serialization run off the lock in
        ``write_snapshot``."""
        return device_lock.settle(self._snapshot(self._data))

    def write_snapshot(self, state, stream) -> None:
        """Off-lock serialization of a captured shard (store-format)."""
        stream.write(np.asarray(state).tobytes())

    def load(self, stream) -> None:
        raw = stream.read(self.size * self.dtype.itemsize)
        values = np.frombuffer(raw, dtype=self.dtype)
        CHECK(values.size == self.size, "checkpoint size mismatch")
        padded = self._data.shape[0]
        if padded != self.size:
            values = np.concatenate(
                [values, np.zeros(padded - self.size, self.dtype)])
        with device_lock.guard():
            self._data = device_lock.settle(
                jax.device_put(values, self._sharding))

    @property
    def raw(self):
        return self._values()
