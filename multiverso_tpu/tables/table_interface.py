"""Worker/Server table bases: the async Get/Add plumbing.

TPU-native equivalent of the reference's table interface
(ref: include/multiverso/table_interface.h:24-75, src/table.cpp:13-112).
Contract preserved exactly:

- ``get_async``/``add_async`` allocate a per-request ``Waiter``, build a
  request message and hand it to the worker actor (ref: src/table.cpp:41-82);
- the worker actor calls ``partition`` to split the request into
  per-server-shard blob lists and re-arms the waiter via ``reset(msg_id, n)``
  (ref: src/worker.cpp:30-76);
- each server reply triggers ``process_reply_get`` + ``notify`` until the
  waiter releases ``wait(msg_id)`` (ref: src/worker.cpp:78-88,
  src/table.cpp:84-111).

``ServerTable`` is ``Serializable`` — ``store``/``load`` stream the shard
state for checkpointing (ref: include/multiverso/table_interface.h:61-75).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from ..core.blob import Blob
from ..core.message import Message, MsgType
from ..runtime import actor as actors
from ..runtime.zoo import current_zoo
from ..util.dashboard import monitor
from ..util.waiter import Waiter


class TableRequestError(RuntimeError):
    """A table request failed remotely (server-side table logic or
    worker-side partition); raised by ``wait`` in the REQUESTER's thread.
    The actor runtime can only log — this carries the failure to the code
    that can actually handle it."""


class WorkerTable:
    """Client-side handle; lives on every worker rank."""

    def __init__(self, zoo=None):
        self._zoo = zoo if zoo is not None else current_zoo()
        self.table_id: int = self._zoo.register_worker_table(self)
        self._msg_id = 0
        self._waitings: Dict[int, Waiter] = {}
        self._errors: Dict[int, str] = {}
        self._mutex = threading.Lock()

    # -- public sync API (ref: src/table.cpp:29-38) --
    def get_raw(self, keys: Blob, extra: Sequence[Blob] = ()) -> None:
        with monitor("WORKER_TABLE_SYNC_GET"):
            self.wait(self.get_async_raw(keys, extra))

    def add_raw(self, keys: Blob, values: Blob,
                option_blob: Optional[Blob] = None) -> None:
        with monitor("WORKER_TABLE_SYNC_ADD"):
            self.wait(self.add_async_raw(keys, values, option_blob))

    # -- async API (ref: src/table.cpp:41-82) --
    def get_async_raw(self, keys: Blob, extra: Sequence[Blob] = ()) -> int:
        msg_id = self._new_request()
        msg = Message(src=self._zoo.rank, dst=-1,
                      msg_type=MsgType.Request_Get,
                      table_id=self.table_id, msg_id=msg_id)
        msg.push(keys)
        for blob in extra:
            msg.push(blob)
        self._zoo.send_to(actors.WORKER, msg)
        return msg_id

    def add_async_raw(self, keys: Blob, values: Blob,
                      option_blob: Optional[Blob] = None) -> int:
        blobs = [keys, values]
        if option_blob is not None:
            blobs.append(option_blob)
        return self.request_async_raw(MsgType.Request_Add, blobs)

    def request_async_raw(self, msg_type: MsgType,
                          blobs: Sequence[Blob]) -> int:
        """Generic async request with an arbitrary blob layout — the
        table subclass's ``partition`` defines what the blobs mean
        (e.g. the matrix table's pre-segmented device-key requests)."""
        msg_id = self._new_request()
        msg = Message(src=self._zoo.rank, dst=-1, msg_type=msg_type,
                      table_id=self.table_id, msg_id=msg_id)
        for blob in blobs:
            msg.push(blob)
        self._zoo.send_to(actors.WORKER, msg)
        return msg_id

    def _new_request(self) -> int:
        # Requests issued AFTER an abort would wait on a reply that can
        # never come (their waiter postdates abort()'s release sweep) —
        # refuse up front.
        self._check_aborted()
        with self._mutex:
            self._msg_id += 1
            msg_id = self._msg_id
            self._waitings[msg_id] = Waiter(1)
        return msg_id

    # -- waiter plumbing, driven by the worker actor
    #    (ref: src/table.cpp:84-111) --
    def wait(self, msg_id: int, timeout: Optional[float] = None) -> bool:
        self._check_aborted()
        with self._mutex:
            waiter = self._waitings.get(msg_id)
        if waiter is None:
            self._raise_if_failed(msg_id)
            return True  # already completed
        ok = waiter.wait(timeout=timeout)
        self._check_aborted()
        if ok:
            with self._mutex:
                self._waitings.pop(msg_id, None)
            self._raise_if_failed(msg_id)
        return ok

    def _raise_if_failed(self, msg_id: int) -> None:
        with self._mutex:
            error = self._errors.pop(msg_id, None)
        if error is not None:
            raise TableRequestError(error)

    def _check_aborted(self) -> None:
        reason = getattr(self, "_abort_reason", None)
        if reason is not None:
            from ..runtime.zoo import ClusterAborted
            raise ClusterAborted(reason)

    def abort(self, reason: str) -> None:
        """Release every outstanding waiter; subsequent/blocked ``wait``
        calls raise ClusterAborted (peer-failure path — without this a
        request to a dead rank blocks forever; the reference has no
        failure detection at all, SURVEY.md section 5.3)."""
        self._abort_reason = reason
        with self._mutex:
            waiters = list(self._waitings.values())
        for waiter in waiters:
            waiter.release()

    def fail(self, msg_id: int, reason: str, count: bool = True) -> None:
        """Record a remote failure for a request; the requester's
        ``wait(msg_id)`` raises TableRequestError once the request
        completes. With ``count`` the failure also counts as one shard
        reply (notify) — it must NOT release the waiter outright: a
        multi-shard request with sibling replies still in flight would
        otherwise unblock early, and a late sibling could write into the
        NEXT request's destination (the one-get-in-flight registers are
        shared). Callers whose control flow already notifies (the reply
        handlers' finally blocks) pass ``count=False``. Entries for
        requests nobody waits on persist until shutdown — errors are
        bugs, not steady-state traffic."""
        with self._mutex:
            # First error wins: follow-up failures of the same request
            # (e.g. the empty BSP clock-tick shards sent after a
            # partition failure) must not mask the root cause.
            self._errors.setdefault(msg_id, reason)
        if count:
            self.notify(msg_id)

    def reset(self, msg_id: int, num_wait: int) -> None:
        with self._mutex:
            waiter = self._waitings.get(msg_id)
        if waiter is not None:
            waiter.reset(num_wait)

    def notify(self, msg_id: int) -> None:
        with self._mutex:
            waiter = self._waitings.get(msg_id)
        if waiter is not None:
            waiter.notify()
            if waiter.done:
                # Reap completed waiters here, not only in wait():
                # fire-and-forget async adds (never waited) would otherwise
                # leak one Waiter per request over a long run.
                with self._mutex:
                    if self._waitings.get(msg_id) is waiter and waiter.done:
                        self._waitings.pop(msg_id, None)

    # -- virtuals (ref: table_interface.h:44-51) --
    def partition(self, blobs: List[Blob],
                  msg_type: MsgType) -> Dict[int, List[Blob]]:
        """Split a request's blobs into {server_id: [blobs]}."""
        raise NotImplementedError

    def process_reply_get(self, reply_blobs: List[Blob]) -> None:
        raise NotImplementedError

    @property
    def zoo(self):
        return self._zoo


class ServerTable:
    """Storage-side shard; lives on every server rank. Serializable
    (ref: table_interface.h:61-75)."""

    def __init__(self, zoo=None):
        self._zoo = zoo if zoo is not None else current_zoo()
        self.table_id: int = self._zoo.register_server_table(self)

    def process_add(self, blobs: List[Blob]) -> None:
        raise NotImplementedError

    def process_get(self, blobs: List[Blob]) -> List[Blob]:
        raise NotImplementedError

    def store(self, stream) -> None:
        raise NotImplementedError

    def load(self, stream) -> None:
        raise NotImplementedError

    @property
    def zoo(self):
        return self._zoo
