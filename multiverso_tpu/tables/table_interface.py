"""Worker/Server table bases: the async Get/Add plumbing.

TPU-native equivalent of the reference's table interface
(ref: include/multiverso/table_interface.h:24-75, src/table.cpp:13-112).
Contract preserved exactly:

- ``get_async``/``add_async`` allocate a per-request ``Waiter``, build a
  request message and hand it to the worker actor (ref: src/table.cpp:41-82);
- the worker actor calls ``partition`` to split the request into
  per-server-shard blob lists and re-arms the waiter via ``reset(msg_id, n)``
  (ref: src/worker.cpp:30-76);
- each server reply triggers ``process_reply_get`` + ``notify`` until the
  waiter releases ``wait(msg_id)`` (ref: src/worker.cpp:78-88,
  src/table.cpp:84-111).

``ServerTable`` is ``Serializable`` — ``store``/``load`` stream the shard
state for checkpointing (ref: include/multiverso/table_interface.h:61-75).
"""

from __future__ import annotations

import io
import itertools
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence

from ..core.blob import Blob
from ..core.message import PEER_LOST_MARK, Message, MsgType, stamp_trace
from ..runtime import actor as actors
from ..runtime.net import PeerLostError
from ..runtime.zoo import current_zoo
from ..util import log, tracing
from ..util.configure import get_flag
from ..util.dashboard import monitor
from ..util.lock_witness import named_lock
from ..util.waiter import Waiter
from .client_cache import VersionTracker

#: Completed-request errors retained for late ``wait`` calls. Beyond
#: this, the oldest completed entries are reaped — fire-and-forget async
#: requests that fail are otherwise a slow leak over a long run.
_MAX_RETAINED_ERRORS = 128

#: Per-instance serial for ServerTable state-lock names: the lock-order
#: witness keys its graph by NAME, so instances must not share one
#: (client_cache.py precedent).
_state_lock_serial = itertools.count()


class TableRequestError(RuntimeError):
    """A table request failed remotely (server-side table logic or
    worker-side partition); raised by ``wait`` in the REQUESTER's thread.
    The actor runtime can only log — this carries the failure to the code
    that can actually handle it."""


class RpcTimeoutError(TableRequestError):
    """A table request's replies did not all arrive within
    ``-rpc_timeout_s``; the message names the peer ranks still pending,
    the table and the msg_id (mirroring the allreduce engine's
    ``-allreduce_timeout_s`` rich errors)."""


class WorkerTable:
    """Client-side handle; lives on every worker rank."""

    def __init__(self, zoo=None):
        self._zoo = zoo if zoo is not None else current_zoo()
        self.table_id: int = self._zoo.register_worker_table(self)
        self._msg_id = 0
        self._waitings: Dict[int, Waiter] = {}
        self._errors: Dict[int, str] = {}
        self._mutex = threading.Lock()
        # Client-cache plumbing: per-server latest-version tracking and
        # the reply context the worker actor sets around
        # process_reply_get (server id, version stamp, request id) so
        # subclasses can attribute replies without a signature change.
        self._version_tracker = VersionTracker()
        #: Client caches registered by subclasses — invalidated when a
        #: serving shard changes GENERATION (server restart + snapshot
        #: restore resets its version counter; docs/FAULT_TOLERANCE.md).
        self._caches: List = []
        #: Data-generation counter for DERIVED read-side caches (the
        #: serving tier's neighbors index and hot-response cache,
        #: docs/SERVING.md): bumped on every event that makes version
        #: arithmetic against the old shard counters meaningless — a
        #: server-generation regression (PR-6 rejoin) and a shard-map
        #: epoch change (PR-12 elastic resharding). Version staleness
        #: alone misses both: a restored/remapped shard's counter can
        #: sit BELOW a derived cache's anchor version forever, so
        #: ``latest - anchor <= bound`` would hold while the underlying
        #: rows changed arbitrarily. Derived caches record the value at
        #: build/store time and treat any mismatch as forced
        #: invalidation. Written on the worker actor thread, read from
        #: serving threads — int assignment, GIL-atomic.
        self._data_generation = 0
        self._on_complete: Dict[int, List[Callable]] = {}
        self._reply_server = -1
        self._reply_version = -1
        self._reply_msg_id = -1
        self._reply_replica_rows = 0
        # Read-your-writes floors per server shard: the latest version
        # OUR OWN Add acks carried. A replica-served group whose floor
        # is below this would hand back pre-write values of rows this
        # worker already saw acknowledged — those rows repair to the
        # owner instead (docs/SHARDING.md). Written/read on the worker
        # actor thread only.
        self._add_floor: Dict[int, int] = {}
        # Replica repair staging: process_reply_get (worker actor
        # thread) records (owner_server_id, request_blobs) follow-ups
        # for rows a replica holder could not serve validly; the worker
        # actor drains them via take_repairs and transfers the reply's
        # notify onto the follow-up requests.
        self._pending_repairs: List = []
        # Request id of the partition in progress (set by the worker
        # actor around ``partition``): replica-routing tables key their
        # per-request routing bookkeeping by it.
        self._partition_msg_id = -1
        # Sampled requests' open ROOT spans: msg_id -> (trace id, issue
        # timestamp, span name). Written on the requester thread at
        # issue, popped on the worker actor thread at completion —
        # plain dict ops, GIL-atomic (util/tracing.py).
        self._trace_open: Dict[int, tuple] = {}

    # -- public sync API (ref: src/table.cpp:29-38) --
    def get_raw(self, keys: Blob, extra: Sequence[Blob] = ()) -> None:
        with monitor("WORKER_TABLE_SYNC_GET"):
            self.retrying_wait(lambda: self.get_async_raw(keys, extra))

    def add_raw(self, keys: Blob, values: Blob,
                option_blob: Optional[Blob] = None) -> None:
        with monitor("WORKER_TABLE_SYNC_ADD"):
            self.retrying_wait(
                lambda: self.add_async_raw(keys, values, option_blob))

    def retrying_wait(self, issue: Callable[[], int]) -> None:
        """Issue a request and wait; on a retryable PeerLostError
        re-issue with bounded exponential backoff (``-rpc_retry_max`` /
        ``-rpc_backoff_ms``). With retries disabled (the default) this
        is exactly ``wait(issue())``.

        Semantics are AT-LEAST-ONCE for Adds: the dead server may have
        applied the original before crashing, or — multi-server — the
        shards on surviving servers applied while the lost shard did
        not, so a retry re-applies them. For the additive updates the
        PS serves this is bounded noise, the same order as what async
        staleness already admits; exactly-once callers must build
        idempotency above this layer (docs/FAULT_TOLERANCE.md).

        BSP (``-sync``) force-disables the re-issue: the sync servers
        count exactly one request per worker per step on their vector
        clocks, so a retried request double-ticks the surviving
        servers' clocks and permanently skews this worker ahead (the
        leveling invariant breaks and cached peers strand). Sync-mode
        fault tolerance is backup workers for dead WORKERS and a loud
        abort for dead servers (zoo.peer_lost)."""
        retry_max = int(get_flag("rpc_retry_max", 0))
        if retry_max and get_flag("sync", False):
            retry_max = 0
        backoff = max(float(get_flag("rpc_backoff_ms", 50.0)), 1.0) / 1e3
        attempt = 0
        while True:
            try:
                self.wait(issue())
                return
            except PeerLostError:
                attempt += 1
                if attempt > retry_max:
                    raise
                delay = min(backoff * (2 ** (attempt - 1)), 5.0)
                log.error("table %d: request lost its peer; retry "
                          "%d/%d in %.0f ms", self.table_id, attempt,
                          retry_max, delay * 1e3)
                time.sleep(delay)

    # -- async API (ref: src/table.cpp:41-82) --
    def get_async_raw(self, keys: Blob, extra: Sequence[Blob] = ()) -> int:
        msg_id = self._new_request()
        self._send_request(MsgType.Request_Get,
                           [keys, *extra], msg_id)
        return msg_id

    def add_async_raw(self, keys: Blob, values: Blob,
                      option_blob: Optional[Blob] = None) -> int:
        blobs = [keys, values]
        if option_blob is not None:
            blobs.append(option_blob)
        return self.request_async_raw(MsgType.Request_Add, blobs)

    def request_async_raw(self, msg_type: MsgType,
                          blobs: Sequence[Blob]) -> int:
        """Generic async request with an arbitrary blob layout — the
        table subclass's ``partition`` defines what the blobs mean
        (e.g. the matrix table's pre-segmented device-key requests)."""
        msg_id = self._new_request()
        self._send_request(msg_type, blobs, msg_id)
        return msg_id

    def _send_request(self, msg_type: MsgType, blobs: Sequence[Blob],
                      msg_id: int) -> None:
        """Build and route a request message for an ALREADY-allocated
        id — the prefetch/dedup machinery allocates first (so reply
        routing state can be registered before anything is in flight)
        and sends later (possibly from a completion callback)."""
        msg = Message(src=self._zoo.rank, dst=-1, msg_type=msg_type,
                      table_id=self.table_id, msg_id=msg_id)
        # Distributed-trace sampling happens HERE, at request issue
        # (util/tracing.py): the id rides TRACE_SLOT on this message
        # and every shard/batch/reply it spawns, and the ROOT span
        # (worker issue -> waiter completion) opens now and closes in
        # ``_complete_if_done``. 0 (the default-off common case) skips
        # all bookkeeping.
        tid = tracing.new_trace(self._zoo.rank)
        if tid:
            stamp_trace(msg, tid)
            self._trace_open[msg_id] = (
                tid, tracing.now_ns(),
                f"worker_issue:{msg_type.name}[t{self.table_id}]")
        for blob in blobs:
            msg.push(blob)
        self._zoo.send_to(actors.WORKER, msg)

    def _local_done(self) -> int:
        """A request satisfied locally (cache hit / no-op prefetch):
        allocate a normal request id and complete it immediately, so
        async callers get an id whose ``wait`` returns at once."""
        msg_id = self._new_request()
        self.notify(msg_id)
        return msg_id

    def _new_request(self) -> int:
        # Requests issued AFTER an abort would wait on a reply that can
        # never come (their waiter postdates abort()'s release sweep) —
        # refuse up front.
        self._check_aborted()
        with self._mutex:
            self._msg_id += 1
            msg_id = self._msg_id
            self._waitings[msg_id] = Waiter(1)
        return msg_id

    # -- waiter plumbing, driven by the worker actor
    #    (ref: src/table.cpp:84-111) --
    def wait(self, msg_id: int, timeout: Optional[float] = None) -> bool:
        self._check_aborted()
        with self._mutex:
            waiter = self._waitings.get(msg_id)
        if waiter is None:
            self._raise_if_failed(msg_id)
            return True  # already completed
        # -rpc_timeout_s turns an unbounded wait into a DIAGNOSTIC one:
        # an explicit caller timeout keeps the boolean contract, but a
        # flag-sourced expiry raises, naming what never replied — the
        # difference between "a knob the caller handles" and "a lost
        # reply that would otherwise block this thread forever".
        flag_timeout = None
        if timeout is None:
            configured = float(get_flag("rpc_timeout_s", 0.0))
            if configured > 0:
                flag_timeout = configured
        ok = waiter.wait(timeout=timeout if timeout is not None
                         else flag_timeout)
        self._check_aborted()
        if ok:
            with self._mutex:
                self._waitings.pop(msg_id, None)
            self._raise_if_failed(msg_id)
        elif flag_timeout is not None:
            worker = self._zoo._actors.get(actors.WORKER)
            has_pending = (worker is not None
                           and hasattr(worker, "pending_peers"))
            peers = worker.pending_peers(self.table_id, msg_id) \
                if has_pending else []
            pending = waiter.pending
            # The request is ABANDONED: reap its waiter, recorded
            # error, and the worker's in-flight entries, or repeated
            # timeouts (the flag's target scenario is a peer that
            # never replies) leak one of each per request and pollute
            # later pending_peers diagnostics. A late straggler reply
            # finding no waiter is a no-op in notify().
            with self._mutex:
                self._waitings.pop(msg_id, None)
                self._errors.pop(msg_id, None)
            if has_pending:
                worker.forget_request(self.table_id, msg_id)
            raise RpcTimeoutError(
                f"table {self.table_id} request {msg_id}: "
                f"{pending} shard replies still missing after "
                f"{flag_timeout}s (peers pending: "
                f"{peers if peers else 'unknown'})")
        return ok

    def _raise_if_failed(self, msg_id: int) -> None:
        with self._mutex:
            error = self._errors.pop(msg_id, None)
        if error is not None:
            if PEER_LOST_MARK in error:
                # Typed retryable failure: the serving rank died; a
                # restarted replacement can serve a re-issue.
                raise PeerLostError(error)
            raise TableRequestError(error)

    def _check_aborted(self) -> None:
        reason = getattr(self, "_abort_reason", None)
        if reason is not None:
            from ..runtime.zoo import ClusterAborted
            raise ClusterAborted(reason)

    def abort(self, reason: str) -> None:
        """Release every outstanding waiter; subsequent/blocked ``wait``
        calls raise ClusterAborted (peer-failure path — without this a
        request to a dead rank blocks forever; the reference has no
        failure detection at all, SURVEY.md section 5.3)."""
        self._abort_reason = reason
        self._trace_open.clear()  # roots of aborted requests never
        # complete; dropping them keeps the dict bounded
        with self._mutex:
            waiters = list(self._waitings.values())
        for waiter in waiters:
            waiter.release()

    def fail(self, msg_id: int, reason: str, count: bool = True) -> None:
        """Record a remote failure for a request; the requester's
        ``wait(msg_id)`` raises TableRequestError once the request
        completes. With ``count`` the failure also counts as one shard
        reply (notify) — it must NOT release the waiter outright: a
        multi-shard request with sibling replies still in flight would
        otherwise unblock early, and a late sibling could write into the
        NEXT request's destination (the one-get-in-flight registers are
        shared). Callers whose control flow already notifies (the reply
        handlers' finally blocks) pass ``count=False``. At most
        ``_MAX_RETAINED_ERRORS`` completed-request entries are retained
        for late ``wait`` calls; past that the oldest completed ones are
        reaped so never-waited fire-and-forget failures don't accumulate
        over a long run."""
        with self._mutex:
            # First error wins: follow-up failures of the same request
            # (e.g. the empty BSP clock-tick shards sent after a
            # partition failure) must not mask the root cause.
            self._errors.setdefault(msg_id, reason)
            if len(self._errors) > _MAX_RETAINED_ERRORS:
                # Insertion order = age; entries still in _waitings are
                # in flight (their requester may yet wait) — keep those.
                for stale in list(self._errors):
                    if stale != msg_id and stale not in self._waitings:
                        del self._errors[stale]
                        if len(self._errors) <= _MAX_RETAINED_ERRORS:
                            break
        if count:
            self.notify(msg_id)

    def reset(self, msg_id: int, num_wait: int) -> None:
        with self._mutex:
            waiter = self._waitings.get(msg_id)
        if waiter is not None:
            waiter.reset(num_wait)
            if num_wait <= 0:
                # Re-armed to zero (empty partition): completion
                # callbacks must still fire or cache blocks strand.
                self._complete_if_done(msg_id, waiter)

    def notify(self, msg_id: int) -> None:
        with self._mutex:
            waiter = self._waitings.get(msg_id)
        if waiter is not None:
            waiter.notify()
            if waiter.done:
                self._complete_if_done(msg_id, waiter)

    def _complete_if_done(self, msg_id: int, waiter: Waiter) -> None:
        """Reap the completed waiter (fire-and-forget async adds would
        otherwise leak one per request) and run any registered
        completion callbacks exactly once."""
        if not waiter.done:
            return
        opened = self._trace_open.pop(msg_id, None)
        if opened is not None:
            tid, t0_ns, name = opened
            # Root span closure + the -trace_slow_ms watchdog: the
            # request's whole issue-to-completion window, enveloping
            # every hop span the shards recorded.
            tracing.end_root(tid, name, self._zoo.rank, t0_ns,
                             args={"table": self.table_id,
                                   "msg_id": msg_id})
        with self._mutex:
            if self._waitings.get(msg_id) is waiter:
                self._waitings.pop(msg_id, None)
            callbacks = self._on_complete.pop(msg_id, None)
        for fn in callbacks or ():
            try:
                fn(msg_id)
            except Exception:  # noqa: BLE001 - a callback must not
                # poison the worker actor's reply loop
                log.error("table %d: completion callback for request "
                          "%d raised", self.table_id, msg_id)
                import traceback
                traceback.print_exc()

    def add_completion(self, msg_id: int,
                       fn: Callable[[int], None]) -> None:
        """Run ``fn(msg_id)`` when the request completes (all shard
        replies in). If it already completed, run immediately — the
        check and the registration share the mutex with the completion
        sweep, so a callback can never be orphaned by a racing reply."""
        run_now = False
        with self._mutex:
            if msg_id in self._waitings:
                self._on_complete.setdefault(msg_id, []).append(fn)
            else:
                run_now = True
        if run_now:
            fn(msg_id)

    # -- client-cache version plumbing (driven by the worker actor) --
    def note_version(self, server_id: int, version: int) -> None:
        """Record a version stamp observed on a reply from a server.
        A version REGRESSION (reply below the shard's latest observed)
        means the server restarted and restored an older snapshot:
        re-anchor the tracker and invalidate every registered cache for
        that shard — entries stamped against the previous generation's
        counter must not serve against the restored one."""
        if self._version_tracker.regressed(server_id, version):
            log.error("table %d: server shard %d version regressed "
                      "(%d -> %d): server generation change, "
                      "invalidating client caches for that shard",
                      self.table_id, server_id,
                      self._version_tracker.latest(server_id), version)
            self._version_tracker.reset(server_id, version)
            self._data_generation += 1
            for cache in self._caches:
                cache.invalidate_server(server_id)
        self._version_tracker.note(server_id, version)

    def note_add_ack(self, server_id: int, version: int) -> None:
        """An Add ack from a server shard: raises this worker's
        read-your-writes floor for that shard (replica-served groups
        below the floor repair to the owner) in addition to the normal
        version observation."""
        if version >= 0:
            floor = self._add_floor.get(server_id, -1)
            if version > floor:
                self._add_floor[server_id] = version
        self.replica_server_alive(server_id)
        self.note_version(server_id, version)

    def add_floor(self, server_id: int) -> int:
        return self._add_floor.get(server_id, -1)

    def _begin_reply(self, server_id: int, version: int,
                     msg_id: int, replica_rows: int = 0) -> None:
        """Reply context for ``process_reply_get`` (single worker-actor
        thread — plain attributes, no lock needed). ``replica_rows``
        is the REPLICA_SLOT count: how many trailing rows of the reply
        were served from a replica store (their versions ride the
        reply's replica descriptor, not the header version slot)."""
        self._reply_server = server_id
        self._reply_version = version
        self._reply_msg_id = msg_id
        self._reply_replica_rows = int(replica_rows)
        self.replica_server_alive(server_id)
        self.note_version(server_id, version)

    def _end_reply(self) -> None:
        self._reply_server = self._reply_version = self._reply_msg_id = -1
        self._reply_replica_rows = 0

    # -- elastic resharding plumbing (runtime/shard_map.py,
    #    docs/SHARDING.md; worker actor thread) --
    def apply_shard_map(self, epoch: int, smap, alive_sids) -> None:
        """Epoch-stamped shard-map broadcast (Control_Shard_Map).
        Default: tables that don't reshard ignore it."""

    def shard_epoch(self) -> int:
        """The shard-map epoch this worker has adopted (-1 = still on
        the frozen creation-time layout). Poll target for
        ``Zoo.reshard_table``."""
        return -1

    def shard_owner_sids(self):
        """Server ids currently owning any of this table's items, or
        None for tables on the frozen layout."""
        return None

    def shard_layout(self):
        """``(bounds, owners)`` lists of the adopted map (None on the
        frozen layout) — the exact-layout poll target for
        ``Zoo.reshard_table``."""
        return None

    def reshard_space(self) -> int:
        """Size of this table's reshardable item space (rows for
        matrix tables, hash buckets for KV), or 0 when the table type
        does not support live resharding."""
        return 0

    def note_shard_moved(self, old_sid: int) -> None:
        """Rows moved OFF ``old_sid`` in an adopted map: a moved row's
        version stamps now come from a DIFFERENT shard counter, which
        is exactly the server-generation change the PR-6
        ``VersionTracker.regressed`` machinery invalidates on — reuse
        that path (drop every cache entry attributed to the old
        owner; entries compared against the new owner's counter would
        be meaningless). Called BEFORE the router swaps maps, so the
        caches' ``server_of`` still attributes the moved rows to the
        old owner and drops exactly them (plus the old owner's
        unmoved rows — conservative, and resharding is rare)."""
        log.info("table %d: rows moved off server shard %d (shard-map "
                 "epoch change) — treating as a generation change, "
                 "invalidating client caches for that shard",
                 self.table_id, old_sid)
        self._data_generation += 1
        for cache in self._caches:
            cache.invalidate_server(old_sid)

    def cache_generation(self) -> int:
        """Current data generation (see ``_data_generation``): derived
        read-side caches compare this against the value they recorded
        at build time and rebuild on any difference."""
        return self._data_generation

    # -- hot-shard replication plumbing (runtime/replica.py) --
    def apply_replica_map(self, epoch: int, rows) -> None:
        """Promoted-row map broadcast (worker actor thread). Default:
        tables that don't participate in replication ignore it."""

    def replica_server_dead(self, server_id: int) -> None:
        """Control_Dead_Peer for a server rank (worker actor thread):
        replica routing must stop striping hot rows to the corpse and
        fall back to owners. Default no-op."""

    def replica_server_alive(self, server_id: int) -> None:
        """A reply from this server landed — re-include it in replica
        routing (rejoin recovery). Default no-op."""

    def replica_reconcile(self, alive_sids) -> None:
        """An epoch-stamped map broadcast carried the controller's
        authoritative live-server view: re-validate the router's dead
        marks against it (a rejoined server resumes serving replicas
        without waiting for organic traffic). Default no-op."""

    def reshard_kind(self) -> int:
        """Initial-layout kind for the controller's planner: 0 =
        contiguous ranges (matrix ``row_offsets``), 1 = modulo hash
        buckets (KV)."""
        return 0

    def _stage_repair(self, server_id: int, blobs: List[Blob]) -> None:
        """Record a follow-up shard request toward ``server_id`` for
        rows the current reply could not serve validly (replica miss /
        stale floor). Called from ``process_reply_get``; the worker
        actor drains the staged repairs and transfers the reply's
        notify onto them, so the request's waiter completes only when
        the repaired rows landed too."""
        self._pending_repairs.append((int(server_id), list(blobs)))

    def take_repairs(self) -> List:
        repairs, self._pending_repairs = self._pending_repairs, []
        return repairs

    def extend_request(self, msg_id: int, extra: int) -> None:
        """Raise a request's expected reply count by ``extra`` (repair
        fan-out to several owners replaces ONE reply's notify)."""
        if extra <= 0:
            return
        with self._mutex:
            waiter = self._waitings.get(msg_id)
        if waiter is not None:
            waiter.add_waits(extra)

    # -- virtuals (ref: table_interface.h:44-51) --
    def partition(self, blobs: List[Blob],
                  msg_type: MsgType) -> Dict[int, List[Blob]]:
        """Split a request's blobs into {server_id: [blobs]}."""
        raise NotImplementedError

    def process_reply_get(self, reply_blobs: List[Blob]) -> None:
        raise NotImplementedError

    @property
    def zoo(self):
        return self._zoo


class ServerTable:
    """Storage-side shard; lives on every server rank. Serializable
    (ref: table_interface.h:61-75)."""

    #: Both-apply exemption flag for the dual-write window (set by
    #: the server actor around the deliberate handoff-copy apply;
    #: tables without elastic support never read it).
    _in_both_apply = False

    #: Whether this table's process_add/process_get dispatch jitted
    #: device programs — those must serialize under the server actor's
    #: process-wide table lock (two in-process server threads
    #: interleaving multi-device XLA executions deadlock the CPU
    #: runtime). Host-only tables (KV) opt out so two LocalFabric
    #: servers doing control-plane work don't serialize on each other.
    needs_device_lock = True

    def __init__(self, zoo=None):
        self._zoo = zoo if zoo is not None else current_zoo()
        self.table_id: int = self._zoo.register_server_table(self)
        #: Monotonically increasing shard version: bumped by the server
        #: actor once per successfully applied Add and stamped on every
        #: reply (client-cache staleness tracking).
        self.version = 0
        #: Guards this shard's (state, version) PAIR for host-only
        #: tables (``needs_device_lock=False``): their adds bypass the
        #: process-wide device lock (by design — KV control plane must
        #: not serialize two in-process servers), so without a
        #: per-table lock the async snapshotter could capture state N
        #: paired with version N+1, and a restore would then claim a
        #: version whose add it lacks — defeating the client caches'
        #: regression-based generation guard. Device-backed tables
        #: never contend on it (their adds hold the device lock the
        #: snapshotter also takes); acquired per-instance, so sibling
        #: shards stay concurrent.
        self._state_lock = named_lock(
            f"server_table[{next(_state_lock_serial)}].state")

    def process_add(self, blobs: List[Blob]) -> None:
        raise NotImplementedError

    def process_get(self, blobs: List[Blob]) -> List[Blob]:
        raise NotImplementedError

    # -- server-side request fusion hooks (runtime/fusion.py,
    #    docs/SERVER_ENGINE.md; server actor thread only, always
    #    entered under Server._lock_for) --
    def fuse_eligible(self, blobs: List[Blob], is_get: bool) -> bool:
        """May this request join a fused (table, op) group? Default
        NO: a table type must opt in per request — sentinel keys,
        device-key blobs, wire-codec frames, elastic windows and
        replica-routed rows all carry per-request semantics the fused
        paths do not reproduce. Called on the server actor thread at
        batch-classification time; nothing else touches table state
        between the check and the fused execution."""
        return False

    def process_fused_get(self, requests: List[List[Blob]]
                          ) -> List[List[Blob]]:
        """Serve N eligible Gets as one unit — ONE device program
        where the table type supports it. Returns one reply blob-list
        per request, in request order; MUST be bit-identical to
        serving each request through ``process_get`` serially.
        Default: the serial loop (host-only tables lose nothing)."""
        return [self.process_get(blobs) for blobs in requests]

    def process_fused_add(self, requests: List[List[Blob]]) -> None:
        """Apply N eligible Adds as one unit — sum-equivalent (left
        fold in request order) to serial ``process_add``. The caller
        bumps ``version`` by len(requests) and stamps every reply
        with the post-batch version. Contract: either parse/validate
        every request BEFORE the first state mutation (so a plain
        exception means nothing applied and the caller replays the
        whole group serially), or raise ``fusion.PartialFuseError``
        naming the applied prefix — the caller then replays only the
        tail. The default serial loop keeps that accounting exact."""
        for i, blobs in enumerate(requests):
            try:
                self.process_add(blobs)
            except Exception as exc:  # noqa: BLE001
                from ..runtime.fusion import PartialFuseError
                raise PartialFuseError(i, exc) from exc

    # -- elastic resharding hooks (runtime/shard_map.py,
    #    docs/SHARDING.md; server actor thread only). Default: table
    #    types that do not support live migration refuse/ignore —
    #    the controller rolls the move back on a refusal. --
    def shard_begin_out(self, desc) -> bool:
        """Controller's Request_ShardBegin: start streaming
        ``[desc.lo, desc.hi)`` to the destination. False = this table
        type cannot migrate live (sparse dirty bitmaps, stateful
        updaters, element-range arrays) — the server NACKs and the
        controller abandons the move."""
        return False

    def shard_pump(self):
        """One streaming step: ``(outbound messages, more)``. The
        server actor re-enqueues a pump message to itself while
        ``more`` — serving traffic interleaves between chunks."""
        return [], False

    def shard_import_chunk(self, msg):
        """Destination side of Request_ShardData; returns outbound
        messages (retransmit request / Control_Shard_Done)."""
        return []

    def shard_ack(self, msg):
        """Source side of Request_ShardAck (retransmit request);
        returns the re-sent chunks."""
        return []

    def shard_abort(self, epoch: int):
        """Controller rollback order: source resumes ownership (drops
        the forwarding window if the final chunk already left),
        destination drops partial state. The map never moved, so the
        pre-migration epoch is the consistent state. Returns outbound
        messages — the source synthesizes retryable error replies for
        requests it FORWARDED into the now-dead window (the requester
        tracked them against THIS rank, so the destination's death
        sweep can never fail them; without these replies a waiter
        blocks forever)."""
        return []

    def shard_announce(self):
        """Traffic-driven resend hook (destination): re-announce a
        pending Control_Shard_Done / retransmit request whose last
        copy may have been lost. Returns outbound messages."""
        return []

    def apply_shard_map_server(self, epoch: int, smap, alive_sids):
        """Epoch-stamped map broadcast on the server side: a commit
        clears migration state (the source KEEPS its forwarding
        entries — stale routers may still send moved rows here),
        prunes replica entries for moved rows. Returns outbound
        messages. Default: ignore."""
        return []

    def shard_forward_get(self, msg):
        """Dual-read window routing for an inbound Get: None = serve
        locally as usual; else a list of outbound messages that fully
        handle the request (the reply reaches the requester from the
        destination, carrying this shard's piggybacked rows as a
        replica group — docs/SHARDING.md)."""
        return None

    def shard_forward_add(self, msg):
        """Dual-write routing for an inbound Add: None = apply locally
        as usual; else ``(local_apply_blobs_or_None, outbound)`` — the
        moved rows' sub-add forwards to the destination (which acks
        the requester), any still-owned remainder applies HERE with no
        ack of its own (the destination's single ack completes the
        waiter; per-request FIFO toward the destination orders the
        forwarded add before any later forwarded read)."""
        return None

    def process_forward_get(self, blobs):
        """Destination side of Request_FwdGet: serve the forwarded
        rows, append the piggybacked source rows, and return
        ``(reply_blobs, n_replica_rows, src_rank, src_version)`` — the
        server actor builds a Reply_Get IMPERSONATING the source rank
        (so the requester's in-flight accounting matches the shard it
        sent) with this shard's rows as the replica group."""
        raise NotImplementedError

    # -- hot-shard replication hooks (runtime/replica.py; server actor
    #    thread only — no locking on the replica state) --
    def apply_replica_map(self, epoch: int, rows) -> List[Message]:
        """Promoted-row map broadcast: owners start/stop the
        write-through fan-out for their rows, holders prune demoted
        entries. Returns outbound messages for the server actor to
        send (the initial value push for newly promoted own rows).
        Default: table types that don't replicate ignore it."""
        return []

    def apply_replica_sync(self, blobs: List[Blob]) -> None:
        """An owner's Request_ReplicaSync refresh push; default drop
        (a non-replicating table should never receive one)."""

    def replica_redirty(self, blobs: List[Blob]) -> None:
        """The communicator's failure echo for a sync push that never
        left this rank: the owner must re-dirty the chunk's rows so the
        next flush re-pushes them (the version watermark is only sound
        when no dirtied row is silently lost). Default no-op."""

    def replica_flush_if_due(self) -> List[Message]:
        """Cadence hook, called by the server actor after each served
        request: returns the due outbound messages — write-through
        refreshes of dirty promoted rows toward the holders and/or the
        hot-row window report toward the controller. Default no-op."""
        return []

    def take_reply_replica_rows(self) -> int:
        """How many trailing rows of the reply just built by
        ``process_get`` were replica-served (the server actor stamps
        REPLICA_SLOT with it); self-clearing. Default 0."""
        return 0

    def store(self, stream) -> None:
        raise NotImplementedError

    def load(self, stream) -> None:
        raise NotImplementedError

    # -- async snapshot split (runtime/snapshot.py) --
    #
    # The periodic snapshotter wants a CONSISTENT cut without holding
    # the server's table lock for the whole serialize+write:
    # ``snapshot_state`` runs under the lock and must be cheap (capture
    # a reference to the immutable device array / copy a small dict);
    # ``write_snapshot`` runs OFF the lock, possibly much later, and
    # must produce bytes that ``load`` accepts (i.e. store()-format).

    def snapshot_state(self):
        """Capture this shard's state for snapshotting. Fallback:
        serialize eagerly (correct for any table, but does the full
        store under the caller's lock — subclasses override with an
        O(1) capture)."""
        buf = io.BytesIO()
        self.store(buf)
        return buf.getvalue()

    def write_snapshot(self, state, stream) -> None:
        """Serialize a ``snapshot_state`` capture into ``stream`` in
        ``store``-compatible format."""
        stream.write(state)

    def snapshot_meta(self):
        """JSON-able sidecar recorded in the snapshot MANIFEST entry
        (runtime/snapshot.py) alongside the payload: reshardable
        tables record their adopted shard-map epoch + owned intervals
        here, so a rejoining server restores into the RIGHT map
        instead of its frozen creation-time layout. None (default) =
        no sidecar, legacy restore path."""
        return None

    def load_with_meta(self, stream, meta) -> None:
        """Restore from a snapshot payload plus its manifest sidecar
        (``snapshot_meta`` round trip). Default: sidecar-less legacy
        ``load``."""
        self.load(stream)

    @property
    def zoo(self):
        return self._zoo
