"""Table factory: create worker+server table pairs by rank role.

TPU-native equivalent of the reference's ``MV_CreateTable``/table_factory
(ref: include/multiverso/table_factory.h:16-26, src/table_factory.cpp:8-22,
include/multiverso/multiverso.h:35-41): on a server rank the server-side
shard is created first, then the worker handle on worker ranks, followed by
a barrier so every rank sees consistent table ids. Creation ORDER must
match across ranks — ids are assigned by per-rank counters, exactly like
the reference.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from ..core.node import is_server, is_worker
from ..runtime.zoo import current_zoo
from ..util.configure import get_flag
from .array_table import ArrayServer, ArrayWorker
from .kv_table import KVServer, KVWorker
from .matrix_table import MatrixServer, MatrixTableOption, MatrixWorker


def _table_role(zoo) -> int:
    if not zoo._nodes:
        hint = " (-ma=true skips the parameter server; flags persist " \
            "across init/shutdown like the reference's statics)" \
            if get_flag("ma") else ""
        raise RuntimeError(f"no parameter server on this rank{hint}")
    return zoo._nodes[zoo.rank].role


@dataclass
class ArrayTableOption:
    """ref: include/multiverso/table/array_table.h (ArrayTableOption)."""
    size: int
    dtype: object = np.float32
    updater_type: Optional[str] = None


@dataclass
class KVTableOption:
    key_dtype: object = np.int64
    val_dtype: object = np.float32


def create_array_table(size: int, dtype=np.float32,
                       updater_type: Optional[str] = None,
                       zoo=None) -> Optional[ArrayWorker]:
    zoo = zoo if zoo is not None else current_zoo()
    role = _table_role(zoo)
    worker = None
    if is_server(role):
        zoo.server_table_ready(
            ArrayServer(size, dtype, zoo=zoo, updater_type=updater_type))
    if is_worker(role):
        worker = ArrayWorker(size, dtype, zoo=zoo)
    if not zoo.rejoining:
        # A restarted rank rejoining a live cluster re-creates its
        # tables alone — the survivors' creation barriers are long
        # past, so entering one would poison the next real barrier.
        zoo.barrier()
    return worker


def create_matrix_table(num_row: int, num_col: int, dtype=np.float32,
                        is_sparse: bool = False, is_pipeline: bool = False,
                        updater_type: Optional[str] = None,
                        random_init: Optional[tuple] = None, seed: int = 0,
                        zoo=None) -> Optional[MatrixWorker]:
    zoo = zoo if zoo is not None else current_zoo()
    role = _table_role(zoo)
    worker = None
    if is_server(role):
        zoo.server_table_ready(
            MatrixServer(num_row, num_col, dtype, is_sparse=is_sparse,
                         is_pipeline=is_pipeline, zoo=zoo,
                         updater_type=updater_type,
                         random_init=random_init, seed=seed))
    if is_worker(role):
        worker = MatrixWorker(num_row, num_col, dtype,
                              is_sparse=is_sparse,
                              is_pipeline=is_pipeline, zoo=zoo,
                              updater_type=updater_type)
    if not zoo.rejoining:  # see create_array_table
        zoo.barrier()
    return worker


def create_kv_table(key_dtype=np.int64, val_dtype=np.float32,
                    zoo=None) -> Optional[KVWorker]:
    zoo = zoo if zoo is not None else current_zoo()
    role = _table_role(zoo)
    worker = None
    if is_server(role):
        zoo.server_table_ready(KVServer(key_dtype, val_dtype, zoo=zoo))
    if is_worker(role):
        worker = KVWorker(key_dtype, val_dtype, zoo=zoo)
    if not zoo.rejoining:  # see create_array_table
        zoo.barrier()
    return worker


def create_table(option, zoo=None):
    """Dispatch on an option struct (the reference's templated
    MV_CreateTable, ref: multiverso.h:35-41)."""
    if isinstance(option, ArrayTableOption):
        return create_array_table(option.size, option.dtype,
                                  option.updater_type, zoo=zoo)
    if isinstance(option, MatrixTableOption):
        return create_matrix_table(option.num_row, option.num_col,
                                   option.dtype, option.is_sparse,
                                   option.is_pipeline, option.updater_type,
                                   zoo=zoo)
    if isinstance(option, KVTableOption):
        return create_kv_table(option.key_dtype, option.val_dtype, zoo=zoo)
    raise TypeError(f"unknown table option: {type(option).__name__}")
