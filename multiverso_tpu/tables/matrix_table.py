"""2-D row-sharded distributed matrix table (dense + sparse).

TPU-native equivalent of the reference's matrix tables — the row-sharded
``MatrixWorkerTable/MatrixServerTable``
(ref: include/multiverso/table/matrix_table.h:16-127,
src/table/matrix_table.cpp:13-468) unified with the sparse variant's
per-worker dirty-row tracking (ref: src/table/sparse_matrix_table.cpp:14-314,
include/multiverso/table/matrix.h:14-123). Semantics preserved:

- row-range partition: each server owns ``num_row/num_servers`` rows, last
  takes the remainder; degenerate one-row-per-server layout when
  ``num_row < num_servers`` (ref: matrix_table.cpp:23-45);
- request keys: sentinel -1 = whole table, else an int32 row-id vector;
  row -> server by ``row / (num_row/num_servers)`` clamped to the last
  server (ref: matrix_table.cpp:267-276);
- whole-table Get replies carry ``[keys, values, server_id]`` so the worker
  places the shard; row Gets reply ``[row_ids, values]``
  (ref: matrix_table.cpp:317-341, 420-454);
- sparse mode: the server keeps an ``up_to_date[worker][row]`` bitmap —
  an Add dirties the row for every *other* worker, a Get (whose GetOption
  names the worker) returns only that worker's dirty rows and marks them
  clean (ref: sparse_matrix_table.cpp:200-258); with pipelining each
  worker counts as two logical consumers (ref: sparse_matrix_table.cpp:
  184-197).

TPU redesign: each server shard is a row-sharded ``jax.Array``; row
Gets/Adds are XLA gather/scatter jitted over power-of-two row buckets, and
whole-table ops are single fused device ops.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional

import jax
import numpy as np

from ..core.blob import Blob, is_device_array
from ..core.message import (PEER_LOST_MARK, Message, MsgType,
                            stamp_trace, trace_of)
from ..runtime import device_lock
from ..runtime import replica as replica_mod
from ..runtime import shard_map as shard_map_mod
from ..runtime.zoo import CONTROLLER_RANK
from ..util import chaos
from ..util.dashboard import count as count_event
from . import client_cache
from .client_cache import RowCache
from ..sharding import mesh as meshlib
from ..updater import AddOption, GetOption, UpdateEngine, create_rule
from ..updater.engine import bucket_size, pad_ids
from ..util import log, wire_codec
from ..util.configure import define_bool, get_flag
from ..util.log import CHECK
from ..util.quantization import OneBitFilter
from .table_interface import (RpcTimeoutError, ServerTable,
                              TableRequestError, WorkerTable)
from ..runtime.net import PeerLostError

define_bool("sparse_compress", True,
            "run sparse-matrix wire traffic through the compact wire "
            "codec (ref: sparse_matrix_table.cpp:148-153; float64-pair "
            "format replaced by int32-index + typed-value frames)")
define_bool("verify_device_ids", False,
            "debug: on the first fused add+dirty-get, read the "
            "row_ids_device mirror back to the host and CHECK it "
            "matches the host ids (turns the documented silent-"
            "corruption mode of a disagreeing mirror into a loud "
            "failure; costs one device->host transfer)")
define_bool("one_bit_push", False,
            "1-bit quantize matrix Add traffic (sign bitmap + per-sign "
            "means, worker-side error feedback) — ~32x smaller pushes "
            "over cross-process transports; completes the reference's "
            "empty OneBitsFilter stub (quantization_util.h:160-161)")

_ALL_KEY = np.array([-1], dtype=np.int32)
# Sentinel -2: whole-table dirty get with a DEVICE-resident reply
# (in-process extension; -1 keeps the reference's host-reply semantics,
# ref: matrix_table.cpp:267-276 sentinel handling).
_ALL_KEY_DEVICE_REPLY = np.array([-2], dtype=np.int32)
# Sentinel -3: PRE-SEGMENTED device-key request — the caller already
# split its (sorted) device ids into one slice per server, so each
# server receives ONLY its segment instead of the full broadcast id set
# (the device twin of the reference's per-server key bucketing,
# ref: matrix_table.cpp:267-276; the round-4 broadcast+mask form made
# every server process every key).
_SEGMENTED_KEY = np.array([-3], dtype=np.int32)
# Sentinel -4: FUSED sparse add + dirty get — semantically the exact
# composition of add_rows and get_dirty_device, executed as ONE device
# program server-side (on a tunneled device each big-argument program
# launch costs more than the work; the 2-program roundtrip is launch-
# bound, and fusing halves it).
_ADD_GET_DIRTY_KEY = np.array([-4], dtype=np.int32)


def _onebit_blobs(chunk: np.ndarray):
    """Encode one server's (error-feedback-adjusted) delta chunk as
    [sign bits, meta]; meta = [pos_mean, neg_mean, element count].
    Returns (blobs, residual) — the caller accumulates the residual into
    its feedback buffer."""
    encoded, residual = OneBitFilter().encode(chunk)
    bits, pos_mean, neg_mean, size = encoded
    meta = np.array([pos_mean, neg_mean, float(size)], np.float64)
    return [Blob(bits), Blob(meta)], residual


def _onebit_decode(bits_blob: Blob, meta_blob: Blob) -> np.ndarray:
    meta = meta_blob.as_array(np.float64)
    return OneBitFilter().decode(
        (bits_blob.as_array(np.uint8), float(meta[0]),
         float(meta[1]), int(meta[2])))


def _compress_values(values: np.ndarray, lossy: bool = False):
    """values -> ([codec frame blob], residual). One self-describing
    frame replaces the old [float64 pairs, size_record] two-blob layout
    (ref layout: quantization_util.h:37-137) — int32 indices + typed
    values, 8 bytes/pair lossless instead of 16. ``residual`` is the
    error-feedback vector when a lossy tier was chosen, else None."""
    frame, residual = wire_codec.encode_blob(
        np.asarray(values).reshape(-1), lossy=lossy)
    return [Blob(np.frombuffer(frame, np.uint8))], residual


def _decompress_values(values_blob: Blob, dtype) -> np.ndarray:
    full = wire_codec.decode_blob(values_blob.as_array(np.uint8))
    return full.astype(dtype, copy=False)


def _is_codec_blob(blob: Blob) -> bool:
    """True when the blob carries a codec frame. Receivers with
    ``_compress`` set sniff before decoding so a peer sending RAW
    values (cross-rank -sparse_compress flag mismatch) degrades to the
    uncompressed layout instead of raising inside the actor loop and
    stranding the requester's waiter. NOTE this does NOT extend to the
    REMOVED float64-pair format: a pre-codec build's compressed
    traffic is a declared wire break (docs/WIRE_FORMAT.md) — its
    3-blob pair layout fails the blob-count/size CHECKs loudly rather
    than being decoded."""
    return not blob.on_device \
        and wire_codec.is_codec_frame(blob.as_array(np.uint8))


def _shaped_rows(values, n_rows: int, num_col: int):
    """Reshape to [n_rows, num_col] only when needed (a no-op reshape on
    a device array still dispatches a device op)."""
    if tuple(np.shape(values)) != (n_rows, num_col):
        values = values.reshape(n_rows, num_col)
    return values


def _trim_rows(values, n_rows: int):
    """Slice gather output down to the real row count only when padding
    added rows (full-range device slices still dispatch)."""
    if values.shape[0] != n_rows:
        values = values[:n_rows]
    return values


def row_offsets(num_row: int, num_servers: int) -> List[int]:
    """Row ranges per server incl. the degenerate rows<servers layout
    (ref: matrix_table.cpp:24-41). Returns num_actual_servers+1 offsets."""
    offsets = [0]
    length = num_row // num_servers
    if length > 0:
        offset = length
        i = 0
        while length > 0 and offset < num_row and i + 1 < num_servers:
            offsets.append(offset)
            offset += length
            i += 1
    else:
        offset = 1
        i = 0
        while offset < num_row and i + 1 < num_servers:
            offsets.append(offset)
            offset += 1
            i += 1
    offsets.append(num_row)
    return offsets


class _ScatterRead:
    """One in-flight scatter-gather serving read (docs/SERVING.md):
    ``rows`` is the SORTED UNIQUE requested id vector; sub-request
    replies (worker actor thread) place values and per-row fetch
    versions at ``searchsorted`` positions. Requester threads read the
    buffers only after every sub-request's waiter completed, so no
    locking is needed — each reply writes a disjoint position set."""

    __slots__ = ("rows", "out", "versions")

    def __init__(self, rows: np.ndarray, out: np.ndarray,
                 versions: np.ndarray):
        self.rows = rows
        self.out = out
        self.versions = versions


@dataclass
class MatrixTableOption:
    """ref: include/multiverso/table/matrix.h:116-123."""
    num_row: int
    num_col: int
    dtype: object = np.float32
    is_sparse: bool = False
    is_pipeline: bool = False
    updater_type: Optional[str] = None


class MatrixWorker(WorkerTable):
    def __init__(self, num_row: int, num_col: int, dtype=np.float32,
                 is_sparse: bool = False, is_pipeline: bool = False,
                 zoo=None, updater_type: Optional[str] = None):
        super().__init__(zoo=zoo)
        self.num_row = int(num_row)
        self.num_col = int(num_col)
        self.dtype = np.dtype(dtype)
        self.is_sparse = bool(is_sparse)
        # Consumer-slot count, mirroring the server's bitmap height —
        # lets caller-side CHECKs reject a bad consumer id instead of
        # hanging on a reply the server actor will never send.
        self._num_consumers = max(self._zoo.num_workers, 1) \
            * (2 if is_pipeline else 1)
        # Device-key row adds may carry duplicate ids, which only sum
        # correctly under stateless rules. The server-side engine CHECK
        # fires inside the server actor, where _safe_dispatch swallows it
        # and the Add ack never comes — so a misconfigured trainer hangs
        # in wait() instead of raising. Validate here, in the CALLER's
        # thread (the factory passes the table's updater_type along),
        # deriving statelessness from the rule registry so this cannot
        # drift from the engine's actual state handling (e.g. int tables
        # and unknown names both resolve to the stateless default adder).
        self._updater_stateless = create_rule(updater_type,
                                              self.dtype).stateless
        # Wire compression for sparse traffic, both directions, as the
        # reference does unconditionally (sparse_matrix_table.cpp:148-153);
        # here behind a flag read at table-construction time — and only
        # when there IS a wire: an in-process fabric moves object
        # references, so filtering would only burn CPU and force device
        # payloads through host bytes.
        self._compress = (self.is_sparse
                          and not self._zoo.net.in_process
                          and bool(get_flag("sparse_compress")))
        # Lossy value tiers (fp16 / int8-with-per-chunk-scale) for Add
        # pushes only, with worker-side error feedback; pulls stay
        # lossless (the server keeps no per-consumer residual state).
        self._lossy = (self._compress and self.dtype == np.float32
                       and bool(get_flag("wire_codec_lossy")))
        # 1-bit push quantization (dense float32 tables; sparse traffic
        # already rides the wire codec). Pulls stay full precision — only
        # gradient pushes quantize. The worker-side error-feedback buffer
        # is table-shaped (1-bit SGD's standard memory cost).
        self._one_bit = (not self.is_sparse
                         and self.dtype == np.float32
                         and bool(get_flag("one_bit_push")))
        self._residual: Optional[np.ndarray] = None
        # Frozen creation-time layout, possibly over only the first
        # -shard_initial_servers servers (the rest are standbys a
        # later reshard can grow onto — docs/SHARDING.md).
        self._init_active = shard_map_mod.initial_active_servers(
            self._zoo.num_servers)
        self._offsets = row_offsets(self.num_row, self._init_active)
        self._num_server = len(self._offsets) - 1  # actual servers used
        self._row_length = max(self.num_row // self._num_server, 1)
        # Live elastic resharding (runtime/shard_map.py): the adopted
        # epoch-stamped map replaces the frozen division rule; None =
        # never resharded, byte-identical routing to the reference.
        # Worker actor thread swaps it; requester threads read it —
        # one attribute, GIL-atomic.
        self._shard_map: Optional[shard_map_mod.ShardMap] = None
        # One outstanding Get per table (the reference's shared row_index_
        # registers, ref: matrix_table.cpp:66-76). _dest xor _device_shards
        # names the reply destination.
        self._dest: Optional[np.ndarray] = None
        self._dest_rows: Optional[np.ndarray] = None  # requested row-id vector
        self._device_shards: Optional[Dict[int, object]] = None
        self._device_shard_ids: Optional[Dict[int, np.ndarray]] = None
        self._mirror_verified = False  # -verify_device_ids: once per table
        # Client cache (-max_get_staleness > 0): row-granular, DENSE
        # host-path row Gets only. Sparse tables are excluded — their
        # dirty-row protocol IS a server-tracked staleness cache, and a
        # client copy on top would double-apply the bookkeeping. Device
        # replies (live jax.Arrays) bypass too: the host cache cannot
        # hold them without forcing a device->host copy per hit.
        bound = client_cache.staleness_bound()
        self._row_cache: Optional[RowCache] = None
        if not self.is_sparse and not get_flag("sync", False):
            # ALWAYS constructed on the dense host path (bound 0 =
            # inactive pass-through, byte-identical behavior to the
            # old no-cache construction) so the autotune layer can
            # widen -max_get_staleness on a LIVE table — the cache's
            # apply hooks rebind the bound; _live_cache() below keeps
            # every hot path on the old code shape while inactive
            # (docs/AUTOTUNE.md). Sync mode stays construction-time
            # disabled: a locally served Get would bypass the vector
            # clocks, so no hook may ever activate it.
            self._row_cache = RowCache(
                bound, self._server_of_rows,
                max(self._zoo.num_servers, self._num_server),
                self._version_tracker)
            self._caches.append(self._row_cache)
        # In-flight prefetch registry (+ dedup/join): msg_id -> sorted
        # unique ids being fetched; _pf_by_key dedups identical
        # prefetches; _pf_joined holds Gets deferred onto an in-flight
        # prefetch (served from the cache — or forwarded to the wire —
        # when it completes). Guarded by _pf_lock: prefetches/joins
        # issue on the requester's thread, completion runs on the
        # worker actor's.
        self._pf_lock = threading.Lock()
        self._pf_rows: Dict[int, np.ndarray] = {}
        self._pf_by_key: Dict[bytes, int] = {}
        self._pf_joined: Dict[int, List] = {}
        # Scatter-gather serving reads (read_rows_scatter,
        # docs/SERVING.md): msg_id -> _ScatterRead. Each sub-request
        # carries its OWN destination buffer, so any number may be in
        # flight concurrently — unlike the one-get-in-flight _dest
        # registers. Registered on the requester thread BEFORE the
        # send, read on the worker actor thread (dict get/pop,
        # GIL-atomic; registration happens-before the mailbox push).
        self._sg: Dict[int, _ScatterRead] = {}
        # Hot-shard read replication routing (runtime/replica.py,
        # docs/SHARDING.md): the promoted-row map re-routes the
        # replicated subset of a host row Get to holder servers
        # (per-row stripe, or the co-located shard when this rank
        # hosts one); Adds always go to the owners (write-through).
        # Dense multi-server tables only, matching the server side.
        # _replica_sent records, per request id, which foreign rows
        # went to which holder so each holder's reply can be diffed
        # for repairs. Worker actor thread only.
        self._replica_router = None
        self._replica_sent: Dict[int, Dict[int, np.ndarray]] = {}
        if (not self.is_sparse and self._num_server > 1
                and replica_mod.replication_enabled()):
            local_sid = self._zoo.rank_to_server_id(self._zoo.rank)
            self._replica_router = replica_mod.ReplicaRouter(
                self._num_server, salt=max(self._zoo.rank, 0),
                preferred=local_sid if local_sid >= 0 else None)

    def _live_cache(self) -> Optional[RowCache]:
        """The row cache when ACTIVE (live bound > 0), else None — the
        gate every read-path use site goes through, so an inactive
        cache costs exactly one attribute check and the control flow
        matches the pre-dynamic-flag no-cache path (the store/fetch
        self-guards in RowCache cover mid-request deactivation)."""
        cache = self._row_cache
        if cache is not None and cache.active:
            return cache
        return None

    def _server_of_rows(self, rows: np.ndarray) -> np.ndarray:
        """Vectorized row ids -> owning server ids (the one sharding
        rule; shared by partition routing, the client cache's
        freshness checks, the replica protocol's owner attribution and
        the serving tier's version attribution). The frozen division
        rule until an epoch-stamped shard map is adopted
        (docs/SHARDING.md elastic resharding)."""
        smap = self._shard_map
        if smap is not None:
            return smap.owner_of(rows)
        return np.minimum(rows // self._row_length, self._num_server - 1)

    # -- elastic resharding: worker side (runtime/shard_map.py) --
    def apply_shard_map(self, epoch: int, smap, alive_sids) -> None:
        """Epoch-stamped map broadcast (worker actor thread — the same
        thread that partitions, so routing never races the swap).
        Moved intervals invalidate client caches through the PR-6
        generation-change path BEFORE the swap (``note_shard_moved``,
        table_interface.py), and the replica router reconciles its
        dead marks against the broadcast's live-server view — or
        retires outright once the map is truly dynamic."""
        old = self._shard_map
        if old is not None and epoch <= old.epoch:
            return
        if old is None:
            old = shard_map_mod.ShardMap.initial(
                self.num_row, self._zoo.num_servers,
                active=self._init_active)
        moved = old.diff_moved(smap)
        for old_sid in sorted({m[2] for m in moved}):
            self.note_shard_moved(old_sid)
        self._shard_map = smap
        if self._replica_router is not None:
            if moved or (old is not None and old.epoch > 0) \
                    or smap.epoch > 0:
                self._replica_router.deactivate()
            else:
                self._replica_router.reconcile(alive_sids)

    def shard_epoch(self) -> int:
        return self._shard_map.epoch if self._shard_map is not None \
            else -1

    def shard_owner_sids(self):
        return self._shard_map.owner_sids() \
            if self._shard_map is not None else None

    def shard_layout(self):
        smap = self._shard_map
        if smap is None:
            return None
        return (smap.bounds.tolist(), smap.owners.tolist())

    def reshard_space(self) -> int:
        """Dense host-path matrix tables reshard at row granularity;
        sparse tables do not (their per-consumer dirty bitmaps are
        keyed to the frozen layout — the server NACKs a Begin and the
        controller rolls the move back)."""
        return 0 if self.is_sparse else self.num_row

    def observed_versions(self) -> Dict[int, int]:
        """Latest shard version this worker has OBSERVED, per server id
        (-1 before any reply). Serving-tier metadata (docs/SERVING.md):
        staleness is measured against these, exactly as the client
        cache measures it."""
        sids = range(self._num_server) if self._shard_map is None \
            else self._shard_map.owner_sids()
        return {int(s): self._version_tracker.latest(int(s))
                for s in sids}

    def _check_row_ids(self, row_ids: np.ndarray) -> None:
        """Fail fast in the CALLER on out-of-range ids. partition() runs
        inside the worker actor, where an exception is swallowed after
        reset(msg_id, 0) — the caller would see a 'successful' request
        backed by uninitialized memory (stray negative) or block forever
        on a shard routed to server -1 (negative id in a vector)."""
        if row_ids.size:
            lo, hi = int(row_ids.min()), int(row_ids.max())
            CHECK(lo >= 0 and hi < self.num_row,
                  "row ids out of range [0, num_row)")

    def _check_frozen_layout(self, what: str) -> None:
        """Device-resident fast paths bake the frozen per-server
        layout into shapes and program caches (per-server segments,
        broadcast masks, fused jits) — they cannot follow a live map.
        Elastic clusters use the host row path; fail in the CALLER."""
        CHECK(self._shard_map is None,
              f"{what} needs the frozen shard layout — this table "
              f"adopted a dynamic shard map (docs/SHARDING.md)")

    # -- Get API (ref: matrix_table.cpp:58-105) --
    def get(self, out: Optional[np.ndarray] = None) -> np.ndarray:
        self.retrying_wait(lambda: self.get_async(out))
        return self._dest

    def get_async(self, out: Optional[np.ndarray] = None) -> int:
        if out is None:
            # Sparse whole-table gets return only dirty rows, so a fresh
            # destination must be zeroed or the clean rows would surface
            # uninitialized memory; callers wanting incremental semantics
            # should pass a persistent out buffer.
            alloc = np.zeros if self.is_sparse else np.empty
            out = alloc((self.num_row, self.num_col), self.dtype)
        CHECK(out.shape == (self.num_row, self.num_col), "bad output shape")
        if self._shard_map is not None and not self.is_sparse:
            # Dynamic map: the whole-table sentinel's reply placement
            # assumes the frozen per-server offsets — route as an
            # all-rows row Get instead (replies carry keys, placement
            # is layout-free). Costs the id vector on the wire; full-
            # table pulls on an elastically resharded table are not a
            # hot path (docs/SHARDING.md).
            return self.get_rows_async(
                np.arange(self.num_row, dtype=np.int32), out)
        self._dest, self._dest_rows, self._device_shards = out, None, None
        return self._request_get(Blob(_ALL_KEY.view(np.uint8)))

    def get_rows(self, row_ids, out: Optional[np.ndarray] = None
                 ) -> np.ndarray:
        self.retrying_wait(lambda: self.get_rows_async(row_ids, out))
        return self._dest

    def get_rows_async(self, row_ids,
                       out: Optional[np.ndarray] = None) -> int:
        row_ids = np.ascontiguousarray(row_ids, dtype=np.int32).reshape(-1)
        self._check_row_ids(row_ids)
        if out is None:
            out = np.empty((row_ids.size, self.num_col), self.dtype)
        CHECK(out.shape == (row_ids.size, self.num_col), "bad output shape")
        self._dest = out
        # The requested id vector, kept for reply placement. Ids may
        # repeat (e.g. power-of-two padded row sets repeat the last id);
        # every requested position gets its id's row.
        self._dest_rows = row_ids
        self._device_shards = None
        if self._live_cache() is not None:
            # Partial-hit serve: fresh rows fill their positions
            # locally; only the MISSING unique rows go to the wire (the
            # reply placement already handles subset keys). A fully
            # fresh request never leaves the process.
            missing = self._row_cache.fetch_into(row_ids, out)
            if missing.size == 0:
                return self._local_done()
            # Dedup: missing rows already being fetched by an in-flight
            # prefetch — defer onto its completion instead of issuing a
            # second wire message for the same rows.
            joined = self._join_inflight(missing, row_ids, out)
            if joined is not None:
                return joined
            return self._request_get(Blob(missing.view(np.uint8)))
        return self._request_get(Blob(row_ids.view(np.uint8)))

    # -- serving-tier read (serving/frontend.py, docs/SERVING.md) --
    def read_rows_versioned(self, row_ids, out: Optional[np.ndarray]
                            = None):
        """``get_rows`` plus the version metadata an inference response
        must carry: ``(values, meta)`` where meta holds

        - ``served_version``: the MINIMUM fetch version among the
          requested rows (how old the oldest byte served is);
        - ``latest_version``: the newest shard version this worker has
          observed among the shards the request touched;
        - ``max_staleness``: the largest per-row (shard latest - row
          fetch version) gap — by the cache's freshness invariant this
          never exceeds ``staleness_bound`` at serve time;
        - ``staleness_bound``: the active ``-max_get_staleness`` bound
          (0 = cache disabled, every row crossed the wire);
        - ``cache_hit``: True when the whole request was served locally
          (no wire message at all);
        - ``rows_requested`` / ``rows_cached``: unique rows asked for
          and how many of them the cache covered (row-granular
          coverage — a partial hit fetches only the remainder).

        The shard latests are read BEFORE the get and the per-row
        versions AFTER it: versions only ever grow, so every served
        row passed its freshness check against a latest AT LEAST the
        pre-read (``v >= latest_at_lookup - bound >= pre_latest -
        bound``), and a wire-fetched row's version postdates the
        pre-read entirely — the reported ``max_staleness <=
        staleness_bound`` invariant is race-free even while a trainer
        pushes Adds concurrently. (Reading latest AFTER the get would
        measure rows against observations the serve never saw and
        overshoot the bound spuriously.)

        Same concurrency contract as ``get_rows``: one Get in flight
        per table — the serving frontend serializes calls per table.
        """
        row_ids = np.ascontiguousarray(row_ids,
                                       dtype=np.int32).reshape(-1)
        uniq = np.unique(row_ids)
        sids = self._server_of_rows(uniq)
        latest_by_sid = {int(s): self._version_tracker.latest(int(s))
                         for s in np.unique(sids)}
        cache = self._live_cache()
        hits_before = cache.hits if cache is not None else 0
        rows_hit_before = cache.rows_hit if cache is not None else 0
        values = self.get_rows(row_ids, out)
        cache_hit = (cache is not None
                     and cache.hits == hits_before + 1)
        # Row-granular coverage: how many of the requested unique rows
        # the cache served locally (the miss fetched only the rest).
        # Exact under the serving frontend's per-table serialization —
        # fetch_into is the only rows_hit writer and only get paths
        # call it.
        rows_cached = (cache.rows_hit - rows_hit_before
                       if cache is not None else 0)
        latest = max(latest_by_sid.values(), default=-1)
        served = latest
        max_stale = 0
        if cache is not None:
            versions = cache.versions_of(uniq)
            for r, s in zip(uniq, sids):
                v = versions.get(int(r))
                if v is None:
                    continue  # wire-fetched fresh / evicted: staleness 0
                served = min(served, v)
                max_stale = max(max_stale,
                                latest_by_sid[int(s)] - v)
                latest = max(latest, v)  # a fetch newer than the
                # pre-read keeps served <= latest consistent
        return values, {
            "served_version": int(served),
            "latest_version": int(latest),
            "max_staleness": int(max(max_stale, 0)),
            "staleness_bound": int(cache.bound
                                   if cache is not None else 0),
            "cache_hit": bool(cache_hit),
            "rows_requested": int(uniq.size),
            "rows_cached": int(rows_cached)}

    def read_rows_scatter(self, row_ids):
        """Concurrent scatter-gather serving read (docs/SERVING.md
        fleet section): unlike ``get_rows``/``read_rows_versioned`` —
        which share the table's one-get-in-flight destination
        registers and therefore serialize — each call owns its buffers
        end to end, so any number of serving threads may read
        concurrently while a trainer Adds.

        The missing (cache-cold) rows fan out as ONE sub-request per
        owning server shard; ``partition`` routes each exactly as a
        normal Get (replica striping, repair machinery, version
        stamps all apply), but a failure — dead shard owner, RPC
        timeout — is contained to that sub-request's row group
        instead of failing the whole read.

        Returns ``(values, info)``: ``values`` is ``[n, num_col]``
        over the SORTED UNIQUE requested rows ``info["rows"]``;
        ``info`` additionally carries per-row ``versions`` (fetch
        version, -1 = failed/unstamped), ``owners`` (owning server
        ids at issue time), ``cached`` (served locally), the
        pre-fetch ``latest_by_sid`` snapshot (read BEFORE any fetch,
        the ``read_rows_versioned`` anchoring rule, so per-row
        ``latest_by_sid[owner] - version <= staleness bound`` is
        race-free under concurrent Adds), ``failed`` (sorted unique
        row ids whose sub-request failed — their positions in
        ``values`` are UNDEFINED), ``failed_fatal`` (the subset whose
        failure was NOT a typed retryable one — callers map per-row:
        retryable rows back off and re-issue, e.g. HTTP 503 +
        Retry-After) and ``retryable`` (no fatal rows at all)."""
        CHECK(not self.is_sparse,
              "scatter reads are for dense host-path tables")
        rows = np.unique(np.ascontiguousarray(
            row_ids, dtype=np.int32).reshape(-1))
        self._check_row_ids(rows)
        n = rows.size
        out = np.empty((n, self.num_col), self.dtype)
        owners = self._server_of_rows(rows)
        # Generation AND shard latests are read BEFORE any fetch (the
        # read_rows_versioned anchoring rule): values fetched across a
        # concurrent reshard/rejoin get tagged with the OLD generation,
        # so a derived cache storing them invalidates — tagging after
        # the fetch could certify pre-move values as current.
        generation = self.cache_generation()
        latest_by_sid = {int(s): self._version_tracker.latest(int(s))
                         for s in np.unique(owners)}
        versions = np.full(n, -1, np.int64)
        cached = np.zeros(n, bool)
        cache = self._live_cache()
        missing = rows
        if cache is not None:
            missing = cache.fetch_into(rows, out)
            if missing.size < n:
                hit_pos = np.flatnonzero(~np.isin(rows, missing))
                cached[hit_pos] = True
                vmap = cache.versions_of(rows[hit_pos])
                for p in hit_pos:
                    # A row evicted between fetch_into and versions_of
                    # reports the shard latest (staleness 0) — the
                    # read_rows_versioned precedent.
                    versions[p] = vmap.get(
                        int(rows[p]), latest_by_sid[int(owners[p])])
        failed_groups: List[np.ndarray] = []
        fatal_groups: List[np.ndarray] = []
        if missing.size:
            entry = _ScatterRead(rows, out, versions)
            group_sids = self._server_of_rows(missing)
            groups = []
            for sid in np.unique(group_sids):
                grp = np.ascontiguousarray(missing[group_sids == sid])
                msg_id = self._new_request()
                self._sg[msg_id] = entry
                groups.append((msg_id, grp))
            for msg_id, grp in groups:
                self._send_request(MsgType.Request_Get,
                                   [Blob(grp.view(np.uint8))], msg_id)
            try:
                for msg_id, grp in groups:
                    try:
                        self.wait(msg_id)
                    except (PeerLostError, RpcTimeoutError):
                        failed_groups.append(grp)
                    except TableRequestError:
                        # Non-retryable: kept SEPARATE from the
                        # retryable groups so a caller can decide per
                        # ROW — one fatal group must not turn another
                        # group's transient failure into a hard error.
                        failed_groups.append(grp)
                        fatal_groups.append(grp)
                    finally:
                        self._sg.pop(msg_id, None)
            finally:
                # ClusterAborted mid-loop must not strand later
                # entries (pop is idempotent).
                for msg_id, _ in groups:
                    self._sg.pop(msg_id, None)
        failed = np.unique(np.concatenate(failed_groups)) \
            .astype(np.int32) if failed_groups \
            else np.empty(0, np.int32)
        failed_fatal = np.unique(np.concatenate(fatal_groups)) \
            .astype(np.int32) if fatal_groups \
            else np.empty(0, np.int32)
        return out, {
            "rows": rows, "versions": versions, "owners": owners,
            "cached": cached, "latest_by_sid": latest_by_sid,
            "failed": failed, "failed_fatal": failed_fatal,
            "retryable": failed_fatal.size == 0,
            "generation": generation}

    # -- client-cache prefetch + in-flight Get dedup --
    def prefetch_rows_async(self, row_ids) -> int:
        """Warm the client cache for ``row_ids`` without touching the
        one-Get-in-flight destination registers: the reply routes into
        the cache, so a later ``get_rows`` for (a subset of) these rows
        hits locally or joins the in-flight fetch. Double-buffering
        trainers call this for step i+1's rows while step i computes,
        overlapping wire latency with device work. Returns a request id
        (``wait`` is optional — the trainer usually never waits).
        No-op when the cache is inactive (``-max_get_staleness=0`` or
        BSP sync mode, where an extra Get would desync vector clocks)."""
        if self._live_cache() is None:
            return self._local_done()
        rows = np.unique(np.ascontiguousarray(
            row_ids, dtype=np.int32).reshape(-1))
        self._check_row_ids(rows)
        # Fetch only what the cache is actually missing — prefetching
        # already-fresh rows would waste the wire it exists to save.
        rows = self._row_cache.missing_of(rows)
        if rows.size == 0:
            return self._local_done()
        key = rows.tobytes()
        with self._pf_lock:
            existing = self._pf_by_key.get(key)
            if existing is not None:
                return existing  # identical prefetch already in flight
            msg_id = self._new_request()
            self._pf_rows[msg_id] = rows
            self._pf_by_key[key] = msg_id
            # Registered BEFORE the send: the completion sweep must be
            # able to find this prefetch however fast the reply lands.
            self.add_completion(msg_id, self._on_prefetch_done)
        count_event(client_cache.PREFETCH)
        self._send_request(MsgType.Request_Get,
                           [Blob(rows.view(np.uint8))], msg_id)
        return msg_id

    def _join_inflight(self, missing: np.ndarray, row_ids: np.ndarray,
                       out: np.ndarray) -> Optional[int]:
        """If an in-flight prefetch covers every MISSING row, defer
        this Get onto it: completion re-serves from the cache, fetching
        over the wire only what still isn't there. Either way the
        returned id completes."""
        with self._pf_lock:
            if not self._pf_rows:
                return None
            for pf_id, pf_rows in self._pf_rows.items():
                if np.isin(missing, pf_rows).all():
                    msg_id = self._new_request()
                    self._pf_joined.setdefault(pf_id, []).append(
                        (msg_id, row_ids, out))
                    count_event(client_cache.JOIN)
                    return msg_id
        return None

    def _on_prefetch_done(self, pf_id: int) -> None:
        """Prefetch completion (worker actor thread): retire the
        registry entry and settle every joined Get — from the cache for
        whatever landed/survived, forwarding a wire request only for
        rows still missing (invalidation raced the prefetch)."""
        with self._pf_lock:
            rows = self._pf_rows.pop(pf_id, None)
            if rows is not None:
                self._pf_by_key.pop(rows.tobytes(), None)
            joined = self._pf_joined.pop(pf_id, [])
        for msg_id, req_rows, out in joined:
            # count_stats=False: the joined Get already counted its
            # miss at issue time — the re-serve must not double-count.
            missing = self._row_cache.fetch_into(req_rows, out,
                                                 count_stats=False)
            if missing.size == 0:
                self.notify(msg_id)
            else:
                self._send_request(MsgType.Request_Get,
                                   [Blob(missing.view(np.uint8))],
                                   msg_id)

    def get_rows_device(self, row_ids):
        """Device-resident row pull: returns ``[k, num_col]`` as a
        ``jax.Array`` assembled from per-server device shards — zero host
        copies when the servers share the process (the TPU-native hot
        path: the reference's RequestParameter row pull,
        communicator.cpp:117-155, without ever leaving HBM)."""
        self.wait(self.get_rows_device_async(row_ids))
        return self.take_device_rows()

    def get_rows_device_async(self, row_ids) -> int:
        """Async device row pull.

        HOST ids must be non-decreasing so each server's reply is one
        contiguous segment and the result reassembles by concatenation
        (sorted-unique row sets — possibly tail-padded by repeating the
        last id — satisfy this).

        DEVICE ids (a ``jax.Array``) pass through the stack without ever
        touching the host: any shape, any order, duplicates welcome —
        the reply is the XLA gather ``table[row_ids]`` with shape
        ``row_ids.shape + (num_col,)``. This is the key enabler for
        trainers whose row sets are computed on device
        (models/wordembedding/device_train.py PS mode).

        Multi-server: splitting device ids into per-server subsets
        would need data-dependent shapes (a host sync), so instead the
        SAME id blob goes to every server; each gathers only its own
        rows (foreign rows fill 0) and the worker SUMS the replies —
        every row is owned by exactly one server, so the sum
        reassembles the exact gather. Costs one extra [k, C] pass per
        additional server, all in HBM."""
        self._check_frozen_layout("device row gets")
        if is_device_array(row_ids):
            CHECK(self._zoo.servers_in_process,
                  "device-key row gets need the servers in this "
                  "process (a serializing transport flattens the "
                  "keys to host bytes and the reply shape contract "
                  "breaks)")
            CHECK(not self._compress, "device gets bypass wire compression")
            self._dest, self._dest_rows = None, None
            self._device_shards = {}
            self._device_sum = self._num_server > 1
            return self._request_get(Blob(row_ids))
        row_ids = np.ascontiguousarray(row_ids, dtype=np.int32).reshape(-1)
        CHECK(row_ids.size > 0, "empty device row get")
        self._check_row_ids(row_ids)
        CHECK(not self._compress, "device gets bypass wire compression")
        if self._num_server > 1:
            CHECK(bool(np.all(np.diff(row_ids) >= 0)),
                  "device row gets need sorted row ids")
        self._dest, self._dest_rows = None, None
        self._device_shards = {}
        self._device_sum = False  # host-key replies CONCATENATE (a
        # stale True from an errored device-key get must not survive)
        return self._request_get(Blob(row_ids.view(np.uint8)))

    def take_device_rows(self):
        """Assembled result of the last ``get_rows_device_async`` (call
        after ``wait``); clears the reply slot. Device-key multi-server
        replies SUM (each server zero-fills foreign rows); host-key
        multi-server replies concatenate (each server returned its
        contiguous sorted segment)."""
        ordered = self.take_device_row_parts()
        if len(ordered) == 1:
            return ordered[0]
        import jax.numpy as jnp
        # Worker-thread reassembly dispatch: guarded like any other
        # multi-device program (multi-zoo mode only; no-op otherwise).
        with device_lock.guard():
            if getattr(self, "_device_sum", False):
                self._device_sum = False
                return device_lock.settle(
                    functools.reduce(jnp.add, ordered))
            return device_lock.settle(jnp.concatenate(ordered, axis=0))

    def get_rows_device_segments_async(self, segments) -> int:
        """Pre-segmented device row pull: ``segments`` is one device id
        vector PER SERVER (the caller computed per-server slices of its
        sorted ids — e.g. inside the program that produced them, where
        the searchsorted bounds are free). Each server receives ONLY
        its segment; out-of-range entries (slice slack / padding)
        gather as zero rows via the server's bounded gather. Replies
        come back keyed by server id — consume with
        ``take_device_row_parts`` and reassemble in the consumer's jit.

        This is the per-server work-conserving form of the device-key
        protocol: per-server gather cost follows the SEGMENT size, not
        the full id count (ref per-server bucketing contract:
        matrix_table.cpp:234-315)."""
        self._check_frozen_layout("segmented device gets")
        CHECK(self._zoo.servers_in_process,
              "segmented device gets need the servers in this process")
        CHECK(len(segments) == self._num_server,
              "one segment per server")
        CHECK(all(is_device_array(s) for s in segments),
              "segments must be device arrays")
        # Shape/dtype violations would otherwise surface inside the
        # server actor, where _safe_dispatch swallows the exception and
        # the caller hangs in wait() forever — fail in the CALLER.
        for seg in segments:
            CHECK(np.dtype(seg.dtype) == np.int32 and len(seg.shape) == 1,
                  "segments must be 1-D int32 id vectors")
        CHECK(not self._compress, "device gets bypass wire compression")
        self._dest, self._dest_rows = None, None
        self._device_shards = {}
        self._device_sum = False
        return self.get_async_raw(Blob(_SEGMENTED_KEY.view(np.uint8)),
                                  [Blob(s) for s in segments])

    def add_rows_device_segments_async(self, segments, deltas,
                                       option: Optional[AddOption] = None
                                       ) -> int:
        """Pre-segmented device row push: per-server (ids, delta) pairs;
        each server scatter-adds only its segment (foreign/padding rows
        mask out-of-range and drop). Same stateless-updater contract as
        ``add_rows_async`` device keys."""
        self._check_frozen_layout("segmented device adds")
        CHECK(self._zoo.servers_in_process,
              "segmented device adds need the servers in this process")
        CHECK(len(segments) == self._num_server
              and len(deltas) == self._num_server,
              "one (segment, delta) pair per server")
        CHECK(self._updater_stateless,
              "device-key row adds need a stateless updater "
              "(default/sgd): duplicate ids must sum")
        for seg, delta in zip(segments, deltas):
            CHECK(is_device_array(seg) and is_device_array(delta),
                  "segments and deltas must be device arrays")
            # Fail in the CALLER: inside the server actor these would
            # be swallowed by _safe_dispatch and the Add ack never
            # comes, hanging the caller in wait().
            CHECK(np.dtype(seg.dtype) == np.int32 and len(seg.shape) == 1,
                  "segments must be 1-D int32 id vectors")
            CHECK(np.dtype(delta.dtype) == self.dtype,
                  "segment delta dtype must match the table dtype")
            CHECK(tuple(delta.shape) ==
                  tuple(seg.shape) + (self.num_col,),
                  "bad segment delta shape")
        blobs = ([Blob(_SEGMENTED_KEY.view(np.uint8))]
                 + [Blob(s) for s in segments]
                 + [Blob(d) for d in deltas]
                 + [self._option_blob(option)])
        tok = self._cache_begin_add(None)  # device ids: block globally
        mid = self.request_async_raw(MsgType.Request_Add, blobs)
        self._cache_resolve_on(mid, tok)
        return mid

    def take_device_row_parts(self):
        """The raw per-server reply shards of the last device get
        WITHOUT assembling them — a consumer that feeds them into its
        own jit can fold the multi-server sum into that program instead
        of paying a separate device op (each eager dispatch costs
        milliseconds over a tunneled link). Replies carry the origin
        server id, so parts return in SERVER order (segmented pulls
        rely on this; the broadcast sum is order-independent)."""
        shards = self._device_shards
        CHECK(shards is not None and len(shards) > 0,
              "no device row get outstanding")
        self._device_shards = None
        return [shards[sid] for sid in sorted(shards)]

    def _request_get(self, keys: Blob) -> int:
        extra = []
        if self.is_sparse:
            # Sparse gets carry the asking worker's id
            # (ref: sparse_matrix_table.h:27-43).
            extra.append(GetOption(self._zoo.worker_id).to_blob())
        return self.get_async_raw(keys, extra)

    # -- Add API (ref: matrix_table.cpp:110-147) --
    def add(self, delta, option: Optional[AddOption] = None) -> None:
        self.retrying_wait(lambda: self.add_async(delta, option))

    def add_async(self, delta, option: Optional[AddOption] = None) -> int:
        """Whole-table add; device arrays stay on device end to end."""
        if not is_device_array(delta):
            delta = np.ascontiguousarray(delta, self.dtype).reshape(-1)
        CHECK(int(np.prod(delta.shape)) == self.num_row * self.num_col,
              "bad delta size")
        if self._shard_map is not None and not self.is_sparse \
                and not is_device_array(delta):
            # Dynamic map: the sentinel add slices per the frozen
            # offsets — route as an all-rows row Add instead (keys
            # travel, the partition buckets by the live map).
            return self.add_rows_async(
                np.arange(self.num_row, dtype=np.int32),
                delta.reshape(self.num_row, self.num_col), option)
        CHECK(self._shard_map is None or self.is_sparse
              or not is_device_array(delta),
              "whole-table device adds need the frozen shard layout "
              "(live resharding serves the host row path)")
        tok = self._cache_begin_add(None)
        mid = self.add_async_raw(Blob(_ALL_KEY.view(np.uint8)),
                                 Blob(delta),
                                 self._option_blob(option))
        self._cache_resolve_on(mid, tok)
        return mid

    def _cache_begin_add(self, row_ids: Optional[np.ndarray]):
        """Block the client-cache slots this Add dirties (None = whole
        table) until its ack resolves them — read-your-writes. NOT
        gated on _live_cache(): an INACTIVE cache still needs the ack
        to fence its shard floors, or a live activation racing an
        in-flight add could serve the pre-add value afterwards
        (RowCache.begin_add's fence token)."""
        cache = self._row_cache
        if cache is None:
            return None
        return cache.begin_add(row_ids)

    def _cache_resolve_on(self, msg_id: int, token) -> None:
        if token is not None:
            self.add_completion(
                msg_id,
                lambda _mid, tok=token: self._row_cache.finish_add(tok))

    def add_rows(self, row_ids, delta,
                 option: Optional[AddOption] = None) -> None:
        self.retrying_wait(
            lambda: self.add_rows_async(row_ids, delta, option))

    def add_rows_async(self, row_ids, delta,
                       option: Optional[AddOption] = None) -> int:
        """Row-delta push. A ``jax.Array`` delta stays on device end to
        end when the servers share the process (scatter-add straight from
        HBM — the device twin of the reference's AddDeltaParameter,
        communicator.cpp:157-249). DEVICE row_ids (single-server,
        in-process tables) keep the ids in HBM too: any shape; delta
        must be shaped ``row_ids.shape + (num_col,)``. Duplicate ids
        SUM only under stateless updaters (default/sgd) — the engine
        rejects stateful rules on this path."""
        if is_device_array(row_ids):
            # Multi-server: the same ids+delta blobs go to every server;
            # each scatter-adds only its own rows (foreign rows masked
            # out-of-range and dropped), so the union applies the full
            # delta exactly once.
            self._check_frozen_layout("device-key row adds")
            CHECK(self._zoo.servers_in_process,
                  "device-key row adds need the servers in this "
                  "process")
            CHECK(self._updater_stateless,
                  "device-key row adds need a stateless updater "
                  "(default/sgd): duplicate ids must sum")
            CHECK(is_device_array(delta),
                  "device-key adds need a device delta")
            CHECK(tuple(delta.shape) ==
                  tuple(row_ids.shape) + (self.num_col,),
                  "bad device delta shape")
            # Device-resident ids cannot be enumerated without a host
            # sync — block the whole cache until the ack.
            tok = self._cache_begin_add(None)
            mid = self.add_async_raw(Blob(row_ids), Blob(delta),
                                     self._option_blob(option))
            self._cache_resolve_on(mid, tok)
            return mid
        row_ids = np.ascontiguousarray(row_ids, dtype=np.int32).reshape(-1)
        self._check_row_ids(row_ids)
        if self._one_bit or self._lossy:
            # The error-feedback gather/write-back breaks on duplicates;
            # the chunk encoder's own CHECK fires inside the worker
            # actor — raise here in the caller instead.
            CHECK(np.unique(row_ids).size == row_ids.size,
                  "error-feedback row pushes need unique row ids")
        if not is_device_array(delta):
            delta = np.ascontiguousarray(delta, self.dtype).reshape(-1)
        CHECK(int(np.prod(delta.shape)) == row_ids.size * self.num_col,
              "bad delta size")
        tok = self._cache_begin_add(row_ids)
        mid = self.add_async_raw(Blob(row_ids.view(np.uint8)),
                                 Blob(delta),
                                 self._option_blob(option))
        self._cache_resolve_on(mid, tok)
        return mid

    def _option_blob(self, option: Optional[AddOption]) -> Blob:
        if option is None:
            option = AddOption(worker_id=max(self._zoo.worker_id, 0))
        return option.to_blob()

    def _feedback_chunk(self, chunk, lo: int, hi: int,
                        rows: Optional[np.ndarray], encode) -> List[Blob]:
        """Shared error-feedback discipline for every lossy encoder
        (1-bit and the codec's quantized tiers): the previous
        quantization error for these slots is folded into the delta
        before encoding, and the new error replaces it. Row pushes need
        UNIQUE row ids — a duplicated row would gather its residual once
        per occurrence and keep only the last write-back, so the bounded-
        error invariant would silently break. ``encode`` maps a flat
        fp32 vector to (blobs, residual); residual None means the
        encoder went lossless this time (nothing remains to carry)."""
        if self._residual is None:
            self._residual = np.zeros((self.num_row, self.num_col),
                                      np.float32)
        chunk2d = np.asarray(chunk).reshape(-1, self.num_col)
        if rows is None:
            res = self._residual[lo:hi]
        else:
            CHECK(np.unique(rows).size == rows.size,
                  "error-feedback row pushes need unique row ids")
            res = self._residual[rows]
        blobs, residual = encode((chunk2d + res).reshape(-1))
        if residual is None:
            residual = np.zeros(chunk2d.size, np.float32)
        residual = residual.reshape(chunk2d.shape)
        if rows is None:
            self._residual[lo:hi] = residual
        else:
            self._residual[rows] = residual
        return blobs

    def _onebit_chunk(self, chunk: np.ndarray, lo: int, hi: int,
                      rows: Optional[np.ndarray] = None) -> List[Blob]:
        return self._feedback_chunk(chunk, lo, hi, rows, _onebit_blobs)

    def _codec_chunk(self, chunk: np.ndarray, lo: int, hi: int,
                     rows: Optional[np.ndarray] = None) -> List[Blob]:
        """Wire-codec Add chunk: lossless passthrough by default, the
        quantized tiers + error feedback under ``-wire_codec_lossy``."""
        if not self._lossy:
            return _compress_values(np.asarray(chunk))[0]
        return self._feedback_chunk(
            chunk, lo, hi, rows,
            lambda flat: _compress_values(flat, lossy=True))

    # -- partition (ref: matrix_table.cpp:234-315) --
    def partition(self, blobs, msg_type) -> Dict[int, List[Blob]]:
        if blobs[0].on_device:
            # Device-key requests: the same blob list goes to EVERY
            # server (object references — zero copies in-process); each
            # server masks foreign rows on device. Splitting the ids
            # here would need their values on the host.
            return {sid: list(blobs) for sid in range(self._num_server)}
        keys = blobs[0].as_array(np.int32)
        out: Dict[int, List[Blob]] = {}
        if keys.size == 1 and keys[0] == -3:
            # Pre-segmented device-key request: the caller already
            # split its ids per server — route segment s (and its delta
            # for adds) to server s ONLY. Layout:
            # Get: [-3, seg_0..seg_{S-1}]
            # Add: [-3, seg_0..seg_{S-1}, delta_0..delta_{S-1}, option]
            S = self._num_server
            rest = blobs[1:]
            if msg_type == MsgType.Request_Get:
                CHECK(len(rest) == S, "segmented get: one id blob "
                      "per server")
                return {s: [rest[s]] for s in range(S)}
            CHECK(len(rest) == 2 * S + 1, "segmented add: per-server "
                  "ids + deltas + option")
            return {s: [rest[s], rest[S + s], rest[2 * S]]
                    for s in range(S)}
        if keys.size == 1 and keys[0] == -4 \
                and msg_type == MsgType.Request_Get:
            # Fused add+dirty-get (a Get — it replies): single-server
            # (enforced in the caller) — the whole blob list goes to
            # server 0. A Request_Add carrying -4 falls through to the
            # stray-negative fail-fast below.
            CHECK(self._num_server == 1 and len(blobs) in (5, 6),
                  "fused add+dirty-get: [marker, rows, delta, "
                  "add_option, get_option(, device rows)] to one "
                  "server")
            return {0: list(blobs)}
        if keys.size == 1 and keys[0] < 0:
            # Only the defined sentinels may go negative; a stray
            # negative row id must fail fast here, not fan out as a
            # whole-table request with undefined server-side handling.
            CHECK(keys[0] in (-1, -2),
                  "negative key must be a whole-table sentinel (-1/-2)")
            is_add = msg_type == MsgType.Request_Add
            compress = is_add and self._compress
            values = blobs[1].typed(self.dtype) if is_add else None
            if compress and is_device_array(values):
                values = np.asarray(values)  # host bytes at the wire
            # Values may arrive flat [R*C] (host callers) or row-shaped
            # [R, C] (device deltas skip the flatten — a device reshape
            # still dispatches); slice in whichever layout they came.
            row_shaped = values is not None and np.ndim(values) == 2
            one_bit = (is_add and self._one_bit and values is not None
                       and not is_device_array(values))
            for sid in range(self._num_server):
                shard = [blobs[0]]
                if values is not None:
                    lo, hi = self._offsets[sid], self._offsets[sid + 1]
                    chunk = values[lo:hi] if row_shaped \
                        else values[lo * self.num_col:hi * self.num_col]
                    if compress:
                        shard.extend(self._codec_chunk(
                            np.asarray(chunk), lo, hi))
                    elif one_bit:
                        shard.extend(self._onebit_chunk(
                            np.asarray(chunk), lo, hi))
                    else:
                        shard.append(Blob(chunk))
                    if len(blobs) == 3:
                        shard.append(blobs[2])
                elif len(blobs) == 2:  # sparse Get: GetOption rides along
                    shard.append(blobs[1])
                out[sid] = shard
            return out

        # Row-id requests: bucket rows by owning server
        # (ref: matrix_table.cpp:267-276). Defense in depth for raw-API
        # callers: a negative id in a VECTOR would bucket to server -1
        # (misrouted shard, silent wrap or a hang) — reject here too.
        CHECK(keys.size == 0 or (int(keys.min()) >= 0
                                 and int(keys.max()) < self.num_row),
              "row ids out of range [0, num_row)")
        is_add = msg_type == MsgType.Request_Add
        dest = self._server_of_rows(keys)
        if (not is_add and self._replica_router is not None
                and self._replica_router.active):
            # Replicated (hot) rows re-route to holder servers — the
            # co-located shard when this rank hosts one, else a
            # per-row stripe across all servers (docs/SHARDING.md);
            # each holder's own rows ride the same shard message.
            # Adds never re-route — write-through keeps the owner
            # authoritative.
            rep_mask = self._replica_router.replicated_mask(keys)
            if bool(rep_mask.any()):
                dest = np.asarray(dest).copy()
                holders = self._replica_router.route(keys[rep_mask])
                # -1 = chosen holder declared dead: fall back to the
                # row's OWNER (the original range dest) — correct by
                # construction, merely unbalanced until rejoin.
                dest[rep_mask] = np.where(holders >= 0, holders,
                                          dest[rep_mask])
                self._note_replica_routed(keys, dest, rep_mask)
        values = dev_values = None
        if is_add:
            if blobs[1].on_device and not self._compress:
                # Device delta: slice per-server segments in HBM (keys
                # must be sorted for multi-server so segments are
                # contiguous; single-server always passes whole).
                dev_values = _shaped_rows(blobs[1].typed(self.dtype),
                                          keys.size, self.num_col)
                if self._num_server > 1:
                    CHECK(bool(np.all(np.diff(dest) >= 0)),
                          "device row adds need sorted row ids")
            else:
                values = blobs[1].as_array(self.dtype).reshape(
                    keys.size, self.num_col)
        for sid in np.unique(dest):
            mask = dest == sid
            shard = [Blob(np.ascontiguousarray(keys[mask]).view(np.uint8))]
            if dev_values is not None:
                lo, hi = np.searchsorted(dest, [sid, sid + 1])
                shard.append(Blob(dev_values[lo:hi]))
                if len(blobs) == 3:
                    shard.append(blobs[2])
            elif values is not None:
                chunk = np.ascontiguousarray(values[mask])
                if self._compress:
                    shard.extend(self._codec_chunk(chunk, 0, 0,
                                                   rows=keys[mask]))
                elif self._one_bit:
                    shard.extend(self._onebit_chunk(chunk, 0, 0,
                                                    rows=keys[mask]))
                else:
                    shard.append(Blob(chunk))
                if len(blobs) == 3:
                    shard.append(blobs[2])
            elif len(blobs) == 2:  # sparse GetOption
                shard.append(blobs[1])
            out[int(sid)] = shard
        return out

    def get_dirty_device(self):
        """Sparse dirty-row pull with a DEVICE-resident reply: returns
        ``(row_ids, values)`` where values is a ``jax.Array`` in HBM —
        the staleness bookkeeping stays host-side (it is a bitmap), but
        the row payload never crosses the host boundary. This is the
        TPU-native form of the reference's dirty-only Get
        (ref: sparse_matrix_table.cpp:226-258), whose host-buffer reply
        is otherwise bounded by host<->device bandwidth."""
        CHECK(self.is_sparse, "dirty gets are for sparse tables")
        CHECK(self._zoo.servers_in_process,
              "device dirty gets need the servers in this process "
              "(the reply payload is a live device array)")
        self._dest, self._dest_rows = None, None
        self._device_shards = {}
        self._device_sum = False
        self._device_shard_ids = {}
        self.wait(self._request_get(
            Blob(_ALL_KEY_DEVICE_REPLY.view(np.uint8))))
        shards, ids = self._device_shards, self._device_shard_ids
        self._device_shards, self._device_shard_ids = None, None
        CHECK(len(shards) == self._num_server,
              "dirty get: one reply per server")
        if self._num_server == 1:
            return ids[0], shards[0]
        # Each server's dirty set is sorted within its own row range and
        # ranges are ordered by server id, so concatenation in server
        # order is globally sorted — same contract as the single-server
        # reply (ref: sparse_matrix_table.cpp:226-258 per-server dirty
        # scan; reassembly is the worker's).
        import jax.numpy as jnp
        order = sorted(shards)
        with device_lock.guard():
            joined = device_lock.settle(
                jnp.concatenate([shards[s] for s in order], axis=0))
        return np.concatenate([ids[s] for s in order]), joined

    def add_get_dirty_device(self, row_ids, delta,
                             option: Optional[AddOption] = None,
                             get_worker: Optional[int] = None,
                             row_ids_device=None):
        """FUSED add + dirty pull: apply a row delta, then return THIS
        worker's dirty rows — the exact composition of ``add_rows`` and
        ``get_dirty_device``, but one request and ONE device program
        server-side (the separate pair is bound by two big-argument
        program launches on a tunneled device). Single in-process
        server, async mode (a hidden add inside a Get would bypass the
        BSP vector clocks). ``option`` names the adder as usual;
        ``get_worker`` the dirty-set consumer (default: this worker).

        ``row_ids_device``: optional DEVICE mirror of ``row_ids`` — a
        caller pushing the same (or precomputed) row set repeatedly
        keeps the ids in HBM, skipping the per-call id upload that
        otherwise rides the tunnel (host ids are still required for
        the dirty bookkeeping, which is a host bitmap). Stateless
        updaters only, as with device-key adds."""
        CHECK(self.is_sparse, "fused add+dirty-get is for sparse tables")
        CHECK(self._num_server == 1 and self._zoo.servers_in_process,
              "fused add+dirty-get is a single-server extension with "
              "the server in this process (multi-server callers "
              "compose add_rows + get_dirty_device)")
        CHECK(not bool(get_flag("sync", False)),
              "fused add+dirty-get is async-only: the embedded add "
              "would bypass the BSP vector clocks")
        row_ids = np.ascontiguousarray(row_ids,
                                       dtype=np.int32).reshape(-1)
        self._check_row_ids(row_ids)
        CHECK(is_device_array(delta), "fused add needs a device delta")
        CHECK(tuple(delta.shape) == (row_ids.size, self.num_col),
              "bad delta shape")
        if get_worker is None:
            get_worker = max(self._zoo.worker_id, 0)
        CHECK(0 <= int(get_worker) < self._num_consumers,
              "get_worker out of the consumer-slot range (the "
              "server-side CHECK would fire inside the actor and the "
              "caller would hang)")
        self._dest, self._dest_rows = None, None
        self._device_shards = {}
        self._device_sum = False
        self._device_shard_ids = {}
        blobs = [Blob(_ADD_GET_DIRTY_KEY.view(np.uint8)),
                 Blob(row_ids.view(np.uint8)), Blob(delta),
                 self._option_blob(option),
                 GetOption(int(get_worker)).to_blob()]
        if row_ids_device is not None:
            CHECK(is_device_array(row_ids_device),
                  "row_ids_device must be a device array")
            # The mirror must arrive PRE-PADDED to the same power-of-two
            # bucket the host path uses (``pad_ids(row_ids, num_row)``
            # then ``jnp.asarray``): the server feeds it straight into
            # the fused jit, so an exact-k mirror would compile one
            # program per distinct k (10s+ per recompile on the
            # tunneled platform) instead of once per bucket width.
            # Padding ids must be >= num_row: they scatter zero rows
            # into dead storage and are dropped by every gather.
            bucket = bucket_size(row_ids.size)
            CHECK(tuple(row_ids_device.shape) == (bucket,)
                  and np.dtype(row_ids_device.dtype) == np.int32,
                  "row_ids_device must mirror row_ids padded to the "
                  "host bucket ([bucket_size(k)] int32; build it as "
                  "jnp.asarray(pad_ids(row_ids, num_row)))")
            CHECK(self._updater_stateless,
                  "device-id fused adds need a stateless updater")
            if get_flag("verify_device_ids") and not self._mirror_verified:
                # A mirror that disagrees with the host ids would mark
                # one row set dirty and scatter the delta at ANOTHER —
                # silent corruption. Opt-in first-call readback turns
                # that into a loud failure (one device->host transfer).
                host_mirror = np.asarray(row_ids_device)
                CHECK(np.array_equal(host_mirror[:row_ids.size], row_ids),
                      "-verify_device_ids: row_ids_device disagrees "
                      "with the host row ids")
                CHECK(row_ids.size == bucket
                      or int(host_mirror[row_ids.size:].min())
                      >= self.num_row,
                      "-verify_device_ids: mirror padding ids must be "
                      ">= num_row (in-range padding would scatter into "
                      "live rows)")
                self._mirror_verified = True
            blobs.append(Blob(row_ids_device))
        self.wait(self.request_async_raw(MsgType.Request_Get, blobs))
        shards, ids = self._device_shards, self._device_shard_ids
        self._device_shards, self._device_shard_ids = None, None
        CHECK(len(shards) == 1, "fused dirty get: one reply")
        return ids[0], shards[0]

    # -- device-resident whole-table Get (shards stay in HBM) --
    def get_device(self):
        self._check_frozen_layout("device whole-table gets")
        CHECK(not self.is_sparse,
              "device get is for dense tables (sparse replies are ragged)")
        self._dest, self._dest_rows, self._device_shards = None, None, {}
        self._device_sum = False
        self.wait(self._request_get(Blob(_ALL_KEY.view(np.uint8))))
        return self.take_device_rows()

    # -- replies (ref: matrix_table.cpp:317-341) --
    def process_reply_get(self, reply_blobs: List[Blob]) -> None:
        if (self._reply_msg_id >= 0
                and self._pf_rows.get(self._reply_msg_id) is not None):
            # Prefetch reply shard: one server's [keys, values] segment
            # routes into the cache ONLY — the destination registers
            # belong to whatever real Get may be concurrently in
            # flight. (Prefetches are dense host row Gets, never codec-
            # compressed or device-resident.)
            keys = reply_blobs[0].as_array(np.int32)
            values = reply_blobs[1].as_array(self.dtype).reshape(
                keys.size, self.num_col)
            ent = self._replica_sent.get(self._reply_msg_id)
            if ent is not None:
                ent.pop(self._reply_server, None)
                if not ent:
                    del self._replica_sent[self._reply_msg_id]
            n_rep = self._reply_replica_rows
            if self._row_cache is not None:
                n_own = keys.size - n_rep
                self._row_cache.store(keys[:n_own], values[:n_own],
                                      self._reply_version,
                                      self._reply_server)
                # Replica groups cache under their OWNER at the group's
                # version floor; groups below the read-your-writes
                # floor and holder misses just stay uncached — a
                # prefetch never repairs (a later real Get fetches
                # whatever is still missing).
                for owner, floor, gkeys, gvals in \
                        self._replica_groups(keys, values, reply_blobs):
                    if floor < self.add_floor(owner):
                        continue
                    self._version_tracker.note(owner, floor)
                    self._row_cache.store(gkeys, gvals, floor, owner)
            return
        sg = self._sg.get(self._reply_msg_id) \
            if self._reply_msg_id >= 0 else None
        if sg is not None:
            # Scatter-gather sub-request shard: values/versions land in
            # the request's own buffers (never the shared _dest
            # registers), replica groups and repairs handled exactly
            # like the classic path.
            self._process_sg_reply(sg, reply_blobs)
            return
        if reply_blobs[0].on_device:
            # Device-key reply: values arrive shaped
            # row_ids.shape + (num_col,), still in HBM — keyed by the
            # origin server id (broadcast replies sum, order-free;
            # segmented replies reassemble positionally, so server
            # attribution matters).
            CHECK(self._device_shards is not None,
                  "device reply with no device get outstanding")
            sid = int(reply_blobs[2].as_array(np.int32)[0]) \
                if len(reply_blobs) >= 3 else len(self._device_shards)
            self._device_shards[sid] = reply_blobs[1].typed(self.dtype)
            return
        keys = reply_blobs[0].as_array(np.int32)
        if keys.size == 1 and keys[0] == -1:
            server_id = int(reply_blobs[2].as_array(np.int32)[0])
            if self._device_shards is not None:  # device-resident get
                self._device_shards[server_id] = \
                    reply_blobs[1].typed(self.dtype)
                return
            CHECK(self._dest is not None,
                  "Get reply with no outstanding destination — only one "
                  "Get may be in flight per table (as in the reference)")
            lo, hi = self._offsets[server_id], self._offsets[server_id + 1]
            values = reply_blobs[1].as_array(self.dtype)
            self._dest[lo:hi] = values.reshape(hi - lo, self.num_col)
            return
        if self._device_shards is not None:
            # Device row pull: keep the server's gather result in HBM,
            # keyed by the owning server (a shard carries one server's
            # contiguous key segment). The dirty-device flow replies
            # [ids, values, server_id] — possibly ZERO rows, so the
            # server id cannot be inferred from the keys.
            if len(reply_blobs) >= 3:
                sid = int(reply_blobs[2].as_array(np.int32)[0])
            else:
                sid = 0 if keys.size == 0 else \
                    int(min(keys[0] // self._row_length,
                            self._num_server - 1))
            self._device_shards[sid] = _shaped_rows(
                reply_blobs[1].typed(self.dtype), keys.size, self.num_col)
            if self._device_shard_ids is not None:
                self._device_shard_ids[sid] = keys
            return
        if self._compress and _is_codec_blob(reply_blobs[1]):
            values = _decompress_values(
                reply_blobs[1],
                self.dtype).reshape(keys.size, self.num_col)
        else:
            # A 3-blob non-codec reply here is the REMOVED float64-pair
            # layout ([keys, pairs, size_record] from a pre-codec
            # build) — fail loudly; reshaping pair bytes as raw values
            # could silently corrupt when the byte counts coincide.
            CHECK(not self._compress or len(reply_blobs) == 2,
                  "legacy float64-pair reply: the pre-codec wire "
                  "format was removed (docs/WIRE_FORMAT.md)")
            values = reply_blobs[1].as_array(self.dtype).reshape(
                keys.size, self.num_col)
        requested = None
        ent = self._replica_sent.get(self._reply_msg_id)
        if ent is not None:
            # This may be a holder shard of a replica-routed request —
            # even a reply with ZERO replica rows (the holder missed
            # everything) must diff against what was routed to it, or
            # the missing positions would silently stay unfilled.
            requested = ent.pop(self._reply_server, None)
            if not ent:
                del self._replica_sent[self._reply_msg_id]
        if self._reply_replica_rows or requested is not None:
            self._process_replica_reply(keys, values, reply_blobs,
                                        requested)
            return
        if self._row_cache is not None and self._dest_rows is not None:
            # Wire-path population: every real row Get refreshes the
            # cache (and, via the reply context, the version tracker) —
            # prefetch is an accelerant, not a requirement, for hits.
            self._row_cache.store(keys, values, self._reply_version,
                                  self._reply_server)
        if self._dest_rows is None:
            # Sparse whole-table get: dirty rows land at their global index.
            self._dest[keys] = values
        else:
            # Vectorized placement: every requested position whose row id
            # appears in THIS reply shard gets that row's value (a shard
            # carries one server's key subset — possibly only the cache-
            # missing rows of a partial hit; other positions are left
            # for sibling shards or were cache-filled). Requests may
            # repeat ids — power-of-two padded row sets repeat the last
            # id thousands of times, so per-position Python loops go
            # quadratic and a single reply can burn minutes.
            client_cache.place_rows(keys, values, self._dest_rows,
                                    self._dest)

    # -- hot-shard replication: worker side (runtime/replica.py,
    #    docs/SHARDING.md; all on the worker actor thread) --
    def apply_replica_map(self, epoch: int, rows) -> None:
        if self._replica_router is not None:
            self._replica_router.apply(epoch, rows)

    def replica_server_dead(self, server_id: int) -> None:
        if self._replica_router is not None:
            self._replica_router.mark_dead(server_id)

    def replica_server_alive(self, server_id: int) -> None:
        if self._replica_router is not None and server_id >= 0:
            self._replica_router.mark_alive(server_id)

    def replica_reconcile(self, alive_sids) -> None:
        if self._replica_router is not None:
            self._replica_router.reconcile(alive_sids)

    def _note_replica_routed(self, keys: np.ndarray, dest: np.ndarray,
                             rep_mask: np.ndarray) -> None:
        """Record which FOREIGN rows (owner != holder) the current
        request routed to which holder — keyed by the request id the
        worker actor set around ``partition`` — so each holder's reply
        can be diffed for repairs. Rows a holder itself owns need no
        bookkeeping (an owner always serves its rows). Entries for
        requests that error out before their reply are reaped by the
        size cap."""
        if self._partition_msg_id < 0:
            return
        owners = self._server_of_rows(keys)
        foreign = rep_mask & (dest != owners)
        if not bool(foreign.any()):
            return
        by_holder: Dict[int, np.ndarray] = {}
        for sid in np.unique(dest[foreign]):
            by_holder[int(sid)] = np.unique(
                keys[foreign & (dest == sid)]).astype(np.int32)
        while len(self._replica_sent) > 256:
            self._replica_sent.pop(next(iter(self._replica_sent)))
        self._replica_sent[self._partition_msg_id] = by_holder

    def _replica_groups(self, keys: np.ndarray, values: np.ndarray,
                        reply_blobs: List[Blob]) -> List:
        """Decode the current reply's replica descriptor (last blob)
        into ``[(owner_sid, floor_version, group_keys, group_values)]``
        — empty when the reply carries no replica rows."""
        if not self._reply_replica_rows:
            return []
        desc = reply_blobs[-1].as_array(np.int32)
        n_groups = int(desc[0])
        total = int(desc[3::3][:n_groups].sum())
        pos = keys.size - total
        out = []
        for g in range(n_groups):
            owner = int(desc[1 + 3 * g])
            floor = int(desc[2 + 3 * g]) - 1
            n_rows = int(desc[3 + 3 * g])
            out.append((owner, floor, keys[pos:pos + n_rows],
                        values[pos:pos + n_rows]))
            pos += n_rows
        return out

    def _process_replica_reply(self, keys: np.ndarray,
                               values: np.ndarray,
                               reply_blobs: List[Blob],
                               requested: Optional[np.ndarray]) -> None:
        """A holder shard's reply on the CLASSIC (one-get-in-flight)
        path: placement targets the shared destination registers."""

        def place(gkeys, gvals, version, owner):
            if self._row_cache is not None \
                    and self._dest_rows is not None:
                self._row_cache.store(gkeys, gvals, version, owner)
            if self._dest is not None and self._dest_rows is not None:
                client_cache.place_rows(gkeys, gvals, self._dest_rows,
                                        self._dest)

        self._serve_reply_groups(keys, values, reply_blobs, requested,
                                 place)

    def _process_sg_reply(self, entry: _ScatterRead,
                          reply_blobs: List[Blob]) -> None:
        """A scatter-gather sub-request's reply shard: same semantics
        as the classic path (cache population, replica-group floors,
        repair staging under the same request id), but placement goes
        to the sub-request's OWN buffers."""
        keys = reply_blobs[0].as_array(np.int32)
        values = reply_blobs[1].as_array(self.dtype).reshape(
            keys.size, self.num_col)
        requested = None
        ent = self._replica_sent.get(self._reply_msg_id)
        if ent is not None:
            requested = ent.pop(self._reply_server, None)
            if not ent:
                del self._replica_sent[self._reply_msg_id]

        def place(gkeys, gvals, version, owner):
            if self._row_cache is not None:
                self._row_cache.store(gkeys, gvals, version, owner)
            if gkeys.size == 0:
                return
            pos = np.minimum(np.searchsorted(entry.rows, gkeys),
                             entry.rows.size - 1)
            ok = entry.rows[pos] == gkeys  # repairs may widen to rows
            pos = pos[ok]                  # outside this entry's set
            entry.out[pos] = gvals[ok]
            if version >= 0:
                entry.versions[pos] = np.maximum(entry.versions[pos],
                                                 int(version))

        self._serve_reply_groups(keys, values, reply_blobs, requested,
                                 place)

    def _serve_reply_groups(self, keys: np.ndarray, values: np.ndarray,
                            reply_blobs: List[Blob],
                            requested: Optional[np.ndarray],
                            place) -> None:
        """Shared reply-shard semantics for the classic and scatter
        read paths: owned rows attribute to the replying shard at the
        header version; each replica group attributes to its OWNER at
        the group's version floor. Groups below this worker's read-
        your-writes floor are discarded (their values may predate an
        Add the owner already acked to us) and — together with routed
        rows the holder did not serve at all — REPAIR to their owners
        under the same request id (the worker actor transfers this
        reply's notify onto the repairs, so wait() completes only when
        they landed). ``place(keys, values, version, owner)`` is the
        path-specific sink (cache store + destination placement)."""
        n_own = keys.size - self._reply_replica_rows
        place(keys[:n_own], values[:n_own], self._reply_version,
              self._reply_server)
        served: List[np.ndarray] = []
        stale: List[np.ndarray] = []
        for owner, floor, gkeys, gvals in \
                self._replica_groups(keys, values, reply_blobs):
            if floor < self.add_floor(owner):
                count_event(replica_mod.REPLICA_STALE, int(gkeys.size))
                stale.append(gkeys)
                continue
            served.append(gkeys)
            # Tracker note(), NOT note_version(): a floor below the
            # owner's latest observed version is normal replica lag,
            # not the generation-change regression signal that
            # invalidates caches.
            self._version_tracker.note(owner, floor)
            place(gkeys, gvals, floor, owner)
        repair = list(stale)
        if requested is not None:
            got = np.concatenate(served + stale) if (served or stale) \
                else np.empty(0, np.int32)
            missing = np.setdiff1d(requested, got)
            if missing.size:
                repair.append(missing)
        if not repair:
            return
        rows = np.unique(np.concatenate(repair)).astype(np.int32)
        owners = self._server_of_rows(rows)
        for sid in np.unique(owners):
            chunk = np.ascontiguousarray(rows[owners == sid])
            self._stage_repair(int(sid), [Blob(chunk.view(np.uint8))])


class MatrixServer(shard_map_mod.ElasticServerMixin, ServerTable):
    def __init__(self, num_row: int, num_col: int, dtype=np.float32,
                 is_sparse: bool = False, is_pipeline: bool = False,
                 zoo=None, updater_type: Optional[str] = None,
                 random_init: Optional[tuple] = None, seed: int = 0):
        super().__init__(zoo=zoo)
        self.dtype = np.dtype(dtype)
        self.num_col = int(num_col)
        self.is_sparse = bool(is_sparse)
        self._compress = (self.is_sparse
                          and not self._zoo.net.in_process
                          and bool(get_flag("sparse_compress")))
        self._one_bit = (not self.is_sparse
                         and np.dtype(dtype) == np.float32
                         and bool(get_flag("one_bit_push")))
        self.num_row = int(num_row)
        offsets = row_offsets(
            int(num_row),
            shard_map_mod.initial_active_servers(self._zoo.num_servers))
        sid = self._zoo.server_id
        self.server_id = sid
        if sid >= len(offsets) - 1:
            self.row_offset, self.my_rows = 0, 0  # idle server (rows<servers)
        else:
            self.row_offset = offsets[sid]
            self.my_rows = offsets[sid + 1] - offsets[sid]

        mesh = meshlib.local_mesh()
        self._sharding = meshlib.row_sharded(mesh)
        padded = meshlib.padded_size(max(self.my_rows, 1),
                                     meshlib.device_count(mesh))
        # Column storage pads to the 128-lane tile width: sub-lane rows
        # scatter ~25x slower on v5e (measured round 4: [1M, 50] row
        # scatter-adds ran at 2.2 GB/s vs 86 GB/s at 128 cols). Bounded
        # to a 4x memory blowup so skinny tables keep compact storage.
        self._col_store = self.num_col
        if self.num_col % 128:
            col_padded = ((self.num_col + 127) // 128) * 128
            if col_padded <= 4 * self.num_col:
                self._col_store = col_padded
        self._data = meshlib.zeros_sharded((padded, self._col_store),
                                           self.dtype, self._sharding)
        if random_init is not None:
            # Server ctor variant with uniform random init
            # (ref: matrix_table.cpp:372-384).
            lo, hi = random_init
            rng = np.random.default_rng(seed + sid)
            host = np.zeros((padded, self._col_store), self.dtype)
            host[:self.my_rows, :self.num_col] = rng.uniform(
                lo, hi, (self.my_rows, self.num_col)).astype(self.dtype)
            # Table construction (CreateTable barrier) can overlap a
            # sibling rank's in-flight program in multi-zoo mode.
            with device_lock.guard():
                self._data = device_lock.settle(
                    jax.device_put(host, self._sharding))
        rule = None if updater_type is None \
            else create_rule(updater_type, dtype)
        num_workers = max(self._zoo.num_workers, 1)
        self._engine = UpdateEngine(rule, (padded, self._col_store),
                                    self.dtype, num_workers, self._sharding)
        # Sparse staleness bitmap: one slot per logical consumer; pipelined
        # workers count twice (ref: sparse_matrix_table.cpp:184-197).
        consumers = num_workers * (2 if is_pipeline else 1)
        self._up_to_date = np.zeros((consumers, self.my_rows), dtype=bool) \
            if is_sparse else None
        # (dirty_ids, padded device ids) of the last fused dirty get —
        # an unchanged dirty set skips the per-call id upload.
        self._dirty_dev_cache = None
        # Hot-shard read replication (runtime/replica.py,
        # docs/SHARDING.md): dense multi-server tables only — the
        # sparse dirty protocol is already a per-consumer staleness
        # tracker, and a single server owns every row. Flag read at
        # construction time, like -sparse_compress.
        self._replica = None
        self._reply_replica_rows_out = 0
        if (not self.is_sparse and self._zoo.num_servers > 1
                and replica_mod.replication_enabled()):
            self._replica = replica_mod.ServerReplicaState(
                self.row_offset, self.my_rows)
        # -- live elastic resharding state (runtime/shard_map.py,
        #    docs/SHARDING.md; server actor thread only) --
        #: adopted epoch-stamped map (None = frozen creation layout)
        self._smap: Optional[shard_map_mod.ShardMap] = None
        #: migrated-IN rows: global row id -> host value row. The
        #: destination side of a move keeps acquired rows host-side
        #: (a numpy gather serves them, like the replica store) — the
        #: device base array keeps its creation-time shape.
        self._overlay: Dict[int, np.ndarray] = {}
        #: forwarded adds for rows whose base chunk is still in flight
        #: (retransmit window only): row -> accumulated signed delta,
        #: merged when the chunk lands.
        self._pending_delta: Dict[int, np.ndarray] = {}
        #: dual-read/forwarding windows this shard is the OLD owner
        #: of: (lo, hi, dst_sid, dst_rank). Kept indefinitely — a
        #: stale router may send moved rows here long after commit.
        self._fwd: List[tuple] = []
        self._mig_out: Optional[shard_map_mod.MigrationOut] = None
        self._mig_in: Dict[int, shard_map_mod.MigrationIn] = {}
        #: requests forwarded into a dual-read/write window since the
        #: last map apply: (requester rank, msg_id, is_get). The
        #: requester tracks them against THIS rank, so if the window's
        #: DESTINATION dies, only this shard can fail their waiters —
        #: shard_abort drains the list into retryable error replies.
        #: Bounded; error replies for long-completed ids are no-ops.
        self._fwd_inflight: List[tuple] = []
        #: True while the server applies the BOTH-APPLY half of a
        #: forwarded add to this (source) shard's handoff copy —
        #: exempts the own-forwarding-window NACK (server actor
        #: thread only).
        self._in_both_apply = False
        #: host twin of the (stateless) update rule for overlay rows:
        #: default adds, sgd subtracts. Stateful rules refuse to
        #: migrate (shard_begin_out).
        self._updater_sign = -1.0 if updater_type == "sgd" else 1.0
        self._updater_stateless = create_rule(updater_type,
                                              dtype).stateless
        #: -reshard_auto load tracking without replication: the same
        #: HotTracker windows feed the controller's skew-split planner
        #: (runtime/shard_map.py ReshardManager.note_report).
        self._hot: Optional[replica_mod.HotTracker] = None
        if (not self.is_sparse and self._zoo.num_servers > 1
                and self._replica is None
                and bool(get_flag("reshard_auto"))):
            self._hot = replica_mod.HotTracker()

    # -- Add (ref: matrix_table.cpp:386-418, sparse_matrix_table.cpp:200-223)
    def process_add(self, blobs: List[Blob]) -> None:
        if blobs[0].on_device:
            # Device-key scatter-add: ids and delta never touch the
            # host. Dense tables only (sparse staleness bookkeeping
            # needs host ids). Multi-server: every server receives the
            # full request; foreign rows are masked out-of-range here
            # and dropped by the scatter.
            CHECK(self._up_to_date is None,
                  "device-key adds are for dense tables")
            option = AddOption.from_blob(blobs[2]) \
                if len(blobs) == 3 else None
            self._data = self._engine.apply_rows(
                self._data, blobs[0].typed(np.int32),
                blobs[1].typed(self.dtype), option,
                bounds=self._shard_bounds)
            if self._replica is not None:
                # Device-resident ids cannot be enumerated without a
                # host sync: conservatively dirty every own promoted
                # row for the next write-through flush.
                self._replica.note_add_all()
            if self._mig_out is not None and self._mig_out.streaming:
                # Unenumerable device ids: conservatively re-stream
                # every already-sent row of the moving range.
                self._mig_out.note_add(np.arange(
                    self._mig_out.lo, self._mig_out.sent_hi,
                    dtype=np.int64))
            return
        keys = blobs[0].as_array(np.int32)
        if self._compress and len(blobs) in (2, 3) \
                and _is_codec_blob(blobs[1]):
            # Compressed wire layout: [keys, codec frame(, option)] —
            # the frame is self-describing (tier + counts in its header;
            # ref decompression on receive: sparse_matrix_table.cpp:
            # 148-153). Magic-sniffed: a peer running without the
            # table-level codec falls through to the raw layouts below.
            option = AddOption.from_blob(blobs[2]) \
                if len(blobs) == 3 else None
            delta = _decompress_values(blobs[1], self.dtype)
        elif self._one_bit and len(blobs) == 4 \
                and not blobs[1].on_device:
            # 1-bit wire layout: exactly [keys, sign bits, meta, option]
            # (matrix adds always carry an option blob). Device-origin
            # deltas stay full precision and arrive as 3 blobs — after a
            # TCP hop they are host bytes, so the blob COUNT, not the
            # device marker, is what distinguishes the layouts.
            option = AddOption.from_blob(blobs[3])
            delta = _onebit_decode(blobs[1], blobs[2])
        else:
            CHECK(len(blobs) in (2, 3), "add needs [keys, values(, option)]")
            option = AddOption.from_blob(blobs[2]) \
                if len(blobs) == 3 else None
            delta = blobs[1].typed(self.dtype)
        if keys.size == 1 and keys[0] == -1:
            CHECK(int(np.prod(delta.shape)) == self.my_rows * self.num_col,
                  "whole-table add size mismatch")
            self._data = self._engine.apply_dense(
                self._data,
                _shaped_rows(delta, self.my_rows, self.num_col), option)
            if self._up_to_date is not None:
                self._mark_dirty(slice(None), option)
            if self._replica is not None:
                self._replica.note_add_all()
            if self._mig_out is not None and self._mig_out.streaming:
                # Whole-shard add while a range streams out: every
                # already-sent row goes dirty (re-streams in the final
                # chunk).
                self._mig_out.note_add(np.arange(
                    self._mig_out.lo, self._mig_out.sent_hi,
                    dtype=np.int64))
            return
        if is_device_array(delta):
            delta = _shaped_rows(delta, keys.size, self.num_col)
        else:
            delta = np.asarray(delta).reshape(keys.size, self.num_col)
        if self._elastic_active():
            self._elastic_row_add(keys, delta, option)
        else:
            local_rows = keys - self.row_offset
            self._data = self._engine.apply_rows(self._data, local_rows,
                                                 delta, option)
            if self._up_to_date is not None:
                self._mark_dirty(local_rows, option)
        if self._replica is not None:
            # Write-through: promoted rows this Add touched refresh to
            # the holders on the next flush cadence.
            self._replica.note_add(keys)

    def _mark_dirty(self, rows, option: Optional[AddOption]) -> None:
        """An Add invalidates the rows for every consumer except the adder,
        whose existing flags are left untouched — only Gets may mark a row
        up-to-date (ref: sparse_matrix_table.cpp:200-223). Setting the
        adder's flag True here would erase a pending dirty mark another
        worker's Add left on the same row, so the adder would read stale
        values on its next dirty-only Get."""
        adder = option.worker_id if option is not None else -1
        if 0 <= adder < self._up_to_date.shape[0]:
            saved = self._up_to_date[adder, rows].copy()
            self._up_to_date[:, rows] = False
            self._up_to_date[adder, rows] = saved
        else:
            self._up_to_date[:, rows] = False

    # -- Get (ref: matrix_table.cpp:420-454, sparse_matrix_table.cpp:226-309)
    def process_get(self, blobs: List[Blob]) -> List[Blob]:
        if blobs[0].on_device:
            # Dense device-key gather: reply values shaped
            # ids.shape + (C,), all in HBM. Multi-server: foreign rows
            # mask out-of-range and gather as 0 — the worker sums the
            # per-server replies (each row owned by exactly one server).
            CHECK(self._up_to_date is None,
                  "device-key gets are for dense tables (sparse dirty "
                  "gets use the -2 host sentinel)")
            rows = blobs[0].typed(np.int32)
            gather = self._gather if self._shard_bounds is None \
                else self._gather_bounded
            # The server id rides along so the worker can key the reply
            # shard by ORIGIN server — segmented pulls reassemble
            # positionally and cannot rely on arrival order.
            return [blobs[0], Blob(gather(self._data, rows)),
                    Blob(np.array([self.server_id], dtype=np.int32))]
        keys = blobs[0].as_array(np.int32)
        if keys.size == 1 and keys[0] == -4:
            return self._fused_add_get_dirty(blobs)
        if keys.size == 1 and keys[0] == -2:
            CHECK(self._up_to_date is not None and len(blobs) >= 2,
                  "-2 sentinel is the sparse dirty device-reply get")
            return self._sparse_get_all_device(
                GetOption.from_blob(blobs[1]))
        if keys.size == 1 and keys[0] == -1:
            if self._up_to_date is not None and len(blobs) >= 2:
                return self._sparse_get_all(GetOption.from_blob(blobs[1]))
            return [blobs[0], Blob(self._values()),
                    Blob(np.array([self.server_id], dtype=np.int32))]
        if self._hot is not None:
            self._hot.note(keys)
        if self._elastic_active():
            # Dynamic ownership: rows serve from the device base range
            # or the migrated-in overlay; a row that is neither NACKs
            # retryably (the requester's map is in motion).
            return [blobs[0],
                    Blob(self._gather_rows_elastic(
                        keys.astype(np.int64)))]
        if self._replica is not None:
            # Hot tracking counts every row REQUESTED here — owned or
            # replica-routed; each row request lands on exactly one
            # server, so the controller's aggregation stays exact and
            # promotion cannot flap when routing moves the head to a
            # holder.
            self._replica.note_get(keys)
            own_mask = (keys >= self.row_offset) \
                & (keys < self.row_offset + self.my_rows)
            if not bool(own_mask.all()):
                return self._replica_row_get(keys, own_mask)
        local_rows = keys - self.row_offset
        padded_rows = pad_ids(local_rows, self._data.shape[0])
        values = _trim_rows(self._gather(self._data, padded_rows),
                            keys.size)
        if self._up_to_date is not None and len(blobs) >= 2:
            opt = GetOption.from_blob(blobs[1])
            if 0 <= opt.worker_id < self._up_to_date.shape[0]:
                self._up_to_date[opt.worker_id, local_rows] = True
        return [blobs[0]] + self._reply_values(values)

    # -- server-side request fusion (runtime/fusion.py,
    #    docs/SERVER_ENGINE.md; always entered under Server._lock_for,
    #    like process_add/process_get above) --
    def fuse_eligible(self, blobs: List[Blob], is_get: bool) -> bool:
        """Plain row-keyed host requests only. Every excluded layout
        carries per-request semantics the fused paths do not
        reproduce: device-key blobs (masking + device replies),
        sentinel protocols (-1/-2/-4 whole-table and dirty gets),
        codec frames and 1-bit pushes (per-request decode), elastic
        windows (row-level routing/NACKs), replica-routed foreign
        rows (host-store serve + repair descriptors), and stateful
        updaters (duplicate ids across requests must SUM inside one
        program — only stateless rules guarantee that,
        updater/engine.py apply_rows)."""
        if not blobs or blobs[0].on_device or self._elastic_active():
            return False
        keys = blobs[0].as_array(np.int32)
        if keys.size == 0 or int(keys[0]) < 0:
            return False
        if is_get:
            if self._replica is None:
                return True
            own = (keys >= self.row_offset) \
                & (keys < self.row_offset + self.my_rows)
            return bool(own.all())
        if not self._updater_stateless:
            return False
        if len(blobs) not in (2, 3) or blobs[1].on_device:
            return False
        if self._compress and _is_codec_blob(blobs[1]):
            return False
        return True

    def process_fused_get(self, requests: List[List[Blob]]
                          ) -> List[List[Blob]]:
        """N row Gets, ONE gather: concatenate the keys, dedup rows
        requested by more than one client (each gathers once —
        SERVER_FUSE_DEDUP_ROWS counts the savings), pad to the bucket
        grid and run the SAME cached gather program the serial path
        uses, then slice per request through the dedup inverse.
        Bit-identical to serial: gather-with-fill over identical row
        ids yields identical bits, and the per-request bookkeeping
        (hot tracking, replica read notes, the sparse staleness
        bitmap) replays per request below, in arrival order."""
        keys_list = [blobs[0].as_array(np.int32) for blobs in requests]
        local = np.concatenate(keys_list) - self.row_offset
        uniq, inverse = np.unique(local, return_inverse=True)
        count_event("SERVER_FUSE_DEDUP_ROWS",
                    int(local.size) - int(uniq.size))
        padded = pad_ids(uniq, self._data.shape[0])
        values = np.asarray(_trim_rows(self._gather(self._data, padded),
                                       uniq.size))
        out: List[List[Blob]] = []
        pos = 0
        for blobs, keys in zip(requests, keys_list):
            sel = inverse[pos:pos + keys.size]
            pos += keys.size
            if self._hot is not None:
                self._hot.note(keys)
            if self._replica is not None:
                self._replica.note_get(keys)
            if self._up_to_date is not None and len(blobs) >= 2:
                opt = GetOption.from_blob(blobs[1])
                if 0 <= opt.worker_id < self._up_to_date.shape[0]:
                    self._up_to_date[opt.worker_id,
                                     keys - self.row_offset] = True
            out.append([blobs[0]] + self._reply_values(values[sel]))
        return out

    def process_fused_add(self, requests: List[List[Blob]]) -> None:
        """N row Adds, ONE scatter per option sub-group: stateless
        rules SUM duplicate ids inside one program (updater/engine.py
        apply_rows), so concatenation is sum-equivalent to the serial
        left fold; requests carrying different option bytes (the rule
        scales the delta by per-request hyperparameters, and the
        dirty bitmap keys on the adder's worker id) split into
        ordered sub-groups. Parse-first contract
        (table_interface.py): every request decodes and reshapes
        before the first apply; a later apply failing raises
        PartialFuseError with the applied request count."""
        runs: List[tuple] = []  # (option bytes, option, [(keys, delta)])
        for blobs in requests:
            keys = blobs[0].as_array(np.int32)
            option = AddOption.from_blob(blobs[2]) \
                if len(blobs) == 3 else None
            okey = blobs[2].as_array(np.uint8).tobytes() \
                if len(blobs) == 3 else None
            delta = np.asarray(blobs[1].typed(self.dtype)).reshape(
                keys.size, self.num_col)
            if not runs or runs[-1][0] != okey:
                runs.append((okey, option, []))
            runs[-1][2].append((keys, delta))
        applied = 0
        for _, option, items in runs:
            try:
                all_keys = np.concatenate([k for k, _ in items])
                local = (all_keys - self.row_offset).astype(np.int32)
                delta = np.ascontiguousarray(
                    np.concatenate([d for _, d in items]))
                self._data = self._engine.apply_rows(
                    self._data, local, delta, option)
            except Exception as exc:  # noqa: BLE001
                from ..runtime.fusion import PartialFuseError
                raise PartialFuseError(applied, exc) from exc
            for keys, _ in items:
                applied += 1
                if self._up_to_date is not None:
                    self._mark_dirty(keys - self.row_offset, option)
                if self._replica is not None:
                    self._replica.note_add(keys)

    # -- hot-shard replication: holder/owner server sides
    #    (runtime/replica.py, docs/SHARDING.md) --
    def _replica_row_get(self, keys: np.ndarray,
                         own_mask: np.ndarray) -> List[Blob]:
        """Holder-side row Get carrying FOREIGN (replica-routed) rows:
        own rows gather as usual, foreign rows serve from the host-side
        replica store — a numpy gather, no device program. Rows the
        store lacks are simply absent from the reply (the worker
        repairs them to their owners). Reply layout: ``[keys = own
        rows + group rows, values, int32 replica descriptor]`` with
        REPLICA_SLOT stamped by the server actor."""
        own = np.ascontiguousarray(keys[own_mask])
        own_values = np.empty((0, self.num_col), self.dtype)
        if own.size:
            local = own - self.row_offset
            padded = pad_ids(local, self._data.shape[0])
            own_values = np.asarray(_trim_rows(
                self._gather(self._data, padded), own.size))
        foreign = np.unique(keys[~own_mask])
        groups, rkeys, rvalues = self._replica.store.serve(
            foreign, self.num_col, self.dtype)
        count_event(replica_mod.REPLICA_HIT, int(rkeys.size))
        count_event(replica_mod.REPLICA_MISS,
                    int(foreign.size) - int(rkeys.size))
        if not groups:
            # Every foreign row missed (the owner's initial push has
            # not landed, or a demotion raced the routing): reply the
            # own part only; the worker repairs the rest.
            return [Blob(own.view(np.uint8)), Blob(own_values)]
        desc = [len(groups)]
        for owner_sid, floor, n_rows in groups:
            desc.extend((int(owner_sid), int(floor) + 1, int(n_rows)))
        keys_out = np.ascontiguousarray(
            np.concatenate([own.astype(np.int32), rkeys]))
        values_out = np.concatenate([own_values, rvalues])
        self._reply_replica_rows_out = int(rkeys.size)
        return [Blob(keys_out.view(np.uint8)), Blob(values_out),
                Blob(np.asarray(desc, dtype=np.int32))]

    def take_reply_replica_rows(self) -> int:
        n, self._reply_replica_rows_out = self._reply_replica_rows_out, 0
        return n

    def apply_replica_map(self, epoch: int, rows) -> List[Message]:
        if self._replica is None:
            return []
        newly_promoted = self._replica.apply_map(epoch, rows)
        # Owner side: newly promoted own rows get their initial value
        # push NOW — until it lands, holders miss and workers repair.
        return self._replica_sync_messages(newly_promoted)

    def apply_replica_sync(self, blobs: List[Blob]) -> None:
        if self._replica is None:
            return
        rows = blobs[0].as_array(np.int32)
        values = blobs[1].as_array(self.dtype).reshape(rows.size,
                                                       self.num_col)
        meta = blobs[2].as_array(np.int32)
        self._replica.store.apply_sync(rows, values,
                                       owner_sid=int(meta[0]),
                                       version=int(meta[1]) - 1,
                                       watermark=bool(meta[2]),
                                       seq=int(meta[3]))

    def replica_redirty(self, blobs: List[Blob]) -> None:
        if self._replica is not None and blobs:
            self._replica.redirty(blobs[0].as_array(np.int32))

    def replica_flush_if_due(self) -> List[Message]:
        if self._replica is None:
            if self._hot is not None and self._hot.due:
                # -reshard_auto without replication: ship the load
                # window so the controller's skew planner sees it
                # (runtime/shard_map.py ReshardManager.note_report).
                rows, counts = self._hot.take_report(top_k=16)
                if rows.size == 0:
                    return []
                msg = Message(src=self._zoo.rank, dst=CONTROLLER_RANK,
                              msg_type=MsgType.Control_Replica_Report,
                              table_id=self.table_id)
                msg.push(Blob(rows))
                msg.push(Blob(counts))
                msg.push(Blob(np.asarray(
                    [self.num_row, self.server_id], dtype=np.int64)))
                return [msg]
            return []
        out: List[Message] = []
        dirty = self._replica.take_due_sync()
        if dirty is not None and (dirty.size or self.version
                                  > self._replica.last_sync_version):
            # An empty drain still refreshes when the shard version
            # advanced (adds landed on NON-promoted rows): the
            # watermark-only message re-certifies the holders' entries
            # at the new version, or every later read-your-writes floor
            # would read them as stale forever.
            out.extend(self._replica_sync_messages(dirty))
        report = self._replica.take_due_report()
        if report is not None:
            msg = Message(src=self._zoo.rank, dst=CONTROLLER_RANK,
                          msg_type=MsgType.Control_Replica_Report,
                          table_id=self.table_id)
            msg.push(Blob(report[0]))
            msg.push(Blob(report[1]))
            out.append(msg)
        return out

    def _replica_sync_messages(self, rows: np.ndarray) -> List[Message]:
        """Write-through fan-out: Request_ReplicaSync carrying current
        values + this shard's version for own promoted ``rows``, one
        message per holder server (chunked at -replica_sync_rows; the
        LAST chunk carries the watermark flag — ``rows`` must be the
        complete drained dirty set for the watermark to be sound, and
        an empty ``rows`` sends one watermark-only message). Runs on
        the server actor thread OUTSIDE the table lock — the gather
        dispatch takes the device guard itself."""
        cap = max(int(get_flag("replica_sync_rows")), 1)
        self._replica.last_sync_version = self.version
        out: List[Message] = []
        n_chunks = max((int(rows.size) + cap - 1) // cap, 1)
        chunks: List[tuple] = []
        for c in range(n_chunks):
            chunk = np.ascontiguousarray(rows[c * cap:(c + 1) * cap])
            if chunk.size:
                local = chunk - self.row_offset
                padded = pad_ids(local, self._data.shape[0])
                with device_lock.guard():
                    gathered = device_lock.settle(
                        self._gather(self._data, padded))
                values = np.asarray(_trim_rows(gathered, chunk.size))
            else:
                values = np.empty((0, self.num_col), self.dtype)
            chunks.append((chunk, values))
            count_event(replica_mod.REPLICA_SYNC)
        for sid in range(self._zoo.num_servers):
            if sid == self.server_id:
                continue
            for c, (chunk, values) in enumerate(chunks):
                # meta: [owner_sid, version+1, watermark, seq]. The
                # per-HOLDER seq is consecutive; a holder seeing a gap
                # drops this owner's entries before applying (a lost
                # chunk must not be papered over by this watermark).
                meta = np.asarray(
                    [self.server_id, self.version + 1,
                     1 if c == n_chunks - 1 else 0,
                     self._replica.next_sync_seq(sid)], dtype=np.int32)
                msg = Message(src=self._zoo.rank,
                              dst=self._zoo.server_rank(sid),
                              msg_type=MsgType.Request_ReplicaSync,
                              table_id=self.table_id)
                msg.push(Blob(chunk.view(np.uint8)))
                msg.push(Blob(values))
                msg.push(Blob(meta))
                out.append(msg)
        return out

    # -- live elastic resharding: server side (runtime/shard_map.py,
    #    docs/SHARDING.md; everything on the server actor thread) --
    def _elastic_active(self) -> bool:
        """Any dynamic-ownership state at all: the static fast paths
        stay byte-identical until the first migration touches this
        shard."""
        return bool(self._overlay or self._pending_delta or self._fwd
                    or self._mig_in or self._mig_out is not None
                    or self._smap is not None)

    def _gather_rows_elastic(self, keys: np.ndarray) -> np.ndarray:
        """Serve rows from the migrated-in overlay (host gather, like
        the replica store) or the device base range; a row that is
        neither — routed here by a map the cluster moved past, or its
        base chunk still in retransmit — NACKs retryably so the
        requester re-issues instead of consuming garbage."""
        keys = np.asarray(keys, dtype=np.int64)
        values = np.empty((keys.size, self.num_col), self.dtype)
        ov = self._overlay
        in_base = (keys >= self.row_offset) \
            & (keys < self.row_offset + self.my_rows)
        # Rows of an INCOMPLETE inbound migration must not fall through
        # to the base range: a range that left this shard and is coming
        # BACK still has its pre-first-move values in the device base —
        # serving them mid-retransmit would be silently stale. The same
        # goes for rows inside one of THIS shard's own forwarding
        # windows (a chained move A->B->C can land a stale-routed
        # request at the dead middle hop; its base copy must NACK, not
        # serve).
        in_mig = np.zeros(keys.size, dtype=bool)
        for mig in self._mig_in.values():
            if not mig.complete:
                in_mig |= (keys >= mig.lo) & (keys < mig.hi)
        fwd_mask, _, _ = self._fwd_route(keys)
        in_mig |= fwd_mask
        base_pos: List[int] = []
        for i, k in enumerate(keys.tolist()):
            row = ov.get(k)
            if row is not None:
                values[i] = row
            elif in_base[i] and not in_mig[i]:
                base_pos.append(i)
            else:
                raise RuntimeError(
                    f"{PEER_LOST_MARK} rank {self._zoo.rank}: row {k} "
                    f"not serveable on server {self.server_id} (shard "
                    f"map in motion) — re-issue")
        if base_pos:
            pos = np.asarray(base_pos, dtype=np.int64)
            local = (keys[pos] - self.row_offset).astype(np.int32)
            padded = pad_ids(local, self._data.shape[0])
            with device_lock.guard():
                gathered = device_lock.settle(
                    self._gather(self._data, padded))
            values[pos] = np.asarray(_trim_rows(gathered, local.size))
        return values

    def _elastic_row_add(self, keys: np.ndarray, delta,
                         option: Optional[AddOption]) -> None:
        """Row add under dynamic ownership: base rows batch through
        the jitted engine, overlay rows apply host-side via the
        stateless rule twin (+/- delta), rows whose base chunk is
        still in flight accumulate in the pending-delta ledger (merged
        when the retransmitted chunk lands). Rows a range move is
        streaming out re-dirty for the final chunk."""
        if self._mig_out is not None and self._mig_out.streaming:
            self._mig_out.note_add(keys.astype(np.int64))
        delta = np.asarray(delta, dtype=self.dtype).reshape(
            keys.size, self.num_col)
        ov, pend = self._overlay, self._pending_delta
        sign = self.dtype.type(self._updater_sign)
        in_base = (keys >= self.row_offset) \
            & (keys < self.row_offset + self.my_rows)
        in_mig = np.zeros(keys.size, dtype=bool)
        for mig in self._mig_in.values():
            if not mig.complete:
                in_mig |= (keys >= mig.lo) & (keys < mig.hi)
        # Rows in this shard's OWN forwarding windows are not appliable
        # here — EXCEPT on the both-apply path, where the server
        # deliberately applies the full add to the handoff copy so a
        # rollback keeps it (Server._process_add route branch).
        if not self._in_both_apply:
            fwd_mask, _, _ = self._fwd_route(keys)
        else:
            fwd_mask = np.zeros(keys.size, dtype=bool)
        # VALIDATE everything before mutating anything: a partial
        # apply followed by the retryable error would double-apply the
        # applied prefix when the caller re-issues (at-least-once).
        for i, k in enumerate(keys.tolist()):
            if k in ov:
                continue
            if fwd_mask[i] or not (in_base[i] or in_mig[i]):
                raise RuntimeError(
                    f"{PEER_LOST_MARK} rank {self._zoo.rank}: add to "
                    f"row {k} not owned by server {self.server_id} "
                    f"(shard map in motion) — re-issue")
        base_pos: List[int] = []
        for i, k in enumerate(keys.tolist()):
            row = ov.get(k)
            if row is not None:
                ov[k] = row + sign * delta[i]
            elif in_base[i] and not in_mig[i]:
                base_pos.append(i)
            else:
                prev = pend.get(k)
                pend[k] = sign * delta[i].copy() if prev is None \
                    else prev + sign * delta[i]
        if base_pos:
            pos = np.asarray(base_pos, dtype=np.int64)
            local = (keys[pos] - self.row_offset).astype(np.int32)
            self._data = self._engine.apply_rows(
                self._data, local, np.ascontiguousarray(delta[pos]),
                option)

    def shard_begin_out(self, desc) -> bool:
        lo, hi, src_sid, dst_sid, dst_rank, epoch = (
            int(v) for v in np.asarray(desc)[:6])
        if self.is_sparse or not self._updater_stateless:
            return False  # dirty bitmaps / stateful optimizer rows
            # cannot migrate live — the controller rolls the move back
        if self._mig_out is not None:
            if self._mig_out.epoch == epoch:
                # Duplicate Begin (the controller re-sent it): if the
                # handoff already happened, the controller's view is
                # STALLED — a lost Done with no destination traffic to
                # ride the re-announce on. Re-send the final chunk
                # (the destination dedups the seq and re-announces).
                self._mig_out.resend_final = self._mig_out.final_sent
                return True
            if self._mig_out.final_sent and epoch > self._mig_out.epoch:
                # The controller serializes moves, so a Begin for a
                # NEWER epoch proves the previous move committed — its
                # broadcast merely lost a race with this Begin (they
                # travel different connections, so nothing orders one
                # before the other). Retire it; the forwarding window
                # installed at its handoff stays.
                self._mig_out = None
            else:
                return False
        if src_sid != self.server_id:
            return False
        rows = np.arange(lo, hi, dtype=np.int64)
        mask, _, _ = self._fwd_route(rows)
        if bool(mask.any()):
            return False  # part of the range already moved away
        in_base = (rows >= self.row_offset) \
            & (rows < self.row_offset + self.my_rows)
        if any(not b and r not in self._overlay
               for r, b in zip(rows.tolist(), in_base.tolist())):
            return False  # not (fully) owned here
        self._mig_out = shard_map_mod.MigrationOut(
            self.table_id, lo, hi, src_sid, dst_sid, dst_rank, epoch)
        chaos.kill_point("shard_begin_accepted")
        return True

    def _shard_data_message(self, mig, seq: int, rows: np.ndarray,
                            is_final: bool) -> Message:
        if mig.frozen is not None:
            # Post-handoff retransmit: values come from the handoff
            # snapshot, never the live copy (forwarded Adds keep
            # both-applying there — see ElasticServerMixin.shard_ack).
            values = mig.frozen[rows - mig.lo] if rows.size else \
                np.empty((0, self.num_col), self.dtype)
        else:
            values = self._gather_rows_elastic(rows) if rows.size else \
                np.empty((0, self.num_col), self.dtype)
        desc = np.asarray(
            [mig.epoch, mig.src_sid, mig.dst_sid, self._zoo.rank,
             mig.lo, mig.hi, seq, 1 if is_final else 0,
             self.version + 1, len(mig.chunks)], dtype=np.int64)
        msg = Message(src=self._zoo.rank, dst=mig.dst_rank,
                      msg_type=MsgType.Request_ShardData,
                      table_id=self.table_id)
        msg.push(Blob(desc))
        msg.push(Blob(rows.astype(np.int64)))
        msg.push(Blob(values))
        count_event("SHARD_MIGRATE_ROWS", int(rows.size))
        return msg

    def _freeze_range(self, mig):
        whole = np.arange(mig.lo, mig.hi, dtype=np.int64)
        return self._gather_rows_elastic(whole) if whole.size \
            else np.empty((0, self.num_col), self.dtype)

    def shard_import_chunk(self, msg: Message):
        desc = msg.data[0].as_array(np.int64)
        (epoch, src_sid, dst_sid, src_rank, lo, hi, seq, is_final,
         wire_version, _n_chunks) = (int(v) for v in desc[:10])
        if dst_sid != self.server_id:
            return []
        mig = self._mig_in.get(epoch)
        if mig is None:
            mig = self._mig_in[epoch] = shard_map_mod.MigrationIn(
                epoch, src_sid, src_rank, lo, hi)
        if not mig.complete and mig.note_applied(seq):
            rows = msg.data[1].as_array(np.int64)
            values = msg.data[2].as_array(self.dtype).reshape(
                rows.size, self.num_col)
            if is_final:
                mig.final_items = set(int(r) for r in rows.tolist())
            pend = self._pending_delta
            for i, r in enumerate(rows.tolist()):
                if not is_final and mig.final_items is not None \
                        and r in mig.final_items:
                    # A reorder-delayed base chunk landing AFTER the
                    # final: the final's copy of this dirty row is
                    # newer — never overwrite it.
                    continue
                v = np.array(values[i], copy=True)
                extra = pend.pop(r, None)
                if extra is not None:
                    # Forwarded Adds that beat this (retransmitted)
                    # chunk merged into the ledger — fold them in.
                    v = v + extra
                self._overlay[r] = v
        if is_final and not mig.complete:
            mig.n_chunks = seq
            mig.src_version = wire_version - 1
            chaos.kill_point("shard_dest_final")
        if mig.n_chunks is None:
            return []
        if mig.check_complete():
            chaos.kill_point("shard_dest_complete")
            return self._announce_done(mig)
        if is_final:
            return self._retransmit_request(mig)
        return []

    def shard_abort(self, epoch: int):
        epoch = int(epoch)
        out: List[Message] = []
        mig = self._mig_out
        if mig is not None and mig.epoch == epoch:
            if mig.final_sent:
                # Post-handoff rollback: drop the forwarding window
                # and resume serving from the (still present) base
                # copy — Adds forwarded since the handoff are the
                # documented at-least-once loss of a dead destination.
                self._fwd = [f for f in self._fwd
                             if not (f[0] == mig.lo and f[1] == mig.hi
                                     and f[2] == mig.dst_sid)]
                log.error("rank %d: migration [%d,%d) -> server %d "
                          "rolled back — resuming ownership from the "
                          "handoff copy", self._zoo.rank, mig.lo,
                          mig.hi, mig.dst_sid)
                out.extend(self._drain_fwd_inflight())
            self._mig_out = None
        mig_in = self._mig_in.pop(epoch, None)
        if mig_in is not None:
            for r in [r for r in self._overlay
                      if mig_in.lo <= r < mig_in.hi]:
                del self._overlay[r]
            for r in [r for r in self._pending_delta
                      if mig_in.lo <= r < mig_in.hi]:
                del self._pending_delta[r]
            log.error("rank %d: inbound migration epoch %d aborted — "
                      "partial [%d,%d) state dropped", self._zoo.rank,
                      epoch, mig_in.lo, mig_in.hi)
        return out

    def apply_shard_map_server(self, epoch: int, smap, alive_sids):
        if self.is_sparse:
            return []
        if self._smap is not None and epoch <= self._smap.epoch:
            return []
        old = self._smap if self._smap is not None else \
            shard_map_mod.ShardMap.initial(
                self.num_row, self._zoo.num_servers,
                active=shard_map_mod.initial_active_servers(
                    self._zoo.num_servers))
        moved = old.diff_moved(smap)
        for lo, hi, old_sid, new_sid in moved:
            if old_sid == self.server_id:
                # Committed away: prune overlay copies; (re)install the
                # forwarding window for routers still behind this epoch.
                for r in [r for r in self._overlay if lo <= r < hi]:
                    del self._overlay[r]
                if not any(f[0] <= lo and hi <= f[1] and f[2] == new_sid
                           for f in self._fwd):
                    self._fwd.append(
                        (lo, hi, new_sid,
                         self._zoo.server_rank(new_sid)))
            if new_sid == self.server_id:
                # Committed to me: stale windows pointing away clear
                # (a range that came back must serve here again).
                self._prune_fwd_windows(lo, hi)
        if self._mig_out is not None \
                and self._mig_out.epoch <= epoch \
                and int(smap.owner_of(np.asarray(
                    [self._mig_out.lo]))[0]) == self._mig_out.dst_sid:
            self._mig_out = None  # committed
        for e in [e for e, m in self._mig_in.items()
                  if m.complete and e <= epoch]:
            self._mig_in.pop(e)
        if moved and self._replica is not None:
            log.info("rank %d: table %d shard map went dynamic — "
                     "retiring hot-row replication for it (ownership "
                     "moves supersede read replicas)", self._zoo.rank,
                     self.table_id)
            self._replica = None
        # A commit broadcast proves the forwarded requests' window
        # destination is alive and serving: the rollback ledger resets.
        self._fwd_inflight = []
        self._smap = smap
        return []

    def shard_forward_get(self, msg: Message):
        if not self._fwd or not msg.data:
            return None
        blob0 = msg.data[0]
        if blob0.on_device:
            return None
        keys = blob0.as_array(np.int32)
        if keys.size == 0 or (keys.size == 1 and keys[0] < 0):
            # Sentinel ops from routers still on the frozen layout keep
            # the frozen path (they see the handoff-time snapshot of
            # moved rows until their map catches up — bounded by the
            # broadcast cadence; docs/SHARDING.md).
            return None
        k64 = keys.astype(np.int64)
        mask, dst_sid, dst_rank = self._fwd_route(k64)
        if not bool(mask.any()):
            return None
        count_event("SHARD_FWD")
        dsts = sorted({int(d) for d in dst_sid[mask]})
        if len(dsts) > 1:
            raise RuntimeError(
                f"{PEER_LOST_MARK} rows span {len(dsts)} forwarding "
                f"windows (router several epochs behind) — re-issue "
                f"after the next shard-map broadcast")
        if self._hot is not None:
            self._hot.note(keys[~mask])
        overflow = self._note_fwd_inflight(msg.src, msg.msg_id, True)
        pig_keys = np.ascontiguousarray(keys[~mask])
        pig_vals = self._gather_rows_elastic(pig_keys) if pig_keys.size \
            else np.empty((0, self.num_col), self.dtype)
        meta = np.asarray([self._zoo.rank, self.version + 1],
                          dtype=np.int64)
        fwd = Message(src=msg.src, dst=int(dst_rank[mask][0]),
                      msg_type=MsgType.Request_FwdGet,
                      table_id=self.table_id, msg_id=msg.msg_id)
        tid = trace_of(msg)
        if tid:
            stamp_trace(fwd, tid)
        fwd.push(Blob(meta))
        fwd.push(Blob(np.ascontiguousarray(keys[mask]).view(np.uint8)))
        fwd.push(Blob(pig_keys.view(np.uint8)))
        fwd.push(Blob(pig_vals))
        return [fwd] + overflow

    def process_forward_get(self, blobs: List[Blob]):
        meta = blobs[0].as_array(np.int64)
        src_rank, src_version = int(meta[0]), int(meta[1]) - 1
        fwd_keys = blobs[1].as_array(np.int32)
        pig_keys = blobs[2].as_array(np.int32)
        pig_vals = blobs[3].as_array(self.dtype).reshape(
            pig_keys.size, self.num_col)
        if self._hot is not None:
            self._hot.note(fwd_keys)
        vals = self._gather_rows_elastic(fwd_keys.astype(np.int64))
        keys_out = np.ascontiguousarray(
            np.concatenate([pig_keys, fwd_keys]).astype(np.int32))
        vals_out = np.concatenate([pig_vals, vals]) if pig_keys.size \
            else vals
        # The source's piggybacked rows are the reply's MAIN body (the
        # reply impersonates the source rank, version-stamped with the
        # source's shard version); this shard's rows ride as one
        # replica group at OUR version floor — the PR-7 reply contract
        # reused verbatim, so the requester's attribution, RYW floors
        # and repair machinery apply unchanged.
        desc = np.asarray([1, self.server_id, self.version + 1,
                           int(fwd_keys.size)], dtype=np.int32)
        return ([Blob(keys_out.view(np.uint8)), Blob(vals_out),
                 Blob(desc)], int(fwd_keys.size), src_rank, src_version)

    def _decode_add_values(self, blobs: List[Blob],
                           n: int) -> Optional[np.ndarray]:
        """Host decode of a row add's delta for window splitting; None
        when the layout cannot be split (unknown framing)."""
        if len(blobs) >= 2 and blobs[1].on_device:
            return np.asarray(blobs[1].typed(self.dtype)).reshape(
                n, self.num_col)
        if self._one_bit and len(blobs) == 4:
            return _onebit_decode(blobs[1], blobs[2]).reshape(
                n, self.num_col)
        if len(blobs) in (2, 3):
            if self._compress and _is_codec_blob(blobs[1]):
                return _decompress_values(blobs[1], self.dtype).reshape(
                    n, self.num_col)
            return blobs[1].as_array(self.dtype).reshape(
                n, self.num_col)
        return None

    def shard_forward_add(self, msg: Message):
        if not self._fwd or not msg.data:
            return None
        blobs = msg.data
        if blobs[0].on_device:
            return None  # device-key adds are frozen-layout only
        keys = blobs[0].as_array(np.int32)
        if keys.size == 0:
            return None
        if keys.size == 1 and keys[0] < 0:
            if int(keys[0]) != -1:
                return None
            keys_eff = np.arange(self.row_offset,
                                 self.row_offset + self.my_rows,
                                 dtype=np.int64)
        else:
            keys_eff = keys.astype(np.int64)
        mask, dst_sid, dst_rank = self._fwd_route(keys_eff)
        if not bool(mask.any()):
            return None
        delta = self._decode_add_values(blobs, keys_eff.size)
        if delta is None:
            raise RuntimeError(
                f"{PEER_LOST_MARK} cannot split this add layout "
                f"across a forwarding window — re-issue")
        option_blob = None
        if len(blobs) == 3:
            option_blob = blobs[2]
        elif self._one_bit and len(blobs) == 4:
            option_blob = blobs[3]
        count_event("SHARD_FWD")
        # BOTH-APPLY: the full add also applies locally (silently, no
        # ack) — exactly one copy survives: on commit the destination's
        # (which got the forwarded subset), on rollback the source's
        # (which applied everything). The ONE ack the requester's
        # waiter needs comes from the destination carrying the real
        # msg_id; additional windows (router several epochs behind)
        # forward with msg_id=-1 — applied, never acked (their Adds'
        # visibility is the documented at-least-once window).
        outs: List[Message] = list(
            self._note_fwd_inflight(msg.src, msg.msg_id, False))
        first = True
        for d in sorted({int(x) for x in dst_sid[mask]}):
            m = mask & (dst_sid == d)
            rank = int(dst_rank[m][0])
            fwd = Message(src=msg.src, dst=rank,
                          msg_type=MsgType.Request_FwdAdd,
                          table_id=self.table_id,
                          msg_id=msg.msg_id if first else -1)
            tid = trace_of(msg)
            if tid:
                stamp_trace(fwd, tid)
            fwd.push(Blob(np.asarray([self._zoo.rank], dtype=np.int64)))
            fwd.push(Blob(np.ascontiguousarray(
                keys_eff[m].astype(np.int32)).view(np.uint8)))
            fwd.push(Blob(np.ascontiguousarray(delta[m])))
            if option_blob is not None:
                fwd.push(option_blob)
            outs.append(fwd)
            first = False
        return msg, outs

    def _reply_values(self, values) -> List[Blob]:
        """Get replies run through the wire filter for sparse tables
        (ref: sparse_matrix_table.cpp:261-308). Always lossless — the
        server keeps no per-consumer error-feedback state."""
        if self._compress:
            return _compress_values(np.asarray(values))[0]
        return [Blob(values)]

    # Always entered under Server._lock_for (process_add/process_get
    # server paths) — the guard is one call layer up, not lexical here.
    def _fused_add_get_dirty(self, blobs: List[Blob]) -> List[Blob]:  # mvlint: ignore[device-dispatch]
        """-4: apply a row add, then reply the get-worker's dirty rows
        gathered from the UPDATED table — ONE compiled program instead
        of the separate scatter + gather pair (whose two big-argument
        launches bound the roundtrip on a tunneled device). Exact
        composition of process_add(rows) + _sparse_get_all_device:
        same dirty bookkeeping, same reply layout. Tunnel-traffic
        trims: the caller may ship a device mirror of the add ids
        (blob 5), and an unchanged dirty set reuses its cached device
        id vector instead of re-uploading ~0.5 MB per call."""
        CHECK(self._up_to_date is not None and len(blobs) in (5, 6),
              "-4 is the fused sparse add+dirty-get")
        rows = blobs[1].as_array(np.int32)
        delta = blobs[2].typed(self.dtype)
        add_opt = AddOption.from_blob(blobs[3])
        get_opt = GetOption.from_blob(blobs[4])
        local = rows - self.row_offset
        self._mark_dirty(local, add_opt)
        dirty = self._dirty_ids(get_opt.worker_id)
        if len(blobs) == 6:
            # Device mirror of the add ids — single server owns row
            # offset 0, so global ids ARE local ids. Arrives BUCKET-
            # PADDED (caller contract), matching the host path below so
            # the fused program compiles once per bucket width.
            add_ids = blobs[5].typed(np.int32)
        else:
            add_ids = pad_ids(local, self._data.shape[0])
        cached = self._dirty_dev_cache
        if cached is not None and np.array_equal(cached[0], dirty):
            get_ids = cached[1]
        else:
            import jax.numpy as jnp
            get_ids = jnp.asarray(pad_ids(dirty, self._data.shape[0]))
            self._dirty_dev_cache = (dirty, get_ids)
        self._data, values = self._engine.apply_rows_gather(
            self._data, add_ids,
            _shaped_rows(delta, rows.size, self.num_col), add_opt,
            get_ids, self.num_col)
        return [Blob(dirty + self.row_offset),
                Blob(_trim_rows(values, dirty.size)),
                Blob(np.array([self.server_id], dtype=np.int32))]

    def _sparse_get_all(self, opt: GetOption) -> List[Blob]:
        """Return only this worker's dirty rows
        (ref: sparse_matrix_table.cpp:226-258)."""
        dirty, values = self._dirty_rows(opt)
        return [Blob(dirty + self.row_offset)] + self._reply_values(values)

    def _sparse_get_all_device(self, opt: GetOption) -> List[Blob]:
        """Dirty rows with the values left in HBM (host ids, device
        payload; no wire filter — this path never crosses a wire). The
        server id rides along: a server with ZERO dirty rows replies an
        empty id vector, which the worker could not attribute by key
        range (multi-server replies would collide on a guessed id)."""
        dirty, values = self._dirty_rows(opt)
        return [Blob(dirty + self.row_offset), Blob(values),
                Blob(np.array([self.server_id], dtype=np.int32))]

    def _dirty_ids(self, wid: int) -> np.ndarray:
        """The consumer's dirty row set, flipped clean on read — the
        ONE copy of the bookkeeping shared by the composed and fused
        dirty paths (they must never diverge)."""
        CHECK(0 <= wid < self._up_to_date.shape[0], "bad worker id")
        dirty = np.nonzero(~self._up_to_date[wid])[0].astype(np.int32)
        self._up_to_date[wid, dirty] = True
        return dirty

    def _dirty_rows(self, opt: GetOption):
        dirty = self._dirty_ids(opt.worker_id)
        padded_rows = pad_ids(dirty, self._data.shape[0])
        values = _trim_rows(self._gather(self._data, padded_rows),
                            dirty.size)
        return dirty, values

    @functools.cached_property
    def _gather(self):
        n_col = self.num_col
        return jax.jit(lambda data, rows: data.at[rows].get(
            mode="fill", fill_value=0)[..., :n_col])

    @property
    def _shard_bounds(self):
        """(row_offset, my_rows) when global row ids need masking to
        this shard — multi-server only. A single server owns every row,
        and the extra in-jit compare/offset would cost nothing, but a
        SEPARATE program variant would recompile the engine's scatter;
        None keeps the round-3 single-server program byte-identical."""
        if self._zoo.num_servers > 1:
            return (self.row_offset, self.my_rows)
        return None

    @functools.cached_property
    def _gather_bounded(self):
        """Masked gather in ONE jitted program (multi-server device
        keys): global ids -> local indices, foreign rows -> the padded
        row count, which gather-fills 0. NOTE: simply subtracting the
        offset is NOT enough — a foreign row could land inside this
        shard's padding and read whatever a scatter left there."""
        ofs, n = self.row_offset, self.my_rows
        padded = self._data.shape[0]
        n_col = self.num_col
        import jax.numpy as jnp

        def gather(data, rows):
            local = jnp.where((rows >= ofs) & (rows < ofs + n),
                              rows - ofs, padded)
            return data.at[local].get(mode="fill",
                                      fill_value=0)[..., :n_col]

        return jax.jit(gather)

    def _values(self):
        """Fresh-buffer snapshot of the logical rows (see ArrayServer._values
        — the live storage is donated away by the next update)."""
        return self._snapshot(self._data)

    @functools.cached_property
    def _snapshot(self):
        n, n_col = self.my_rows, self.num_col
        return jax.jit(lambda x: jax.numpy.copy(x[:n, :n_col]))

    # -- checkpoint (ref: matrix_table.cpp:456-464) --
    def store(self, stream) -> None:
        stream.write(np.asarray(self._values()).tobytes())

    # -- async snapshot split (runtime/snapshot.py) --
    def snapshot_state(self):
        """Capture under the caller's table lock (see
        ArrayServer.snapshot_state: the updater DONATES the live
        storage away on the next add, so the capture must copy into a
        fresh device buffer; host transfer happens off-lock). Under
        dynamic ownership the cut additionally copies the migrated-in
        overlay, the pending-delta ledger and the forwarding windows —
        the elastic half of the shard's state."""
        base = device_lock.settle(self._snapshot(self._data))
        if not self._elastic_active():
            return base
        return (base,
                {k: v.copy() for k, v in self._overlay.items()},
                {k: v.copy() for k, v in self._pending_delta.items()},
                list(self._fwd))

    def snapshot_meta(self):
        """Manifest sidecar (runtime/snapshot.py): the shard-map epoch
        and this shard's elastic inventory, so a rejoining server
        restores into the RIGHT map — its payload parses as
        elastic-format and the controller's re-register re-broadcast
        re-anchors the epoch (docs/SHARDING.md)."""
        if not self._elastic_active():
            return None
        return {"elastic": 1,
                "shard_epoch": self._smap.epoch
                if self._smap is not None else -1,
                "overlay_rows": len(self._overlay),
                "fwd": [[int(lo), int(hi), int(sid)]
                        for lo, hi, sid, _rank in self._fwd]}

    def write_snapshot(self, state, stream) -> None:
        if isinstance(state, tuple):
            import pickle
            import struct
            base, overlay, pending, fwd = state
            side = pickle.dumps({"overlay": overlay,
                                 "pending": pending, "fwd": fwd})
            stream.write(struct.pack("<Q", len(side)))
            stream.write(side)
            stream.write(np.asarray(base).tobytes())
            return
        stream.write(np.asarray(state).tobytes())

    def load_with_meta(self, stream, meta) -> None:
        if not meta or not meta.get("elastic"):
            self.load(stream)
            return
        import pickle
        import struct
        (length,) = struct.unpack("<Q", stream.read(8))
        side = pickle.loads(stream.read(length))
        self._overlay = dict(side.get("overlay", {}))
        self._pending_delta = dict(side.get("pending", {}))
        self._fwd = [(int(lo), int(hi), int(sid),
                      self._zoo.server_rank(int(sid)))
                     for lo, hi, sid, *_ in side.get("fwd", [])]
        self.load(stream)
        log.info("rank %d: table %d restored elastic state — %d "
                 "overlay rows, %d forwarding window(s), recorded "
                 "shard epoch %s (the controller re-broadcasts the "
                 "live map on re-register)", self._zoo.rank,
                 self.table_id, len(self._overlay), len(self._fwd),
                 meta.get("shard_epoch"))

    def load(self, stream) -> None:
        raw = stream.read(self.my_rows * self.num_col * self.dtype.itemsize)
        values = np.frombuffer(raw, dtype=self.dtype).reshape(
            self.my_rows, self.num_col)
        padded = self._data.shape[0]
        host = np.zeros((padded, self._col_store), self.dtype)
        host[:self.my_rows, :self.num_col] = values
        with device_lock.guard():
            self._data = device_lock.settle(
                jax.device_put(host, self._sharding))

    @property
    def raw(self):
        return self._values()
