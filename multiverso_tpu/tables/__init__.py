"""Distributed tables: sharded jax.Array state behind the PS Get/Add API."""

from .array_table import ArrayServer, ArrayWorker, server_offsets  # noqa: F401
from .factory import (ArrayTableOption, KVTableOption, create_array_table,  # noqa: F401
                      create_kv_table, create_matrix_table, create_table)
from .kv_table import KVServer, KVWorker  # noqa: F401
from .matrix_table import (MatrixServer, MatrixTableOption, MatrixWorker,  # noqa: F401
                           row_offsets)
from .table_interface import ServerTable, WorkerTable  # noqa: F401
