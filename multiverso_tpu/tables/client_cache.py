"""Worker-side versioned parameter cache (the client cache).

Extension over the reference: Multiverso's workers re-issue a full
server roundtrip for every ``Get`` even when the rows were fetched one
step earlier and nothing changed (ref: src/worker.cpp:30-51 always
partitions and sends). Over the tunneled bench transport a dispatch
roundtrip costs ~92 ms, and the wordembedding workload's power-law row
popularity (SparCML's observation, PAPERS.md) means a small hot-row
cache absorbs most of that traffic.

Versioning model
----------------
* every ``ServerTable`` shard keeps a monotonically increasing
  ``version``, bumped once per successfully applied Add (the server
  actor owns the bump, runtime/server.py);
* Get/Add/BatchAdd replies carry the serving shard's version
  (``core.message.VERSION_SLOT`` on per-message replies, a descriptor
  column on batch acks);
* each worker table tracks, per server shard, the LATEST version it has
  observed (``VersionTracker``);
* a cache entry fetched at version ``v`` may serve a Get only while
  ``v >= latest_observed - max_get_staleness``.

``-max_get_staleness=0`` (the default) disables the cache outright —
every Get takes today's wire path, byte-identical. BSP sync mode
force-disables it regardless of the flag: a locally served Get is a Get
the sync server's vector clocks never count, which would break the
every-i-th-Get-sees-every-i-th-Add contract.

Read-your-writes
----------------
The staleness bound alone would let a worker read back a PRE-write value
of a row it just pushed a delta to. So issuing an Add immediately
*blocks* the touched slots (they neither serve nor accept stores), and
the Add's ack — which carries the post-add version — resolves the block
and raises the slots' floor to the latest observed version: only values
fetched at-or-after the worker's own write can serve again. This is the
piggybacked self-invalidation the Add-ack version stamp exists for.

Staleness is measured against the latest version THIS worker has
observed, not the server's true head: a worker that never hears from the
server (no Gets, no Add acks) cannot age its entries. The wire-path
population of the cache (every real Get refreshes entries AND the
tracker) keeps the two converged in any workload that misses
occasionally; workloads needing a hard recency guarantee set the bound
to 0 for the critical read or call the table's uncached device/sync
paths.
"""

from __future__ import annotations

import itertools
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..util.configure import (define_int, get_flag,
                              register_tunable_hook)
from ..util.dashboard import count
from ..util.lock_witness import named_lock

# Per-INSTANCE witness names: the lock-order graph is keyed by name,
# so two tables' caches sharing one name would hide real cross-table
# cycles and manufacture false ones (same reason mt_queue/waiter/tcp
# use serial/rank names).
_lock_serial = itertools.count()

define_int("max_get_staleness", 0,
           "client-side parameter-cache staleness bound, in server-shard "
           "versions (one version = one applied Add): a cached Get may "
           "serve while its fetch version is within this many versions "
           "of the latest version observed from the owning shard. "
           "0 (default) disables the cache; BSP sync mode force-disables "
           "it (a locally served Get would bypass the vector clocks)")
define_int("client_cache_rows", 65536,
           "row capacity of the matrix client cache (oldest entries "
           "evicted past this; bounds worker memory at rows * num_col * "
           "itemsize)")

#: Dashboard counter names (util/dashboard.py `count`).
HIT = "CLIENT_CACHE_HIT"
MISS = "CLIENT_CACHE_MISS"
JOIN = "CLIENT_CACHE_JOIN"
PREFETCH = "CLIENT_CACHE_PREFETCH"


def staleness_bound() -> int:
    """The active staleness bound; 0 = cache disabled. Read at table
    construction time (matching ``-sparse_compress`` and friends)."""
    if bool(get_flag("sync", False)):
        return 0
    try:
        bound = int(get_flag("max_get_staleness", 0))
    except (TypeError, ValueError):
        return 0
    return max(bound, 0)


def cache_enabled() -> bool:
    return staleness_bound() > 0


def place_rows(keys: np.ndarray, values, req: np.ndarray, out) -> None:
    """Vectorized subset placement: every position of ``req`` whose row
    id appears in ``keys`` receives that id's row of ``values``;
    positions for absent ids are left untouched. Shared by the cache's
    partial-hit fill and the table reply path — ``req`` may repeat ids
    thousands of times (power-of-two padded row sets), so per-position
    Python loops are pathological here."""
    if len(keys) == 0 or len(req) == 0:
        return
    sorter = np.argsort(keys, kind="stable")
    sorted_keys = keys[sorter]
    slot = np.searchsorted(sorted_keys, req)
    slot = np.minimum(slot, sorted_keys.size - 1)
    hit = sorted_keys[slot] == req
    out[hit] = values[sorter[slot[hit]]]


class VersionTracker:
    """Latest table-shard version observed per server id (-1 before any
    observation). Fed by the worker actor from reply version stamps."""

    def __init__(self) -> None:
        self._lock = named_lock(
            f"client_cache.VersionTracker[{next(_lock_serial)}]")
        self._latest: Dict[int, int] = {}  # guarded_by: _lock

    def note(self, server_id: int, version: int) -> None:
        if version < 0:
            return
        with self._lock:
            if version > self._latest.get(server_id, -1):
                self._latest[server_id] = version

    def latest(self, server_id: int) -> int:
        # Under the lock like every other reader: a torn read is not
        # possible for one dict probe, but the freshness math in
        # RowCache._fresh must not see a version OLDER than one a
        # concurrent note() already published to another field.
        with self._lock:
            return self._latest.get(server_id, -1)

    def regressed(self, server_id: int, version: int) -> bool:
        """True when a stamped reply carries a LOWER version than the
        latest observed from that shard. Versions per shard only ever
        grow within one server generation (monotonic counter, FIFO
        reply stream), so a regression means the server RESTARTED and
        reset/restored its counter — the generation-change signal the
        caches invalidate on (docs/CLIENT_CACHE.md)."""
        return 0 <= version < self.latest(server_id)

    def reset(self, server_id: int, version: int) -> None:
        """Re-anchor a shard's latest-observed version downward after a
        server generation change (``note`` only moves it up)."""
        with self._lock:
            self._latest[server_id] = version

    def known_servers(self) -> List[int]:
        with self._lock:
            return list(self._latest)


class RowCache:
    """Row-granular cache for dense matrix worker tables.

    Every public method is thread-safe: lookups/invalidation run on the
    requester's thread, stores and add-resolution on the worker actor's
    reply thread.
    """

    def __init__(self, bound: int, server_of: Callable, num_servers: int,
                 tracker: VersionTracker,
                 capacity: Optional[int] = None) -> None:
        self._bound = int(bound)
        self._server_of = server_of  # vectorized row ids -> server ids
        self._num_servers = int(num_servers)
        self._tracker = tracker
        self._capacity = int(capacity if capacity is not None  # guarded_by: _lock
                             else get_flag("client_cache_rows"))
        self._lock = named_lock(
            f"client_cache.RowCache[{next(_lock_serial)}]")
        # _bound stays unannotated by choice: the hot read path probes
        # it lock-free (one int, GIL-atomic) and _retune_bound rebinds
        # it under the lock — a stale read is one Get at the old bound.
        self._rows: Dict[int, Tuple[int, np.ndarray]] = {}  # guarded_by: _lock
        # _floor: per-row min fetch version; _floor_all: per-server
        # floor; _pending: row -> outstanding own-adds; _pending_all:
        # whole-table own-adds.
        self._floor: Dict[int, int] = {}      # guarded_by: _lock
        self._floor_all: Dict[int, int] = {}  # guarded_by: _lock
        self._pending: Dict[int, int] = {}    # guarded_by: _lock
        self._pending_all = 0                 # guarded_by: _lock
        # hits/misses: whole-Get accounting (full-local vs needed the
        # wire); rows_hit/rows_missed: row-granular across both.
        self.hits = 0        # guarded_by: _lock
        self.misses = 0      # guarded_by: _lock
        self.rows_hit = 0    # guarded_by: _lock
        self.rows_missed = 0  # guarded_by: _lock
        #: test hook: fn(row, entry_version, latest_observed, bound),
        #: called under the cache lock for every row actually SERVED.
        self.on_hit = None
        # Live retuning (docs/AUTOTUNE.md): the bound and capacity
        # were cached above at construction, so a Control_Config
        # broadcast must land through these hooks — bound methods held
        # weakly by the registry, so a dropped table unregisters
        # itself. Registered LAST: a broadcast may fire them from the
        # recv thread the instant they register, and they touch the
        # lock and row dicts above.
        register_tunable_hook("max_get_staleness", self._retune_bound)
        register_tunable_hook("client_cache_rows",
                              self._retune_capacity)

    # -- freshness core (caller holds the lock) --
    def _fresh(self, row: int, sid: int,
               record: bool = True) -> Optional[np.ndarray]:
        if self._pending_all or self._pending.get(row):
            return None
        ent = self._rows.get(row)
        if ent is None:
            return None
        version, value = ent
        if version < max(self._floor.get(row, -1),
                         self._floor_all.get(sid, -1)):
            return None
        latest = self._tracker.latest(sid)
        if latest - version > self._bound:
            return None
        if record and self.on_hit is not None:
            self.on_hit(row, version, latest, self._bound)
        return value

    # -- read side --
    def missing_of(self, row_ids: np.ndarray) -> np.ndarray:
        """The sorted unique requested rows that would NOT hit (no
        copies, no counter bumps) — the prefetch planning check; an
        empty result means full coverage."""
        uniq = np.unique(row_ids)
        if self._bound <= 0:  # inactive: everything misses
            return uniq.astype(np.int32)
        sids = self._server_of(uniq)
        with self._lock:
            return np.asarray(
                [int(r) for r, s in zip(uniq, sids)
                 if self._fresh(int(r), int(s), record=False) is None],
                dtype=np.int32)

    def fetch_into(self, row_ids: np.ndarray, out: np.ndarray,
                   count_stats: bool = True) -> np.ndarray:
        """Partial-hit fill: copy every fresh row into its requested
        positions (duplicates welcome) and return the sorted unique
        MISSING rows — empty = full local hit. The caller fetches only
        the missing set over the wire; its reply placement fills the
        remaining positions (reply keys are a subset of the request's,
        which the placement path already supports). The join-completion
        re-serve passes ``count_stats=False`` so one logical Get
        contributes exactly one hit-or-miss."""
        uniq = np.unique(row_ids)
        if self._bound <= 0:
            # Inactive (live-deactivated mid-flight): everything
            # misses, nothing is counted — the old no-cache path.
            return uniq.astype(np.int32)
        sids = self._server_of(uniq)
        fresh_vals: List[np.ndarray] = []
        fresh_keys: List[int] = []
        missing: List[int] = []
        with self._lock:
            for r, s in zip(uniq, sids):
                v = self._fresh(int(r), int(s),
                                record=count_stats)
                if v is None:
                    missing.append(int(r))
                else:
                    fresh_keys.append(int(r))
                    fresh_vals.append(v)
            if count_stats:
                self.rows_hit += len(fresh_keys)
                self.rows_missed += len(missing)
                if missing:
                    self.misses += 1
                else:
                    self.hits += 1
        if count_stats:
            count(MISS if missing else HIT)
        if fresh_keys:
            place_rows(np.asarray(fresh_keys, dtype=np.int64),
                       np.stack(fresh_vals), row_ids, out)
        return np.asarray(missing, dtype=np.int32)

    # -- write side (worker actor reply thread) --
    def store(self, row_ids: np.ndarray, values: np.ndarray,
              version: int, server_id: int) -> None:
        """Record one reply shard's rows at the version it was served.
        Slots blocked by an outstanding own-add, or whose floor exceeds
        the fetch version, are skipped — never silently resurrected."""
        if version < 0:  # unstamped legacy peer
            return
        if self._bound <= 0:  # inactive: store nothing (a reply
            # racing a live deactivation must not leave entries)
            return
        with self._lock:
            if self._pending_all:
                return
            if version < self._floor_all.get(int(server_id), -1):
                return
            for i, r in enumerate(row_ids):
                r = int(r)
                if self._pending.get(r):
                    continue
                floor = self._floor.get(r, -1)
                if version < floor:
                    continue
                # Replies per server connection arrive version-ordered
                # (FIFO socket, monotonic server counter), so a passed
                # floor never needs re-checking.
                self._floor.pop(r, None)
                self._rows[r] = (version, np.array(values[i], copy=True))
            while len(self._rows) > self._capacity:
                self._rows.pop(next(iter(self._rows)))

    # -- own-add self-invalidation --
    def begin_add(self, row_ids: Optional[np.ndarray] = None):
        """Block the slots an own Add is about to dirty (None = whole
        table). Returns a token for ``finish_add``.

        While INACTIVE there are no entries to block, but the ack must
        still FENCE the owning shards' floors: a Get reply served
        before this add could land after a live activation, store the
        pre-add value, and serve it within the widened bound — a
        read-your-writes violation across the activation edge. The
        fence token costs O(owning servers), not O(rows)."""
        if self._bound <= 0:
            if row_ids is None:
                sids = list(range(self._num_servers))
            else:
                rows = np.unique(np.asarray(
                    row_ids, dtype=np.int64).reshape(-1))
                sids = [int(s) for s in np.unique(
                    self._server_of(rows))]
            return ("fence", sids)
        if row_ids is None:
            with self._lock:
                self._pending_all += 1
            return (None, None)
        rows = np.unique(np.asarray(row_ids,
                                    dtype=np.int64).reshape(-1))
        sids = self._server_of(rows)
        rows = [int(r) for r in rows]
        with self._lock:
            for r in rows:
                self._pending[r] = self._pending.get(r, 0) + 1
                self._rows.pop(r, None)
        return (rows, [int(s) for s in sids])

    def finish_add(self, token) -> None:
        """Resolve a ``begin_add`` once its ack arrived: unblock the
        slots and raise their floor to the latest observed version (the
        ack was noted before this runs), so only values fetched at-or-
        after the write serve again."""
        if token is None:
            return
        if token[0] == "fence":
            # Inactive-mode ack fence: raise the per-shard floor to
            # the latest version observed at ack (the ack was noted
            # before this runs). _fresh and store() both honor
            # _floor_all, so a pre-add reply landing after a live
            # activation can neither store nor serve.
            with self._lock:
                for sid in token[1]:
                    self._floor_all[sid] = max(
                        self._floor_all.get(sid, -1),
                        self._tracker.latest(sid))
            return
        rows, sids = token
        with self._lock:
            if rows is None:
                self._pending_all -= 1
                if self._pending_all == 0:
                    self._rows.clear()
                    for sid in range(self._num_servers):
                        self._floor_all[sid] = max(
                            self._floor_all.get(sid, -1),
                            self._tracker.latest(sid))
                return
            for r, s in zip(rows, sids):
                remaining = self._pending.get(r, 0) - 1
                if remaining > 0:
                    self._pending[r] = remaining
                else:
                    self._pending.pop(r, None)
                self._floor[r] = max(self._floor.get(r, -1),
                                     self._tracker.latest(int(s)))

    @property
    def bound(self) -> int:
        """The LIVE staleness bound (serving tier response metadata,
        docs/SERVING.md; retunable via the dynamic-flag layer)."""
        return self._bound

    @property
    def active(self) -> bool:
        """False while the bound is 0: the cache object exists (so a
        live config broadcast can activate it) but serves nothing and
        stores nothing — the table's ``_live_cache`` treats it exactly
        like the old no-cache construction path."""
        return self._bound > 0

    # -- live retuning (dynamic-flag apply hooks, docs/AUTOTUNE.md) --
    def _retune_bound(self, value) -> None:
        """``-max_get_staleness`` landed live. Widening/narrowing just
        rebinds the freshness check; a FLIP (activation 0 -> n or
        deactivation -> 0) also drops every entry — the cache must
        start from scratch, never from state recorded across the
        edge. Floors are KEPT on a flip: they only ever make serving
        stricter, and the inactive-mode ack fences recorded in
        ``_floor_all`` are exactly what protects read-your-writes
        against a pre-activation reply landing late. BSP sync mode
        keeps its force-disable (a locally served Get would bypass
        the vector clocks)."""
        if bool(get_flag("sync", False)):
            value = 0
        new = max(int(value), 0)
        with self._lock:
            flipped = (new > 0) != (self._bound > 0)
            self._bound = new
            if flipped:
                self._rows.clear()

    def _retune_capacity(self, value) -> None:
        with self._lock:
            self._capacity = max(int(value), 0)
            while len(self._rows) > self._capacity:
                self._rows.pop(next(iter(self._rows)))

    def versions_of(self, row_ids) -> Dict[int, int]:
        """Fetch version per requested row currently present (rows
        absent — evicted, never fetched, or blocked by a pending
        own-add — are simply omitted). Serving-tier metadata read: the
        frontend reports the minimum served version and the per-row
        staleness against the tracker on every response."""
        out: Dict[int, int] = {}
        with self._lock:
            for r in np.unique(np.asarray(row_ids).reshape(-1)):
                ent = self._rows.get(int(r))
                if ent is not None:
                    out[int(r)] = ent[0]
        return out

    def invalidate_server(self, server_id: int) -> None:
        """Drop every row owned by a shard whose server changed
        generation (restart + snapshot restore): entries and floors
        recorded against the old generation's version counter are
        meaningless against the restored one."""
        sid = int(server_id)
        with self._lock:
            touched = set(self._rows) | set(self._floor)
            if touched:
                rows = np.asarray(sorted(touched), dtype=np.int64)
                sids = self._server_of(rows)
                for r, s in zip(rows, sids):
                    if int(s) == sid:
                        self._rows.pop(int(r), None)
                        self._floor.pop(int(r), None)
            self._floor_all.pop(sid, None)

    @property
    def stats(self) -> Dict[str, float]:
        # One consistent cut under the lock: the counters move together
        # in fetch_into, and a rate computed from a half-updated pair
        # can exceed 1.0.
        with self._lock:
            hits, misses = self.hits, self.misses
            rows_hit, rows_missed = self.rows_hit, self.rows_missed
            nrows = len(self._rows)
        total = hits + misses
        rows_total = rows_hit + rows_missed
        return {"hits": hits, "misses": misses,
                "hit_rate": hits / total if total else 0.0,
                "rows_hit": rows_hit,
                "rows_missed": rows_missed,
                "row_hit_rate": rows_hit / rows_total
                if rows_total else 0.0,
                "rows": nrows}


class BlobCache:
    """Whole-shard cache for Array worker tables: one entry per server
    shard; a hit requires EVERY shard fresh (array Gets are whole-table)."""

    def __init__(self, bound: int, num_servers: int,
                 tracker: VersionTracker) -> None:
        self._bound = int(bound)
        self._num_servers = int(num_servers)
        self._tracker = tracker
        self._lock = named_lock(
            f"client_cache.BlobCache[{next(_lock_serial)}]")
        self._shards: Dict[int, Tuple[int, np.ndarray]] = {}  # guarded_by: _lock
        self._floor: Dict[int, int] = {}  # guarded_by: _lock
        self._pending = 0  # guarded_by: _lock
        self.hits = 0  # guarded_by: _lock
        self.misses = 0  # guarded_by: _lock
        self.on_hit = None  # fn(server_id, entry_version, latest, bound)

    def fresh_all(self) -> bool:
        """Counter-free freshness probe (the prefetch planning check —
        hit/miss accounting must reflect Get serving only)."""
        with self._lock:
            if self._pending:
                return False
            for sid in range(self._num_servers):
                ent = self._shards.get(sid)
                if ent is None:
                    return False
                version, _ = ent
                if version < self._floor.get(sid, -1) \
                        or self._tracker.latest(sid) - version \
                        > self._bound:
                    return False
        return True

    def fetch_all(self) -> Optional[Dict[int, np.ndarray]]:
        with self._lock:
            if self._pending:
                out = None
            else:
                out = {}
                for sid in range(self._num_servers):
                    ent = self._shards.get(sid)
                    if ent is None:
                        out = None
                        break
                    version, value = ent
                    if version < self._floor.get(sid, -1):
                        out = None
                        break
                    latest = self._tracker.latest(sid)
                    if latest - version > self._bound:
                        out = None
                        break
                    if self.on_hit is not None:
                        self.on_hit(sid, version, latest, self._bound)
                    out[sid] = value
            if out is None:
                self.misses += 1
            else:
                self.hits += 1
        count(HIT if out is not None else MISS)
        return out

    def store(self, server_id: int, values: np.ndarray,
              version: int) -> None:
        if version < 0:
            return
        with self._lock:
            if self._pending:
                return
            if version < self._floor.get(int(server_id), -1):
                return
            self._floor.pop(int(server_id), None)
            self._shards[int(server_id)] = (version,
                                            np.array(values, copy=True))

    def begin_add(self) -> None:
        with self._lock:
            self._pending += 1
            self._shards.clear()

    def finish_add(self) -> None:
        with self._lock:
            self._pending -= 1
            if self._pending == 0:
                for sid in range(self._num_servers):
                    self._floor[sid] = max(self._floor.get(sid, -1),
                                           self._tracker.latest(sid))

    def invalidate_server(self, server_id: int) -> None:
        """Server generation change: the shard's entry and floor are
        stamped against a counter that no longer exists."""
        with self._lock:
            self._shards.pop(int(server_id), None)
            self._floor.pop(int(server_id), None)


class SnapshotCache:
    """Request-granular snapshot cache for KV worker tables: keyed by
    the exact requested key bytes; an entry records the version of every
    server shard that contributed."""

    def __init__(self, bound: int, tracker: VersionTracker,
                 capacity: int = 256) -> None:
        self._bound = int(bound)
        self._tracker = tracker
        self._capacity = int(capacity)
        self._lock = named_lock(
            f"client_cache.SnapshotCache[{next(_lock_serial)}]")
        self._entries: Dict[bytes, Tuple[Dict[int, int], dict]] = {}  # guarded_by: _lock
        self._floor: Dict[int, int] = {}  # guarded_by: _lock
        self._pending = 0  # guarded_by: _lock
        self.hits = 0  # guarded_by: _lock
        self.misses = 0  # guarded_by: _lock

    def fetch(self, key: bytes, server_ids) -> Optional[dict]:
        with self._lock:
            snap = None
            if not self._pending:
                ent = self._entries.get(key)
                if ent is not None:
                    versions, values = ent
                    ok = True
                    for sid in server_ids:
                        sid = int(sid)
                        v = versions.get(sid)
                        if (v is None or v < self._floor.get(sid, -1)
                                or self._tracker.latest(sid) - v
                                > self._bound):
                            ok = False
                            break
                    if ok:
                        snap = dict(values)
            if snap is None:
                self.misses += 1
            else:
                self.hits += 1
        count(HIT if snap is not None else MISS)
        return snap

    def store(self, key: bytes, versions: Dict[int, int],
              values: dict) -> None:
        with self._lock:
            if self._pending:
                return
            for sid, v in versions.items():
                if v < 0 or v < self._floor.get(int(sid), -1):
                    return
            self._entries[key] = (dict(versions), dict(values))
            while len(self._entries) > self._capacity:
                self._entries.pop(next(iter(self._entries)))

    def begin_add(self) -> None:
        with self._lock:
            self._pending += 1
            self._entries.clear()

    def finish_add(self) -> None:
        with self._lock:
            self._pending -= 1
            if self._pending == 0:
                for sid in self._tracker.known_servers():
                    self._floor[sid] = max(self._floor.get(sid, -1),
                                           self._tracker.latest(sid))

    def invalidate_server(self, server_id: int) -> None:
        """Server generation change: snapshots record multi-shard
        version vectors, so any entry touching the restarted shard is
        stale — clearing all is the simple safe sweep (rare event)."""
        with self._lock:
            self._entries.clear()
            self._floor.pop(int(server_id), None)
