"""Distributed key-value table (hash-sharded map).

TPU-native equivalent of the reference's ``KVWorkerTable/KVServerTable``
(ref: include/multiverso/table/kv_table.h:18-124). Semantics preserved:

- partition by ``key % num_servers`` (ref: kv_table.h:48-65);
- server ``process_add`` does ``table[k] += v`` (ref: kv_table.h:99-106);
- the worker keeps a local ``raw`` dict refreshed by Get
  (ref: kv_table.h:40, 68-75).

KV state is host-side (it backs control-plane things like WordEmbedding's
word counts, ref: Applications/WordEmbedding/src/communicator.cpp:251-259);
numeric bulk state belongs in Array/Matrix tables in HBM. Unlike the
reference we also implement Store/Load (the reference raises
"Not implemented", ref: kv_table.h:108-114).
"""

from __future__ import annotations

import pickle
import struct
from typing import Dict, List, Optional

import numpy as np

from ..core.blob import Blob
from ..core.message import MsgType
from ..util.log import CHECK
from . import client_cache
from .client_cache import SnapshotCache
from .table_interface import ServerTable, WorkerTable


class KVWorker(WorkerTable):
    def __init__(self, key_dtype=np.int64, val_dtype=np.float32, zoo=None):
        super().__init__(zoo=zoo)
        self.key_dtype = np.dtype(key_dtype)
        self.val_dtype = np.dtype(val_dtype)
        self._num_server = self._zoo.num_servers
        self.raw: Dict[int, float] = {}
        # Client cache (-max_get_staleness > 0): whole-request
        # snapshots keyed by the exact requested key set, versioned per
        # contributing server shard.
        bound = client_cache.staleness_bound()
        self._snap_cache: Optional[SnapshotCache] = None
        if bound > 0:
            self._snap_cache = SnapshotCache(bound, self._version_tracker)
            self._caches.append(self._snap_cache)
        self._collect_versions: Optional[Dict[int, int]] = None

    def get(self, keys) -> Dict[int, float]:
        """Refresh ``raw`` for the requested keys and return it."""
        keys = np.ascontiguousarray(keys, dtype=self.key_dtype).reshape(-1)
        if self._snap_cache is not None:
            sids = np.unique(keys % self._num_server)
            snap = self._snap_cache.fetch(keys.tobytes(), sids)
            if snap is not None:
                self.raw.update(snap)
                return self.raw
            # Collect per-shard version stamps as the replies land (the
            # worker actor's reply context carries them).
            self._collect_versions = {}
        self.retrying_wait(
            lambda: self.get_async_raw(Blob(keys.view(np.uint8))))
        if self._snap_cache is not None:
            versions, self._collect_versions = self._collect_versions, None
            if versions is not None and \
                    {int(s) for s in sids} <= set(versions):
                self._snap_cache.store(
                    keys.tobytes(), versions,
                    {int(k): self.raw.get(int(k), 0.0) for k in keys})
        return self.raw

    def add(self, keys, values) -> None:
        self.retrying_wait(lambda: self.add_async(keys, values))

    def add_async(self, keys, values) -> int:
        keys = np.ascontiguousarray(keys, dtype=self.key_dtype).reshape(-1)
        values = np.ascontiguousarray(values,
                                      dtype=self.val_dtype).reshape(-1)
        CHECK(keys.size == values.size, "keys/values size mismatch")
        if self._snap_cache is not None:
            # Self-invalidation until the ack's version resolves it.
            self._snap_cache.begin_add()
        mid = self.add_async_raw(Blob(keys.view(np.uint8)),
                                 Blob(values.view(np.uint8)))
        if self._snap_cache is not None:
            self.add_completion(
                mid, lambda _mid: self._snap_cache.finish_add())
        return mid

    # ref: kv_table.h:48-65
    def partition(self, blobs, msg_type) -> Dict[int, List[Blob]]:
        keys = blobs[0].as_array(self.key_dtype)
        values = blobs[1].as_array(self.val_dtype) \
            if len(blobs) >= 2 else None
        out: Dict[int, List[Blob]] = {}
        dest = (keys % self._num_server).astype(np.int64)
        for sid in np.unique(dest):
            mask = dest == sid
            shard = [Blob(np.ascontiguousarray(keys[mask]).view(np.uint8))]
            if values is not None:
                shard.append(
                    Blob(np.ascontiguousarray(values[mask]).view(np.uint8)))
            out[int(sid)] = shard
        return out

    # ref: kv_table.h:68-75
    def process_reply_get(self, reply_blobs: List[Blob]) -> None:
        keys = reply_blobs[0].as_array(self.key_dtype)
        values = reply_blobs[1].as_array(self.val_dtype)
        for k, v in zip(keys, values):
            self.raw[int(k)] = v.item()
        if (self._collect_versions is not None
                and self._reply_version >= 0):
            self._collect_versions[self._reply_server] = \
                self._reply_version


class KVServer(ServerTable):
    #: KV state is a host-side dict — pure control-plane work that must
    #: not serialize two in-process server shards on the device lock.
    needs_device_lock = False

    def __init__(self, key_dtype=np.int64, val_dtype=np.float32, zoo=None):
        super().__init__(zoo=zoo)
        self.key_dtype = np.dtype(key_dtype)
        self.val_dtype = np.dtype(val_dtype)
        self._store: Dict[int, float] = {}

    # ref: kv_table.h:99-106
    def process_add(self, blobs: List[Blob]) -> None:
        keys = blobs[0].as_array(self.key_dtype)
        values = blobs[1].as_array(self.val_dtype)
        for k, v in zip(keys, values):
            self._store[int(k)] = self._store.get(int(k), 0) + v.item()

    # ref: kv_table.h:88-97
    def process_get(self, blobs: List[Blob]) -> List[Blob]:
        keys = blobs[0].as_array(self.key_dtype)
        values = np.array([self._store.get(int(k), 0) for k in keys],
                          dtype=self.val_dtype)
        return [blobs[0], Blob(values.view(np.uint8))]

    def store(self, stream) -> None:
        payload = pickle.dumps(self._store)
        stream.write(struct.pack("<Q", len(payload)))
        stream.write(payload)

    # -- async snapshot split (runtime/snapshot.py) --
    def snapshot_state(self):
        """Consistent capture: ``dict(d)`` copies at C level without
        releasing the GIL, so it is atomic against the server actor's
        concurrent adds (KV tables run without the device table lock)."""
        return dict(self._store)

    def write_snapshot(self, state, stream) -> None:
        payload = pickle.dumps(state)
        stream.write(struct.pack("<Q", len(payload)))
        stream.write(payload)

    def load(self, stream) -> None:
        (length,) = struct.unpack("<Q", stream.read(8))
        self._store = pickle.loads(stream.read(length))

    @property
    def raw(self) -> Dict[int, float]:
        return self._store
