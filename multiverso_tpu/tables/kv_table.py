"""Distributed key-value table (hash-sharded map).

TPU-native equivalent of the reference's ``KVWorkerTable/KVServerTable``
(ref: include/multiverso/table/kv_table.h:18-124). Semantics preserved:

- partition by ``key % num_servers`` (ref: kv_table.h:48-65);
- server ``process_add`` does ``table[k] += v`` (ref: kv_table.h:99-106);
- the worker keeps a local ``raw`` dict refreshed by Get
  (ref: kv_table.h:40, 68-75).

KV state is host-side (it backs control-plane things like WordEmbedding's
word counts, ref: Applications/WordEmbedding/src/communicator.cpp:251-259);
numeric bulk state belongs in Array/Matrix tables in HBM. Unlike the
reference we also implement Store/Load (the reference raises
"Not implemented", ref: kv_table.h:108-114).

Elastic resharding (docs/SHARDING.md): KV tables reshard at HASH-BUCKET
granularity — ``bucket = key % (16 * num_servers)``; the bucket count is
a multiple of the server count so the frozen layout's
``(key % B) % num_servers`` equals the reference's ``key %
num_servers`` bit-for-bit. A dynamic :class:`ShardMap` over bucket ids
then reassigns bucket intervals between live servers through the same
controller-coordinated stream/forward/commit protocol as dense matrix
rows (runtime/shard_map.py); the dict state of a bucket moves as one
pickled chunk.
"""

from __future__ import annotations

import pickle
import struct
from typing import Dict, List, Optional

import numpy as np

from ..core.blob import Blob
from ..core.message import (PEER_LOST_MARK, Message, MsgType,
                            stamp_trace, trace_of)
from ..runtime import shard_map as shard_map_mod
from ..util import chaos, log
from ..util.dashboard import count as count_event
from ..util.log import CHECK
from . import client_cache
from .client_cache import SnapshotCache
from .table_interface import ServerTable, WorkerTable


def _kv_buckets(num_servers: int) -> int:
    """Bucket-space size: a multiple of the server count, so the
    frozen modulo layout reproduces ``key % num_servers`` exactly."""
    return 16 * max(int(num_servers), 1)


def _modulo_map(num_buckets: int, active: int) -> shard_map_mod.ShardMap:
    """Epoch-0 bucket map: bucket b -> server ``b % active`` (the
    frozen hash layout over the first ``active`` servers)."""
    bounds = np.arange(num_buckets + 1, dtype=np.int64)
    owners = np.arange(num_buckets, dtype=np.int64) % max(active, 1)
    return shard_map_mod.ShardMap(bounds, owners, epoch=0)


class KVWorker(WorkerTable):
    def __init__(self, key_dtype=np.int64, val_dtype=np.float32, zoo=None):
        super().__init__(zoo=zoo)
        self.key_dtype = np.dtype(key_dtype)
        self.val_dtype = np.dtype(val_dtype)
        self._num_server = self._zoo.num_servers
        self._num_buckets = _kv_buckets(self._num_server)
        # Frozen layout: plain modulo (byte-identical to the
        # reference) unless -shard_initial_servers narrows the active
        # set, in which case an epoch-0 bucket map routes over it.
        active = shard_map_mod.initial_active_servers(self._num_server)
        self._bucket_map: Optional[shard_map_mod.ShardMap] = \
            _modulo_map(self._num_buckets, active) \
            if active < self._num_server else None
        self.raw: Dict[int, float] = {}
        # Client cache (-max_get_staleness > 0): whole-request
        # snapshots keyed by the exact requested key set, versioned per
        # contributing server shard.
        bound = client_cache.staleness_bound()
        self._snap_cache: Optional[SnapshotCache] = None
        if bound > 0:
            self._snap_cache = SnapshotCache(bound, self._version_tracker)
            self._caches.append(self._snap_cache)
        self._collect_versions: Optional[Dict[int, int]] = None

    def _owner_of_keys(self, keys: np.ndarray) -> np.ndarray:
        buckets = (keys.astype(np.int64) % self._num_buckets)
        if self._bucket_map is not None:
            return self._bucket_map.owner_of(buckets)
        return buckets % self._num_server

    # -- elastic resharding: worker side --
    def apply_shard_map(self, epoch: int, smap, alive_sids) -> None:
        old = self._bucket_map
        if old is not None and epoch <= old.epoch:
            return
        if old is None:
            old = _modulo_map(self._num_buckets, self._num_server)
        moved = old.diff_moved(smap)
        for old_sid in sorted({m[2] for m in moved}):
            # Snapshot-cache entries record multi-shard version
            # vectors; a moved bucket's versions now come from another
            # counter — the generation-change sweep clears them.
            self.note_shard_moved(old_sid)
        self._bucket_map = smap

    def shard_epoch(self) -> int:
        return self._bucket_map.epoch if self._bucket_map is not None \
            else -1

    def shard_owner_sids(self):
        return self._bucket_map.owner_sids() \
            if self._bucket_map is not None else None

    def shard_layout(self):
        smap = self._bucket_map
        if smap is None:
            return None
        return (smap.bounds.tolist(), smap.owners.tolist())

    def reshard_space(self) -> int:
        return self._num_buckets

    def reshard_kind(self) -> int:
        return 1  # modulo initial layout (runtime/shard_map.py)

    def get(self, keys) -> Dict[int, float]:
        """Refresh ``raw`` for the requested keys and return it."""
        keys = np.ascontiguousarray(keys, dtype=self.key_dtype).reshape(-1)
        if self._snap_cache is not None:
            sids = np.unique(self._owner_of_keys(keys))
            snap = self._snap_cache.fetch(keys.tobytes(), sids)
            if snap is not None:
                self.raw.update(snap)
                return self.raw
            # Collect per-shard version stamps as the replies land (the
            # worker actor's reply context carries them).
            self._collect_versions = {}
        self.retrying_wait(
            lambda: self.get_async_raw(Blob(keys.view(np.uint8))))
        if self._snap_cache is not None:
            versions, self._collect_versions = self._collect_versions, None
            if versions is not None and \
                    {int(s) for s in sids} <= set(versions):
                self._snap_cache.store(
                    keys.tobytes(), versions,
                    {int(k): self.raw.get(int(k), 0.0) for k in keys})
        return self.raw

    def add(self, keys, values) -> None:
        self.retrying_wait(lambda: self.add_async(keys, values))

    def add_async(self, keys, values) -> int:
        keys = np.ascontiguousarray(keys, dtype=self.key_dtype).reshape(-1)
        values = np.ascontiguousarray(values,
                                      dtype=self.val_dtype).reshape(-1)
        CHECK(keys.size == values.size, "keys/values size mismatch")
        if self._snap_cache is not None:
            # Self-invalidation until the ack's version resolves it.
            self._snap_cache.begin_add()
        mid = self.add_async_raw(Blob(keys.view(np.uint8)),
                                 Blob(values.view(np.uint8)))
        if self._snap_cache is not None:
            self.add_completion(
                mid, lambda _mid: self._snap_cache.finish_add())
        return mid

    # ref: kv_table.h:48-65
    def partition(self, blobs, msg_type) -> Dict[int, List[Blob]]:
        keys = blobs[0].as_array(self.key_dtype)
        values = blobs[1].as_array(self.val_dtype) \
            if len(blobs) >= 2 else None
        out: Dict[int, List[Blob]] = {}
        dest = self._owner_of_keys(keys).astype(np.int64)
        for sid in np.unique(dest):
            mask = dest == sid
            shard = [Blob(np.ascontiguousarray(keys[mask]).view(np.uint8))]
            if values is not None:
                shard.append(
                    Blob(np.ascontiguousarray(values[mask]).view(np.uint8)))
            out[int(sid)] = shard
        return out

    # ref: kv_table.h:68-75
    def process_reply_get(self, reply_blobs: List[Blob]) -> None:
        keys = reply_blobs[0].as_array(self.key_dtype)
        values = reply_blobs[1].as_array(self.val_dtype)
        for k, v in zip(keys, values):
            self.raw[int(k)] = v.item()
        if (self._collect_versions is not None
                and self._reply_version >= 0):
            self._collect_versions[self._reply_server] = \
                self._reply_version


class KVServer(shard_map_mod.ElasticServerMixin, ServerTable):
    #: KV state is a host-side dict — pure control-plane work that must
    #: not serialize two in-process server shards on the device lock.
    needs_device_lock = False

    def __init__(self, key_dtype=np.int64, val_dtype=np.float32, zoo=None):
        super().__init__(zoo=zoo)
        self.key_dtype = np.dtype(key_dtype)
        self.val_dtype = np.dtype(val_dtype)
        self._store: Dict[int, float] = {}
        self.server_id = self._zoo.server_id
        self._num_buckets = _kv_buckets(self._zoo.num_servers)
        active = shard_map_mod.initial_active_servers(
            self._zoo.num_servers)
        self._smap: Optional[shard_map_mod.ShardMap] = \
            _modulo_map(self._num_buckets, active) \
            if active < self._zoo.num_servers else None
        #: dual-read windows over BUCKET intervals
        self._fwd: List[tuple] = []
        self._mig_out: Optional[shard_map_mod.MigrationOut] = None
        self._mig_in: Dict[int, shard_map_mod.MigrationIn] = {}
        #: forwarded adds whose bucket's base chunk is still in flight
        self._pending: Dict[int, float] = {}
        #: requests forwarded into a window since the last map apply
        #: (see MatrixServer._fwd_inflight): drained into retryable
        #: error replies on rollback.
        self._fwd_inflight: List[tuple] = []
        #: both-apply exemption flag (see MatrixServer._in_both_apply)
        self._in_both_apply = False
        #: buckets of incomplete inbound migrations whose chunk landed
        self._based: set = set()

    def _buckets_of(self, keys: np.ndarray) -> np.ndarray:
        return keys.astype(np.int64) % self._num_buckets

    def _unbased_mask(self, buckets: np.ndarray) -> np.ndarray:
        """Buckets of an incomplete inbound migration whose base chunk
        has not landed (retransmit window): serving them would hand
        back values missing their base."""
        mask = np.zeros(buckets.size, dtype=bool)
        for mig in self._mig_in.values():
            if mig.complete:
                continue
            mask |= ((buckets >= mig.lo) & (buckets < mig.hi)
                     & ~np.isin(buckets, np.asarray(sorted(self._based),
                                                   dtype=np.int64)))
        return mask

    # ref: kv_table.h:99-106
    def process_add(self, blobs: List[Blob]) -> None:
        keys = blobs[0].as_array(self.key_dtype)
        values = blobs[1].as_array(self.val_dtype)
        if self._mig_out is not None and self._mig_out.streaming \
                and keys.size:
            self._mig_out.note_add(self._buckets_of(keys))
        if self._fwd and keys.size and not self._in_both_apply:
            # Keys in this shard's OWN forwarding windows live at the
            # new owner now; applying (and acking) into the dead copy
            # here would silently lose the write — a chained move
            # (A->B->C) can land a stale-routed add at the dead middle
            # hop. VALIDATE before any mutation (at-least-once).
            fwd_mask, _, _ = self._fwd_route(self._buckets_of(keys))
            if bool(fwd_mask.any()):
                raise RuntimeError(
                    f"{PEER_LOST_MARK} rank {self._zoo.rank}: add to "
                    f"moved bucket(s) (shard map in motion) — "
                    f"re-issue")
        unbased = None
        if self._mig_in and keys.size:
            unbased = self._unbased_mask(self._buckets_of(keys))
        for i, (k, v) in enumerate(zip(keys, values)):
            if unbased is not None and unbased[i]:
                # Base chunk still in flight: ledger the delta, merged
                # when the (retransmitted) chunk lands.
                self._pending[int(k)] = \
                    self._pending.get(int(k), 0.0) + v.item()
            else:
                self._store[int(k)] = \
                    self._store.get(int(k), 0) + v.item()

    # ref: kv_table.h:88-97
    def process_get(self, blobs: List[Blob]) -> List[Blob]:
        keys = blobs[0].as_array(self.key_dtype)
        if self._mig_in and keys.size:
            unbased = self._unbased_mask(self._buckets_of(keys))
            if bool(unbased.any()):
                raise RuntimeError(
                    f"{PEER_LOST_MARK} rank {self._zoo.rank}: bucket "
                    f"base still in retransmit — re-issue")
        # NOTE: keys in this shard's own forwarding windows never reach
        # here from Server._process_get (shard_forward_get intercepts);
        # process_forward_get applies its own check below.
        values = np.array([self._store.get(int(k), 0) for k in keys],
                          dtype=self.val_dtype)
        return [blobs[0], Blob(values.view(np.uint8))]

    # -- server-side request fusion (runtime/fusion.py) --
    def fuse_eligible(self, blobs: List[Blob], is_get: bool) -> bool:
        """Host-dict table: fusion is just the base-class serial loop
        under one dispatch, so any steady-state request qualifies.
        Opt out whenever elastic state is live — forwarding windows,
        in/out migrations or a pending-delta ledger re-route or defer
        individual requests, and those paths must keep their serial
        retryable-NACK semantics."""
        if blobs and blobs[0].on_device:
            return False
        return not (self._fwd or self._mig_in
                    or self._mig_out is not None or self._pending)

    # -- elastic resharding: server side (runtime/shard_map.py) --
    def shard_begin_out(self, desc) -> bool:
        lo, hi, src_sid, dst_sid, dst_rank, epoch = (
            int(v) for v in np.asarray(desc)[:6])
        if self._mig_out is not None:
            if self._mig_out.epoch == epoch:
                # Stalled-commit recovery: see MatrixServer.
                self._mig_out.resend_final = self._mig_out.final_sent
                return True
            if self._mig_out.final_sent and epoch > self._mig_out.epoch:
                # A Begin for a NEWER epoch proves the previous move
                # committed (the controller serializes moves) — its
                # broadcast lost a race with this Begin. Retire it;
                # the handoff's forwarding window stays.
                self._mig_out = None
            else:
                return False
        if src_sid != self.server_id:
            return False
        buckets = np.arange(lo, hi, dtype=np.int64)
        mask, _, _ = self._fwd_route(buckets)
        if bool(mask.any()):
            return False
        self._mig_out = shard_map_mod.MigrationOut(
            self.table_id, lo, hi, src_sid, dst_sid, dst_rank, epoch)
        chaos.kill_point("shard_begin_accepted")
        return True

    def _bucket_items(self, buckets: np.ndarray) -> Dict[int, float]:
        wanted = set(int(b) for b in buckets.tolist())
        B = self._num_buckets
        return {k: v for k, v in self._store.items()
                if (k % B) in wanted}

    def _shard_data_message(self, mig, seq: int, buckets: np.ndarray,
                            is_final: bool) -> Message:
        if mig.frozen is not None:
            # Post-handoff retransmit: serve from the handoff snapshot
            # (the live dict keeps moving — both-applied forwarded
            # Adds; see ElasticServerMixin.shard_ack).
            wanted = set(int(b) for b in buckets.tolist())
            B = self._num_buckets
            items = {k: v for k, v in mig.frozen.items()
                     if (k % B) in wanted}
        else:
            items = self._bucket_items(buckets)
        payload = pickle.dumps(items)
        desc = np.asarray(
            [mig.epoch, mig.src_sid, mig.dst_sid, self._zoo.rank,
             mig.lo, mig.hi, seq, 1 if is_final else 0,
             self.version + 1, len(mig.chunks)], dtype=np.int64)
        msg = Message(src=self._zoo.rank, dst=mig.dst_rank,
                      msg_type=MsgType.Request_ShardData,
                      table_id=self.table_id)
        msg.push(Blob(desc))
        msg.push(Blob(buckets.astype(np.int64)))
        msg.push(Blob(np.frombuffer(payload, np.uint8).copy()))
        count_event("SHARD_MIGRATE_ROWS", int(buckets.size))
        return msg

    def _freeze_range(self, mig):
        return self._bucket_items(
            np.arange(mig.lo, mig.hi, dtype=np.int64))

    def shard_import_chunk(self, msg: Message):
        desc = msg.data[0].as_array(np.int64)
        (epoch, src_sid, dst_sid, src_rank, lo, hi, seq, is_final,
         wire_version, _n_chunks) = (int(v) for v in desc[:10])
        if dst_sid != self.server_id:
            return []
        mig = self._mig_in.get(epoch)
        if mig is None:
            mig = self._mig_in[epoch] = shard_map_mod.MigrationIn(
                epoch, src_sid, src_rank, lo, hi)
        if not mig.complete and mig.note_applied(seq):
            buckets = msg.data[1].as_array(np.int64)
            items = pickle.loads(bytes(msg.data[2].as_array(np.uint8)))
            if is_final:
                mig.final_items = set(int(b) for b in buckets.tolist())
            elif mig.final_items is not None:
                # Reorder-delayed base chunk after the final: the
                # final re-exported every dirty BUCKET wholesale, so
                # its copies are newer — skip those buckets entirely.
                B = self._num_buckets
                items = {k: v for k, v in items.items()
                         if (k % B) not in mig.final_items}
            for k, v in items.items():
                # REPLACE with the source's value plus any forwarded
                # adds that beat this chunk (the pending ledger).
                self._store[int(k)] = float(v) \
                    + self._pending.pop(int(k), 0.0)
            self._based.update(int(b) for b in buckets.tolist())
            # Pending deltas for keys the source held no entry for
            # still resolve once their bucket is based.
            B = self._num_buckets
            based = set(int(b) for b in buckets.tolist())
            for k in [k for k in self._pending if (k % B) in based]:
                self._store[k] = self._store.get(k, 0) \
                    + self._pending.pop(k)
        if is_final and not mig.complete:
            mig.n_chunks = seq
            mig.src_version = wire_version - 1
            chaos.kill_point("shard_dest_final")
        if mig.n_chunks is None:
            return []
        if mig.check_complete():
            chaos.kill_point("shard_dest_complete")
            return self._announce_done(mig)
        if is_final:
            return self._retransmit_request(mig)
        return []

    def shard_abort(self, epoch: int):
        epoch = int(epoch)
        out: List[Message] = []
        mig = self._mig_out
        if mig is not None and mig.epoch == epoch:
            if mig.final_sent:
                self._fwd = [f for f in self._fwd
                             if not (f[0] == mig.lo and f[1] == mig.hi
                                     and f[2] == mig.dst_sid)]
                out.extend(self._drain_fwd_inflight())
            self._mig_out = None
        mig_in = self._mig_in.pop(epoch, None)
        if mig_in is not None:
            B = self._num_buckets
            for k in [k for k in self._store
                      if mig_in.lo <= (k % B) < mig_in.hi]:
                del self._store[k]
            for k in [k for k in self._pending
                      if mig_in.lo <= (k % B) < mig_in.hi]:
                del self._pending[k]
            self._based -= {b for b in self._based
                            if mig_in.lo <= b < mig_in.hi}
        return out

    def apply_shard_map_server(self, epoch: int, smap, alive_sids):
        if self._smap is not None and epoch <= self._smap.epoch:
            return []
        old = self._smap if self._smap is not None else \
            _modulo_map(self._num_buckets, self._zoo.num_servers)
        moved = old.diff_moved(smap)
        B = self._num_buckets
        for lo, hi, old_sid, new_sid in moved:
            if old_sid == self.server_id:
                # Committed away: drop the moved buckets' entries and
                # keep the forwarding window for stale routers.
                for k in [k for k in self._store
                          if lo <= (k % B) < hi]:
                    del self._store[k]
                if not any(f[0] <= lo and hi <= f[1] and f[2] == new_sid
                           for f in self._fwd):
                    self._fwd.append(
                        (lo, hi, new_sid,
                         self._zoo.server_rank(new_sid)))
            if new_sid == self.server_id:
                self._prune_fwd_windows(lo, hi)
        if self._mig_out is not None \
                and self._mig_out.epoch <= epoch \
                and int(smap.owner_of(np.asarray(
                    [self._mig_out.lo]))[0]) == self._mig_out.dst_sid:
            self._mig_out = None
        for e in [e for e, m in self._mig_in.items()
                  if m.complete and e <= epoch]:
            m = self._mig_in.pop(e)
            self._based -= {b for b in self._based
                            if m.lo <= b < m.hi}
        self._fwd_inflight = []  # window destination proven alive
        self._smap = smap
        return []

    def shard_forward_get(self, msg: Message):
        if not self._fwd or not msg.data:
            return None
        keys = msg.data[0].as_array(self.key_dtype)
        if keys.size == 0:
            return None
        buckets = self._buckets_of(keys)
        mask, dst_sid, dst_rank = self._fwd_route(buckets)
        if not bool(mask.any()):
            return None
        count_event("SHARD_FWD")
        dsts = sorted({int(d) for d in dst_sid[mask]})
        if len(dsts) > 1:
            raise RuntimeError(
                f"{PEER_LOST_MARK} keys span {len(dsts)} forwarding "
                f"windows — re-issue after the next shard-map "
                f"broadcast")
        overflow = self._note_fwd_inflight(msg.src, msg.msg_id, True)
        pig_keys = np.ascontiguousarray(keys[~mask])
        pig_vals = np.array([self._store.get(int(k), 0)
                             for k in pig_keys], dtype=self.val_dtype)
        meta = np.asarray([self._zoo.rank, 0], dtype=np.int64)
        fwd = Message(src=msg.src, dst=int(dst_rank[mask][0]),
                      msg_type=MsgType.Request_FwdGet,
                      table_id=self.table_id, msg_id=msg.msg_id)
        tid = trace_of(msg)
        if tid:
            stamp_trace(fwd, tid)
        fwd.push(Blob(meta))
        fwd.push(Blob(np.ascontiguousarray(keys[mask]).view(np.uint8)))
        fwd.push(Blob(pig_keys.view(np.uint8)))
        fwd.push(Blob(pig_vals.view(np.uint8)))
        return [fwd] + overflow

    def process_forward_get(self, blobs: List[Blob]):
        meta = blobs[0].as_array(np.int64)
        src_rank, src_version = int(meta[0]), int(meta[1]) - 1
        fwd_keys = blobs[1].as_array(self.key_dtype)
        pig_keys = blobs[2].as_array(self.key_dtype)
        pig_vals = blobs[3].as_array(self.val_dtype)
        if self._mig_in and fwd_keys.size:
            unbased = self._unbased_mask(self._buckets_of(fwd_keys))
            if bool(unbased.any()):
                raise RuntimeError(
                    f"{PEER_LOST_MARK} forwarded bucket base still in "
                    f"retransmit — re-issue")
        if self._fwd and fwd_keys.size:
            # Chained move: these buckets moved on from here too —
            # serving the dead copy would be silently stale.
            fwd_mask, _, _ = self._fwd_route(self._buckets_of(fwd_keys))
            if bool(fwd_mask.any()):
                raise RuntimeError(
                    f"{PEER_LOST_MARK} forwarded bucket moved on from "
                    f"this shard (chained migration) — re-issue")
        vals = np.array([self._store.get(int(k), 0) for k in fwd_keys],
                        dtype=self.val_dtype)
        keys_out = np.ascontiguousarray(
            np.concatenate([pig_keys, fwd_keys]))
        vals_out = np.concatenate([pig_vals, vals])
        # KV forward replies stay version-UNSTAMPED (src_version is -1
        # by construction): the snapshot cache must not record a
        # cross-shard mixture under one shard's counter — mid-window
        # KV gets simply don't cache (self-correcting once the
        # requester adopts the committed map).
        return ([Blob(keys_out.view(np.uint8)),
                 Blob(vals_out.view(np.uint8))], 0, src_rank,
                src_version)

    def shard_forward_add(self, msg: Message):
        if not self._fwd or len(msg.data) < 2:
            return None
        keys = msg.data[0].as_array(self.key_dtype)
        if keys.size == 0:
            return None
        values = msg.data[1].as_array(self.val_dtype)
        buckets = self._buckets_of(keys)
        mask, dst_sid, dst_rank = self._fwd_route(buckets)
        if not bool(mask.any()):
            return None
        count_event("SHARD_FWD")
        # BOTH-APPLY (see MatrixServer.shard_forward_add): the full add
        # applies locally without an ack; the destination acks the
        # forwarded moved-bucket subset under the real msg_id.
        outs: List[Message] = list(
            self._note_fwd_inflight(msg.src, msg.msg_id, False))
        first = True
        for d in sorted({int(x) for x in dst_sid[mask]}):
            m = mask & (dst_sid == d)
            fwd = Message(src=msg.src, dst=int(dst_rank[m][0]),
                          msg_type=MsgType.Request_FwdAdd,
                          table_id=self.table_id,
                          msg_id=msg.msg_id if first else -1)
            tid = trace_of(msg)
            if tid:
                stamp_trace(fwd, tid)
            fwd.push(Blob(np.asarray([self._zoo.rank], dtype=np.int64)))
            fwd.push(Blob(np.ascontiguousarray(keys[m]).view(np.uint8)))
            fwd.push(Blob(np.ascontiguousarray(values[m])
                          .view(np.uint8)))
            outs.append(fwd)
            first = False
        return msg, outs

    def store(self, stream) -> None:
        payload = pickle.dumps(self._store)
        stream.write(struct.pack("<Q", len(payload)))
        stream.write(payload)

    # -- async snapshot split (runtime/snapshot.py) --
    def snapshot_state(self):
        """Consistent capture: ``dict(d)`` copies at C level without
        releasing the GIL, so it is atomic against the server actor's
        concurrent adds (KV tables run without the device table lock)."""
        return dict(self._store)

    def snapshot_meta(self):
        if self._smap is None and not self._fwd:
            return None
        return {"elastic": 1,
                "shard_epoch": self._smap.epoch
                if self._smap is not None else -1,
                "fwd": [[int(lo), int(hi), int(sid)]
                        for lo, hi, sid, _rank in self._fwd]}

    def write_snapshot(self, state, stream) -> None:
        payload = pickle.dumps(state)
        stream.write(struct.pack("<Q", len(payload)))
        stream.write(payload)

    def load_with_meta(self, stream, meta) -> None:
        self.load(stream)
        if meta and meta.get("elastic"):
            self._fwd = [(int(lo), int(hi), int(sid),
                          self._zoo.server_rank(int(sid)))
                         for lo, hi, sid, *_ in meta.get("fwd", [])]
            log.info("rank %d: KV table %d restored elastic state "
                     "(%d forwarding window(s), recorded shard epoch "
                     "%s)", self._zoo.rank, self.table_id,
                     len(self._fwd), meta.get("shard_epoch"))

    def load(self, stream) -> None:
        (length,) = struct.unpack("<Q", stream.read(8))
        self._store = pickle.loads(stream.read(length))

    @property
    def raw(self) -> Dict[int, float]:
        return self._store
