"""Device-mesh helpers for table storage.

This is where the TPU-native build departs hardest from the reference: the
reference shards tables across *server processes* connected by MPI/ZMQ
(ref: src/table/array_table.cpp:98-108); here each server shard is
additionally a sharded ``jax.Array`` laid out over the local TPU mesh, so
updater arithmetic runs data-parallel over ICI with XLA-inserted
collectives. A 1-D mesh with axis ``"shard"`` covers HBM placement of table
state; model-parallel axes (dp/tp/pp/sp) are built on top by apps via
``make_mesh``.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

SHARD_AXIS = "shard"


@functools.lru_cache(maxsize=None)
def local_mesh(num_devices: Optional[int] = None) -> Mesh:
    """A 1-D mesh over (a prefix of) the local devices."""
    devices = jax.devices()
    if num_devices is not None:
        devices = devices[:num_devices]
    return Mesh(np.array(devices), (SHARD_AXIS,))


def make_mesh(axis_sizes: Sequence[int], axis_names: Sequence[str],
              devices=None) -> Mesh:
    """Build an N-D mesh (dp/tp/pp/...) over the given devices."""
    devices = np.array(devices if devices is not None else jax.devices())
    return Mesh(devices.reshape(tuple(axis_sizes)), tuple(axis_names))


def sharded_1d(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(SHARD_AXIS))


def row_sharded(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P(SHARD_AXIS, None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def padded_size(n: int, num_shards: int) -> int:
    """Smallest multiple of num_shards >= n (even HBM shards; the logical
    size is tracked separately, mirroring how the reference gives the last
    server the remainder, ref: src/table/array_table.cpp:98-108)."""
    if num_shards <= 0:
        return n
    return ((n + num_shards - 1) // num_shards) * num_shards


def device_count(mesh: Mesh) -> int:
    return int(np.prod(list(mesh.shape.values())))


@functools.lru_cache(maxsize=None)
def _zeros_fn(shape: Tuple[int, ...], dtype, sharding: NamedSharding):
    return jax.jit(lambda: jax.numpy.zeros(shape, dtype),
                   out_shardings=sharding)


def zeros_sharded(shape: Tuple[int, ...], dtype, sharding: NamedSharding):
    """Allocate a zero array already laid out shard-wise (no host roundtrip).

    The underlying jitted constructor is cached per (shape, dtype,
    sharding) so repeated table creation does not retrace."""
    return _zeros_fn(tuple(shape), np.dtype(dtype).name, sharding)()
