"""Device-mesh and sharding helpers (the ICI-native layer)."""

from .mesh import (SHARD_AXIS, device_count, local_mesh, make_mesh,  # noqa: F401
                   padded_size, replicated, row_sharded, sharded_1d,
                   zeros_sharded)
