"""Wire-serialized per-request hyperparameter structs.

Bit-compatible with the reference's ``AddOption``/``GetOption``
(ref: include/multiverso/updater/updater.h:10-110): a flat array of 4-byte
slots, each read as int32 or float32 —

- AddOption: [worker_id:i32, momentum:f32, learning_rate:f32, rho:f32,
  lambda:f32]
- GetOption: [worker_id:i32]

They ride as an extra trailing blob on Add/Get messages and are parsed
server-side (ref: src/table/matrix_table.cpp:392-395).
"""

from __future__ import annotations

import numpy as np

from ..core.blob import Blob


class AddOption:
    __slots__ = ("worker_id", "momentum", "learning_rate", "rho", "lambda_")
    NUM_SLOTS = 5

    def __init__(self, worker_id: int = 0, momentum: float = 0.0,
                 learning_rate: float = 0.01, rho: float = 0.1,
                 lambda_: float = 0.1):
        self.worker_id = int(worker_id)
        self.momentum = float(momentum)
        self.learning_rate = float(learning_rate)
        self.rho = float(rho)
        self.lambda_ = float(lambda_)

    def to_blob(self) -> Blob:
        raw = np.empty(self.NUM_SLOTS, dtype=np.float32)
        raw.view(np.int32)[0] = self.worker_id
        raw[1] = self.momentum
        raw[2] = self.learning_rate
        raw[3] = self.rho
        raw[4] = self.lambda_
        return Blob(raw.view(np.uint8))

    @classmethod
    def from_blob(cls, blob: Blob) -> "AddOption":
        raw = blob.as_array(np.float32)
        opt = cls()
        opt.worker_id = int(raw.view(np.int32)[0])
        opt.momentum = float(raw[1])
        opt.learning_rate = float(raw[2])
        opt.rho = float(raw[3])
        opt.lambda_ = float(raw[4])
        return opt

    def hyper_array(self) -> np.ndarray:
        """[momentum, lr, rho, lambda] as a jit argument (not static, so
        hyperparameter changes never retrace)."""
        return np.array([self.momentum, self.learning_rate,
                         self.rho, self.lambda_], dtype=np.float32)

    def __repr__(self) -> str:
        return (f"AddOption(worker_id={self.worker_id}, "
                f"momentum={self.momentum}, lr={self.learning_rate}, "
                f"rho={self.rho}, lambda={self.lambda_})")


class GetOption:
    __slots__ = ("worker_id",)
    NUM_SLOTS = 1

    def __init__(self, worker_id: int = 0):
        self.worker_id = int(worker_id)

    def to_blob(self) -> Blob:
        return Blob(np.array([self.worker_id], dtype=np.int32).view(np.uint8))

    @classmethod
    def from_blob(cls, blob: Blob) -> "GetOption":
        return cls(worker_id=int(blob.as_array(np.int32)[0]))

    def __repr__(self) -> str:
        return f"GetOption(worker_id={self.worker_id})"
