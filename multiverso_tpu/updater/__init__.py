"""Server-side optimizers as jit-compiled donated-buffer updates."""

from .engine import UpdateEngine, bucket_size, pad_rows  # noqa: F401
from .options import AddOption, GetOption  # noqa: F401
from .rules import (AdaGradRule, DefaultRule, MomentumRule, SGDRule,  # noqa: F401
                    UpdaterRule, create_rule)
