"""Jit-compiled updater engine: in-place donated updates on sharded state.

Binds an ``UpdaterRule`` to a concrete table: owns the optimizer state
(sharded like the table data) and the jitted dense/row update callables.
Donation (``donate_argnums``) lets XLA update the table buffers in place in
HBM — the TPU equivalent of the reference server's in-place OpenMP loops
(ref: src/updater/updater.cpp:24-31).

Row-sparse calls are padded to power-of-two bucket sizes so XLA compiles a
small, bounded set of scatter programs instead of one per distinct row
count (the host-variable-shape hazard called out in SURVEY.md §7).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import numpy as np

from ..sharding import mesh as meshlib
from .options import AddOption
from .rules import UpdaterRule, create_rule

_DEFAULT_HYP = AddOption().hyper_array()


def bucket_size(n: int, minimum: int = 8) -> int:
    """Smallest power of two >= n (>= minimum)."""
    size = minimum
    while size < n:
        size *= 2
    return size


class UpdateEngine:
    """Applies a rule to a table's device array with donated buffers."""

    def __init__(self, rule: Optional[UpdaterRule], shape, dtype,
                 num_workers: int, sharding=None):
        self.rule = rule if rule is not None else create_rule(dtype=dtype)
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)
        state = self.rule.init_state(self.shape, self.dtype, num_workers)
        if state is not None and sharding is not None:
            # Optimizer state lives shard-aligned with the data; the
            # per-worker leading axis (adagrad) is replicated.
            state = jax.device_put(state, _state_sharding(state, sharding))
        self._state = state

        # Table storage is padded to the mesh shard count (uneven shardings
        # are not device_put-able) and possibly to the 128-lane tile width
        # in the last dim (sub-lane rows scatter ~25x slower on v5e);
        # deltas arrive logical-sized and are zero-extended *inside* the
        # jit so XLA fuses the pad into the update — no host-side copy.
        def pad_cols(data, delta):
            """Zero-extend the delta's LAST dim to the storage width."""
            if delta.ndim >= 2 and data.shape[-1] != delta.shape[-1]:
                pad = [(0, 0)] * (delta.ndim - 1) \
                    + [(0, data.shape[-1] - delta.shape[-1])]
                delta = jax.numpy.pad(delta, pad)
            return delta

        def dense_padded(data, st, delta, hyp, worker_id):
            delta = pad_cols(data, delta)
            if data.shape[0] != delta.shape[0]:
                pad = ((0, data.shape[0] - delta.shape[0]),) \
                    + ((0, 0),) * (delta.ndim - 1)
                delta = jax.numpy.pad(delta, pad)
            return self.rule.dense(data, st, delta, hyp, worker_id)

        def pad_row_count(row_ids, delta):
            """Zero-extend a [k, ...] delta to the padded id count —
            in-jit, so a device delta costs no separate pad program
            (each standalone program execution costs ~10-15ms on the
            tunneled platform regardless of size)."""
            if delta.ndim >= 2 and row_ids.ndim == 1 \
                    and delta.shape[0] != row_ids.shape[0]:
                pad = ((0, row_ids.shape[0] - delta.shape[0]),) \
                    + ((0, 0),) * (delta.ndim - 1)
                delta = jax.numpy.pad(delta, pad)
            return delta

        def rows_padded(data, st, row_ids, delta, hyp, worker_id):
            delta = pad_row_count(row_ids, pad_cols(data, delta))
            return self.rule.rows(data, st, row_ids, delta, hyp,
                                  worker_id)

        self._pad_cols = pad_cols
        self._pad_row_count = pad_row_count
        self._dense = jax.jit(dense_padded, donate_argnums=(0, 1))
        self._rows = jax.jit(rows_padded, donate_argnums=(0, 1))
        self._rows_bounded = {}
        self._rows_gather = {}

    def apply_dense(self, data, delta, option: Optional[AddOption] = None):
        hyp, worker_id = _unpack(option)
        data, self._state = self._dense(data, self._state, delta,
                                        hyp, worker_id)
        return data

    def apply_rows(self, data, row_ids, delta,
                   option: Optional[AddOption] = None, bounds=None):
        """``row_ids`` int32[k], ``delta`` [k, ...]; pads to a power-of-two
        bucket with out-of-range indices (dropped by scatter). Device
        row_ids (any shape, delta shaped ids.shape + row shape) skip
        padding — the caller's shapes are already fixed, so each distinct
        caller shape compiles exactly once. ``bounds=(offset, n)`` maps
        GLOBAL row ids to this shard's local indices INSIDE the jit
        (foreign rows go out-of-range and drop) — one dispatch, not a
        separate masking op per request."""
        hyp, worker_id = _unpack(option)
        from ..core.blob import is_device_array
        if is_device_array(row_ids):
            # Device-key ids may carry duplicates, which only SUM
            # correctly under stateless rules (default/sgd scatter-add);
            # stateful rules apply .set per unique row and would corrupt
            # their state silently.
            from ..util.log import CHECK
            CHECK(self._state is None,
                  "device-key row adds need a stateless updater "
                  "(default/sgd): duplicate ids must sum")
        else:
            row_ids, delta = pad_rows(row_ids, delta, self.shape[0])
        rows_fn = self._rows if bounds is None \
            else self._bounded_rows_fn(bounds)
        data, self._state = rows_fn(data, self._state, row_ids, delta,
                                    hyp, worker_id)
        return data

    def _bounded_rows_fn(self, bounds):
        fn = self._rows_bounded.get(bounds)
        if fn is None:
            import jax.numpy as jnp
            ofs, n = bounds
            padded = self.shape[0]
            rule_rows = self.rule.rows

            def rows_fn(data, st, row_ids, delta, hyp, worker_id):
                # Foreign rows map to the padded row count: out of range
                # for the scatter (drop) — NOT merely offset-shifted,
                # which could land a foreign row inside this shard's
                # padding where a later masked gather would read it.
                row_ids = jnp.where((row_ids >= ofs) & (row_ids < ofs + n),
                                    row_ids - ofs, padded)
                return rule_rows(data, st, row_ids,
                                 self._pad_cols(data, delta), hyp,
                                 worker_id)

            fn = jax.jit(rows_fn, donate_argnums=(0, 1))
            self._rows_bounded[bounds] = fn
        return fn

    def apply_rows_gather(self, data, row_ids, delta, option,
                          get_ids, n_col: int):
        """FUSED row update + row gather in ONE compiled program: apply
        the delta, then gather ``get_ids`` from the UPDATED table. On a
        tunneled device each separately dispatched program pays a
        launch whose cost scales with its buffer arguments — for the
        sparse dirty-row roundtrip (add, then dirty get) that overhead
        is the measured bound, and fusing the pair halves it. Both id
        vectors MUST arrive padded to power-of-two buckets
        (out-of-range drops/zero-fills); the delta pads in-jit like
        apply_rows. Device-mirror ids are held to the same contract —
        an exact-k mirror would recompile the fused program for every
        distinct k (10s+ each on this platform) instead of once per
        bucket width."""
        hyp, worker_id = _unpack(option)
        from ..util.log import CHECK
        k = int(np.shape(row_ids)[0])
        CHECK(k == bucket_size(k),
              "apply_rows_gather ids must be bucket-padded "
              "(pad_ids on the host, a pad_ids-built device mirror)")
        fn = self._rows_gather.get(n_col)
        if fn is None:
            rule_rows = self.rule.rows
            pad_cols = self._pad_cols
            pad_row_count = self._pad_row_count

            def f(data, st, row_ids, delta, hyp, wid, get_ids):
                delta = pad_row_count(row_ids, pad_cols(data, delta))
                data, st = rule_rows(data, st, row_ids, delta, hyp,
                                     wid)
                values = data.at[get_ids].get(
                    mode="fill", fill_value=0)[..., :n_col]
                return data, st, values

            fn = jax.jit(f, donate_argnums=(0, 1))
            self._rows_gather[n_col] = fn
        data, self._state, values = fn(data, self._state, row_ids,
                                       delta, hyp, worker_id, get_ids)
        return data, values

    @property
    def state(self):
        return self._state


def _unpack(option: Optional[AddOption]) -> Tuple[np.ndarray, np.ndarray]:
    if option is None:
        return _DEFAULT_HYP, np.int32(0)
    return option.hyper_array(), np.int32(max(option.worker_id, 0))


def pad_ids(row_ids, num_rows: int) -> np.ndarray:
    """Pad a row-id vector to the next bucket size with an out-of-range
    sentinel (gather fills zeros, scatter drops)."""
    row_ids = np.asarray(row_ids, dtype=np.int32)
    b = bucket_size(row_ids.shape[0])
    if b != row_ids.shape[0]:
        row_ids = np.concatenate(
            [row_ids, np.full(b - row_ids.shape[0], num_rows,
                              dtype=np.int32)])
    return row_ids


def pad_rows(row_ids, delta, num_rows: int):
    """Pad (row_ids, delta) to the next bucket size; padding rows index
    out-of-range so scatter drops them and gather fills zeros. DEVICE
    deltas pass through logical-sized — the engine's rows jit extends
    them to the id count internally (a separate device pad would cost a
    full program launch per add)."""
    row_ids = np.asarray(row_ids, dtype=np.int32)
    k = row_ids.shape[0]
    b = bucket_size(k)
    if b != k:
        row_ids = np.concatenate(
            [row_ids, np.full(b - k, num_rows, dtype=np.int32)])
        from ..core.blob import is_device_array
        if not is_device_array(delta):
            pad = ((0, b - k),) + ((0, 0),) * (len(np.shape(delta)) - 1)
            delta = np.pad(np.asarray(delta), pad)
    return row_ids, delta


@functools.lru_cache(maxsize=None)
def _state_sharding_cached(ndim_state: int, data_sharding):
    mesh = data_sharding.mesh
    spec = data_sharding.spec
    # Prepend replicated axes for any leading state dims beyond the data's.
    pad = ndim_state - len(spec)
    from jax.sharding import NamedSharding, PartitionSpec
    return NamedSharding(mesh, PartitionSpec(*([None] * pad + list(spec))))


def _state_sharding(state, data_sharding):
    return _state_sharding_cached(np.ndim(state), data_sharding)
