"""Server-side optimizer rules as pure jittable functions.

TPU-native re-design of the reference's updater family
(ref: include/multiverso/updater/, src/updater/updater.cpp:23-58). The
reference applies per-element OpenMP loops on the server thread; here each
rule is a pure function over whole (sharded) arrays, jit-compiled once per
table with donated buffers so updates happen in-place in HBM, and a `rows`
variant using XLA scatter for row-sparse traffic.

Hyperparameters arrive as a traced float32[4] array ``hyp`` =
[momentum, learning_rate, rho, lambda] (from ``AddOption.hyper_array``) so
changing them never triggers recompilation; ``worker_id`` is a traced int32
scalar indexing per-worker optimizer state.

Formulas (and deviations):

- default: ``data += delta`` (ref: src/updater/updater.cpp:24-31)
- sgd: ``data -= delta`` — caller pre-multiplies the learning rate
  (ref: include/multiverso/updater/sgd_updater.h:15-19)
- momentum: ``smooth = m*smooth + (1-m)*delta; data -= smooth``
  (ref: include/multiverso/updater/momentum_updater.h:17-26)
- adagrad: per-worker accumulator ``G[w] += (delta/lr)^2``;
  ``data -= rho * (delta/lr) / sqrt(G[w] + e)``. NOTE: the reference's
  implementation (adagrad_updater.h:23-41) mutates a *copy* of the
  accumulator row and *subtracts* the squared gradient — two bugs that make
  its accumulator never persist and go negative; we implement the intended
  AdaGrad semantics its structure describes (per-worker historic squared
  gradients, lr-normalized delta, rho-scaled step).

- dcasgd: delay-compensated ASGD — see DCASGDRule (the reference ships
  this updater permanently disabled; here it works).

Duplicate row indices within one row-sparse Add compound correctly for
default/sgd (scatter-add); for momentum/adagrad/dcasgd the state update
applies once per unique row (the reference's sequential loop compounds
instead — callers there dedupe rows per block, e.g. WordEmbedding's
DataBlock).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..util import log
from ..util.configure import define_string, get_flag

define_string("updater_type", "default",
              "server updater: default / sgd / momentum / adagrad / "
              "dcasgd")

ADAGRAD_EPS = 1e-6  # ref: adagrad_updater.h:18


def _safe_lr(lr):
    """Rules that recover the gradient as delta/lr must not turn a
    user-supplied learning_rate=0 into inf/NaN written silently into the
    table — clamp away from zero (delta is 0 whenever lr is)."""
    return jnp.maximum(lr, jnp.asarray(1e-12, lr.dtype))


class UpdaterRule:
    """A pure update rule: (data, state, delta, hyp, worker_id) -> (data, state)."""

    name = "base"
    # True when init_state returns None — i.e. duplicate row ids in one
    # scatter-add SUM correctly. Worker-side device-key validation
    # consults this through create_rule so it cannot drift from the
    # engine's state handling.
    stateless = True

    def init_state(self, shape, dtype, num_workers: int) -> Any:
        return None

    def dense(self, data, state, delta, hyp, worker_id):
        raise NotImplementedError

    def rows(self, data, state, row_ids, delta, hyp, worker_id):
        """Row-sparse update. ``row_ids`` may be padded with out-of-range
        indices (>= data.shape[0]); padded entries are dropped by XLA
        scatter semantics."""
        raise NotImplementedError


class DefaultRule(UpdaterRule):
    name = "default"

    def dense(self, data, state, delta, hyp, worker_id):
        return data + delta, state

    def rows(self, data, state, row_ids, delta, hyp, worker_id):
        return data.at[row_ids].add(delta, mode="drop"), state


class SGDRule(UpdaterRule):
    name = "sgd"

    def dense(self, data, state, delta, hyp, worker_id):
        return data - delta, state

    def rows(self, data, state, row_ids, delta, hyp, worker_id):
        return data.at[row_ids].add(-delta, mode="drop"), state


class MomentumRule(UpdaterRule):
    name = "momentum"
    stateless = False

    def init_state(self, shape, dtype, num_workers: int):
        return jnp.zeros(shape, dtype)

    def dense(self, data, state, delta, hyp, worker_id):
        m = hyp[0].astype(data.dtype)
        smooth = m * state + (1 - m) * delta
        return data - smooth, smooth

    def rows(self, data, state, row_ids, delta, hyp, worker_id):
        m = hyp[0].astype(data.dtype)
        smooth_rows = (m * state.at[row_ids].get(mode="fill", fill_value=0)
                       + (1 - m) * delta)
        state = state.at[row_ids].set(smooth_rows, mode="drop")
        return data.at[row_ids].add(-smooth_rows, mode="drop"), state


class AdaGradRule(UpdaterRule):
    name = "adagrad"
    stateless = False

    def init_state(self, shape, dtype, num_workers: int):
        # Per-worker historic squared gradients, leading worker axis
        # (ref: adagrad_updater.h:17-21).
        return jnp.zeros((num_workers,) + tuple(shape), dtype)

    def dense(self, data, state, delta, hyp, worker_id):
        lr, rho = hyp[1].astype(data.dtype), hyp[2].astype(data.dtype)
        grad = delta / _safe_lr(lr)
        g_sqr = state[worker_id] + grad * grad
        step = rho * grad * jax.lax.rsqrt(g_sqr + ADAGRAD_EPS)
        return data - step, state.at[worker_id].set(g_sqr)

    def rows(self, data, state, row_ids, delta, hyp, worker_id):
        lr, rho = hyp[1].astype(data.dtype), hyp[2].astype(data.dtype)
        grad = delta / _safe_lr(lr)
        g_rows = state.at[worker_id, row_ids].get(mode="fill", fill_value=0)
        g_sqr = g_rows + grad * grad
        step = rho * grad * jax.lax.rsqrt(g_sqr + ADAGRAD_EPS)
        state = state.at[worker_id, row_ids].set(g_sqr, mode="drop")
        return data.at[row_ids].add(-step, mode="drop"), state


class DCASGDRule(UpdaterRule):
    """Delay-compensated ASGD (Zheng et al. 2017). The reference declares
    this updater but ships it permanently disabled — the source file is
    absent and the ENABLE_DCASGD macro is never defined
    (ref: src/updater/updater.cpp:2-9,53-55, CMakeLists.txt:9); this is a
    working implementation of the hook.

    The server keeps a per-worker parameter backup; a delta arriving from
    worker m (delta = lr * g, the sgd convention) is compensated for the
    staleness it accumulated since that worker's last update:

        w -= lr * (g + lambda * g * g * (w - backup[m]));  backup[m] = w

    The backup starts at zero, so each worker's FIRST push compensates
    against the origin — with the second-order term scaled by lambda this
    is benign, and every later push uses the true snapshot."""

    name = "dcasgd"
    stateless = False

    def init_state(self, shape, dtype, num_workers: int):
        return jnp.zeros((num_workers,) + tuple(shape), dtype)

    def dense(self, data, state, delta, hyp, worker_id):
        lr, lam = hyp[1].astype(data.dtype), hyp[3].astype(data.dtype)
        grad = delta / _safe_lr(lr)
        comp = lam * grad * grad * (data - state[worker_id])
        new = data - (delta + lr * comp)
        return new, state.at[worker_id].set(new)

    def rows(self, data, state, row_ids, delta, hyp, worker_id):
        lr, lam = hyp[1].astype(data.dtype), hyp[3].astype(data.dtype)
        grad = delta / _safe_lr(lr)
        rows_now = data.at[row_ids].get(mode="fill", fill_value=0)
        bak = state.at[worker_id, row_ids].get(mode="fill", fill_value=0)
        step = delta + lr * lam * grad * grad * (rows_now - bak)
        # Scatter-ADD the step so duplicate row ids compound their deltas
        # (matching sgd; the compensation term is evaluated against the
        # same pre-update rows for each duplicate, like momentum/adagrad's
        # once-per-unique-row state). The backup records one step for a
        # duplicated row — second-order staleness error, documented.
        data = data.at[row_ids].add(-step, mode="drop")
        state = state.at[worker_id, row_ids].set(rows_now - step,
                                                 mode="drop")
        return data, state


_RULES = {cls.name: cls for cls in
          (DefaultRule, SGDRule, MomentumRule, AdaGradRule, DCASGDRule)}
# The reference's flag value for the momentum updater is "momentum_sgd"
# (ref: src/updater/updater.cpp:47-58); accept both spellings.
_RULES["momentum_sgd"] = MomentumRule


def create_rule(updater_type: Optional[str] = None,
                dtype=np.float32) -> UpdaterRule:
    """Factory on the -updater_type flag (ref: src/updater/updater.cpp:42-58).
    Integer tables always get the default adder, as in the reference."""
    if np.issubdtype(np.dtype(dtype), np.integer):
        return DefaultRule()
    name = updater_type if updater_type is not None \
        else get_flag("updater_type")
    cls = _RULES.get(name)
    if cls is None:
        log.error("unknown updater_type %r; using default", name)
        return DefaultRule()
    return cls()
