"""Prototype: banded SGNS step exploiting window overlap.

Context positions of C consecutive centers span a contiguous band
kept[base-W : base+C+W]; gather those C+2W rows ONCE and form the 2W
context logits as shifted slices — 2W-fold less gather/scatter traffic
than the [C, 2W] row-gather formulation. Verify numerics against the
existing _apply_step, then slope-time it.
"""
import functools
import time
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

from multiverso_tpu.models.wordembedding.model import _MAX_EXP, _sigmoid_xent
from multiverso_tpu.models.wordembedding.device_train import (
    _window_and_negs, _apply_step)


def banded_step(C, W, K, n, emb_in, emb_out, kept_pad, ksent_pad,
                neg_prob, neg_alias, key, base, lr, n_kept,
                sort_scatter=True):
    """kept_pad/ksent_pad are padded with W sentinel entries on both
    sides (sentinel token 0 with sentence -2), so the band slice is
    always in range; position p of the unpadded stream is p+W here."""
    k_shrink, k_idx, k_keep = jax.random.split(key, 3)
    idx = base + jnp.arange(C, dtype=jnp.int32)       # center positions
    safe = jnp.minimum(idx, n - 1)
    centers = jax.lax.dynamic_slice_in_dim(kept_pad, base + W, C)
    csent = jax.lax.dynamic_slice_in_dim(ksent_pad, base + W, C)
    center_ok = (idx < n_kept) & (csent >= 0)
    shrink = jax.random.randint(k_shrink, (C,), 1, W + 1)

    band = jax.lax.dynamic_slice_in_dim(kept_pad, base, C + 2 * W)
    band_sent = jax.lax.dynamic_slice_in_dim(ksent_pad, base, C + 2 * W)

    # negatives per center, alias method
    draw = jax.random.randint(k_idx, (C, K), 0, neg_prob.shape[0])
    keep_draw = jax.random.uniform(k_keep, (C, K)) < neg_prob[draw]
    negs = jnp.where(keep_draw, draw, neg_alias[draw])

    v = emb_in[centers]                    # [C, D]
    u_band = emb_out[band]                 # [C+2W, D]
    u_neg = emb_out[negs]                  # [C, K, D]

    offs = [o for o in range(-W, W + 1) if o != 0]
    abs_offs = np.abs(np.array(offs))

    # Validity per (center, offset): in-band position p = c + W + off;
    # absolute stream position = idx + off must be in [0, n_kept) and
    # same sentence, |off| <= shrink, and the center itself valid.
    def pos_valid(w):
        off = offs[w]
        p = idx + off
        inb = (p >= 0) & (p < n_kept)
        s = jax.lax.dynamic_slice_in_dim(band_sent, W + off, C)
        return (inb & (s == csent) & (abs_offs[w] <= shrink)
                & center_ok).astype(jnp.float32)

    pmask = jnp.stack([pos_valid(w) for w in range(2 * W)], axis=1)
    nvalid = pmask.sum(axis=1)

    def loss_fn(v, u_band, u_neg):
        pos_logits = []
        for w, off in enumerate(offs):
            u_off = jax.lax.dynamic_slice_in_dim(u_band, W + off, C)
            pos_logits.append(jnp.sum(v * u_off, axis=-1))
        pos = jnp.clip(jnp.stack(pos_logits, axis=1), -_MAX_EXP, _MAX_EXP)
        neg = jnp.clip(jnp.einsum("cd,ckd->ck", v, u_neg),
                       -_MAX_EXP, _MAX_EXP)
        xp = _sigmoid_xent(pos, 1.0) * pmask
        xn = _sigmoid_xent(neg, 0.0) * nvalid[:, None]
        return xp.sum() + xn.sum()

    loss, (g_v, g_band, g_neg) = jax.value_and_grad(
        loss_fn, argnums=(0, 1, 2))(v, u_band, u_neg)

    emb_in = emb_in.at[centers].add(-lr * g_v)
    if sort_scatter:
        flat_ids = jnp.concatenate([band, negs.reshape(-1)])
        flat_g = jnp.concatenate(
            [g_band, g_neg.reshape(-1, g_neg.shape[-1])])
        order = jnp.argsort(flat_ids)
        emb_out = emb_out.at[flat_ids[order]].add(
            -lr * flat_g[order], indices_are_sorted=True)
    else:
        emb_out = emb_out.at[band].add(-lr * g_band)
        emb_out = emb_out.at[negs].add(-lr * g_neg)
    return emb_in, emb_out, loss, pmask.sum()


def pad_stream(kept, ksent, W, C):
    # Left pad W; right pad C+W so the band slice NEVER clamps (a
    # clamped dynamic_slice shifts the whole window and misaligns valid
    # centers on the epoch's tail step). Padding carries sentence -2:
    # never matches a real sentence, so everything there is masked.
    kp = jnp.pad(kept, (W, C + W))
    ks = jnp.pad(ksent, (W, C + W), constant_values=-2)
    return kp, ks


# ---------- numeric parity on small shapes (CPU-friendly sizes) ----------
def check_numerics():
    V, D, n = 500, 16, 4000
    C, W, K = 64, 5, 5
    key = jax.random.PRNGKey(7)
    kept = jax.random.randint(key, (n,), 0, V, jnp.int32)
    ksent = jnp.repeat(jnp.arange(n // 20, dtype=jnp.int32), 20)[:n]
    neg_prob = jnp.ones((V,)) * 0.5
    neg_alias = jax.random.randint(key, (V,), 0, V, jnp.int32)
    emb_in = jax.random.normal(key, (V, D), jnp.float32) * 0.1
    emb_out = jax.random.normal(jax.random.PRNGKey(8), (V, D)) * 0.1
    n_kept = jnp.int32(n - 100)
    base = jnp.int32(1200)
    lr = jnp.float32(0.025)
    step_key = jax.random.PRNGKey(42)

    ref = _apply_step(C, W, K, n, False, emb_in, emb_out, kept, ksent,
                      neg_prob, neg_alias, step_key, base, lr, n_kept)
    kp, ks = pad_stream(kept, ksent, W, C)
    new = banded_step(C, W, K, n, emb_in, emb_out, kp, ks,
                      neg_prob, neg_alias, step_key, base, lr, n_kept)
    for name, a, b in (("emb_in", ref[0], new[0]),
                       ("emb_out", ref[1], new[1]),
                       ("loss", ref[2], new[2]),
                       ("pairs", ref[3], new[3])):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        diff = np.max(np.abs(a - b)) if a.shape else abs(a - b)
        print(f"  {name}: max|diff| = {diff:.3e}")
        assert diff < 2e-4, (name, diff)
    # also check a boundary base (start of stream)
    for b0 in (0, n - C // 2):
        ref = _apply_step(C, W, K, n, False, emb_in, emb_out, kept,
                          ksent, neg_prob, neg_alias, step_key,
                          jnp.int32(b0), lr, n_kept)
        new = banded_step(C, W, K, n, emb_in, emb_out, kp, ks, neg_prob,
                          neg_alias, step_key, jnp.int32(b0), lr, n_kept)
        d = float(np.max(np.abs(np.asarray(ref[1]) - np.asarray(new[1]))))
        print(f"  base={b0}: emb_out max|diff| = {d:.3e}")
        assert d < 2e-4


print("numeric parity:")
check_numerics()
print("OK")

# ---------- speed at bench shapes ----------
V, D = 1_013_245, 128
N = 6_000_000
C, W, K = 32768, 5, 5
key = jax.random.PRNGKey(0)
kept = jax.random.randint(key, (N,), 0, V, jnp.int32)
ksent = jnp.repeat(jnp.arange(N // 40, dtype=jnp.int32), 40)[:N]
kp, ks = pad_stream(kept, ksent, W, C)
neg_prob = jax.random.uniform(key, (V,))
neg_alias = jax.random.randint(key, (V,), 0, V, jnp.int32)
n_kept = jnp.int32(N - 1000)


def force(x):
    return float(jnp.ravel(x)[0])


def slope_time(build, lo=4, hi=16):
    def run(G):
        fn = build(G)
        emb_in = jnp.zeros((V, D), jnp.float32)
        emb_out = jnp.zeros((V, D), jnp.float32)
        out = fn(emb_in, emb_out, jax.random.PRNGKey(1))
        force(out)
        best = float("inf")
        for _ in range(3):
            emb_in = jnp.zeros((V, D), jnp.float32)
            emb_out = jnp.zeros((V, D), jnp.float32)
            force(emb_in); force(emb_out)
            t0 = time.perf_counter()
            out = fn(emb_in, emb_out, jax.random.PRNGKey(2))
            force(out)
            best = min(best, time.perf_counter() - t0)
        return best
    t_lo, t_hi = run(lo), run(hi)
    return (t_hi - t_lo) / (hi - lo)


def build_banded(sort_scatter):
    def build(G):
        @functools.partial(jax.jit, donate_argnums=(0, 1),
                           static_argnums=3)
        def f(emb_in, emb_out, key, g):
            def body(carry, base):
                emb_in, emb_out, key = carry
                key, sub = jax.random.split(key)
                emb_in, emb_out, loss, pairs = banded_step(
                    C, W, K, N, emb_in, emb_out, kp, ks, neg_prob,
                    neg_alias, sub, base, jnp.float32(0.01), n_kept,
                    sort_scatter=sort_scatter)
                return (emb_in, emb_out, key), loss
            bases = jnp.arange(g, dtype=jnp.int32) * C
            (emb_in, emb_out, key), losses = jax.lax.scan(
                body, (emb_in, emb_out, key), bases)
            return losses.sum() + emb_in[0, 0] + emb_out[0, 0]
        return lambda a, b, k2: f(a, b, k2, G)
    return build


for sort in (False, True):
    s = slope_time(build_banded(sort))
    print(f"banded sort={sort}: {s*1e3:8.2f} ms/step  "
          f"{C/s/1e6:6.2f} M centers/s")


# ---------- variant: negatives shared across blocks of B centers ----------
def banded_step_blockneg(C, W, K, B, n, emb_in, emb_out, kept_pad,
                         ksent_pad, neg_prob, neg_alias, key, base, lr,
                         n_kept):
    k_shrink, k_idx, k_keep = jax.random.split(key, 3)
    idx = base + jnp.arange(C, dtype=jnp.int32)
    centers = jax.lax.dynamic_slice_in_dim(kept_pad, base + W, C)
    csent = jax.lax.dynamic_slice_in_dim(ksent_pad, base + W, C)
    center_ok = (idx < n_kept) & (csent >= 0)
    shrink = jax.random.randint(k_shrink, (C,), 1, W + 1)
    band = jax.lax.dynamic_slice_in_dim(kept_pad, base, C + 2 * W)
    band_sent = jax.lax.dynamic_slice_in_dim(ksent_pad, base, C + 2 * W)
    nb = C // B
    draw = jax.random.randint(k_idx, (nb, K), 0, neg_prob.shape[0])
    keep_draw = jax.random.uniform(k_keep, (nb, K)) < neg_prob[draw]
    negs = jnp.where(keep_draw, draw, neg_alias[draw])   # [nb, K]

    v = emb_in[centers]
    u_band = emb_out[band]
    u_neg = emb_out[negs]                                # [nb, K, D]

    offs = [o for o in range(-W, W + 1) if o != 0]
    abs_offs = np.abs(np.array(offs))

    def pos_valid(w):
        off = offs[w]
        p = idx + off
        inb = (p >= 0) & (p < n_kept)
        s = jax.lax.dynamic_slice_in_dim(band_sent, W + off, C)
        return (inb & (s == csent) & (abs_offs[w] <= shrink)
                & center_ok).astype(jnp.float32)

    pmask = jnp.stack([pos_valid(w) for w in range(2 * W)], axis=1)
    nvalid = pmask.sum(axis=1)

    def loss_fn(v, u_band, u_neg):
        pos_logits = []
        for w, off in enumerate(offs):
            u_off = jax.lax.dynamic_slice_in_dim(u_band, W + off, C)
            pos_logits.append(jnp.sum(v * u_off, axis=-1))
        pos = jnp.clip(jnp.stack(pos_logits, axis=1), -_MAX_EXP, _MAX_EXP)
        vb = v.reshape(nb, B, v.shape[-1])
        neg = jnp.clip(jnp.einsum("nbd,nkd->nbk", vb, u_neg),
                       -_MAX_EXP, _MAX_EXP)
        xp = _sigmoid_xent(pos, 1.0) * pmask
        xn = _sigmoid_xent(neg, 0.0) * nvalid.reshape(nb, B)[:, :, None]
        return xp.sum() + xn.sum()

    loss, (g_v, g_band, g_neg) = jax.value_and_grad(
        loss_fn, argnums=(0, 1, 2))(v, u_band, u_neg)
    emb_in = emb_in.at[centers].add(-lr * g_v)
    emb_out = emb_out.at[band].add(-lr * g_band)
    emb_out = emb_out.at[negs].add(-lr * g_neg)
    return emb_in, emb_out, loss, pmask.sum()


def build_blockneg(B):
    def build(G):
        @functools.partial(jax.jit, donate_argnums=(0, 1),
                           static_argnums=3)
        def f(emb_in, emb_out, key, g):
            def body(carry, base):
                emb_in, emb_out, key = carry
                key, sub = jax.random.split(key)
                emb_in, emb_out, loss, pairs = banded_step_blockneg(
                    C, W, K, B, N, emb_in, emb_out, kp, ks, neg_prob,
                    neg_alias, sub, base, jnp.float32(0.01), n_kept)
                return (emb_in, emb_out, key), loss
            bases = jnp.arange(g, dtype=jnp.int32) * C
            (emb_in, emb_out, key), losses = jax.lax.scan(
                body, (emb_in, emb_out, key), bases)
            return losses.sum() + emb_in[0, 0] + emb_out[0, 0]
        return lambda a, b, k2: f(a, b, k2, G)
    return build


if __name__ == "__main__":
    for B in (8, 32):
        s = slope_time(build_blockneg(B))
        print(f"banded blockneg B={B}: {s*1e3:8.2f} ms/step  "
              f"{C/s/1e6:6.2f} M centers/s")
