"""Microbenchmark: scatter-add / gather / sweep cost at the bench table
shape (1M x 128 f32) on the real chip. Timing forces completion with a
scalar readback (block_until_ready lies on this platform)."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

R, C = 1_000_000, 128
TABLE_BYTES = R * C * 4


def force(x):
    return float(jnp.ravel(x)[0])


def timeit(fn, *args, n=6):
    fn(*args)  # compile
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        force(out if not isinstance(out, tuple) else out[0])
        best = min(best, time.perf_counter() - t0)
    return best


key = jax.random.PRNGKey(0)
table = jnp.zeros((R, C), jnp.float32)

results = {}

# Pure sweep: read+write whole table.
sweep = jax.jit(lambda t: t + 1.0)
dt = timeit(sweep, table)
results["sweep_add1"] = (dt, 2 * TABLE_BYTES / dt / 1e9)

# copy (read+write, no donation)
copyf = jax.jit(lambda t: jnp.copy(t))
dt = timeit(copyf, table)
results["copy"] = (dt, 2 * TABLE_BYTES / dt / 1e9)

for k in (1024, 32768, 491520):
    ids = jax.random.randint(key, (k,), 0, R, jnp.int32)
    delta = jnp.ones((k, C), jnp.float32)
    io_bytes = 2 * k * C * 4

    # scatter-add, donated buffer (the hot-path form)
    scat = jax.jit(lambda t, i, d: t.at[i].add(d), donate_argnums=0)
    tt = jnp.zeros((R, C), jnp.float32)
    scat(tt, ids, delta)  # compile w/ donation (consumes tt)
    times = []
    for _ in range(5):
        tt = jnp.zeros((R, C), jnp.float32)
        force(tt)
        t0 = time.perf_counter()
        tt = scat(tt, ids, delta)
        force(tt)
        times.append(time.perf_counter() - t0)
    dt = min(times)
    results[f"scatter_donated_k{k}"] = (dt, io_bytes / dt / 1e9)

    # scatter-add sorted-unique flags via segment_sum + sorted ids
    def scat_sorted(t, i, d):
        si = jnp.sort(i)
        order = jnp.argsort(i)
        return t.at[si].add(d[order], indices_are_sorted=True), si

    scat_s = jax.jit(scat_sorted, donate_argnums=0)
    tt = jnp.zeros((R, C), jnp.float32)
    scat_s(tt, ids, delta)
    times = []
    for _ in range(5):
        tt = jnp.zeros((R, C), jnp.float32)
        force(tt)
        t0 = time.perf_counter()
        out = scat_s(tt, ids, delta)
        force(out[0])
        tt = out[0]
        times.append(time.perf_counter() - t0)
    dt = min(times)
    results[f"scatter_sorted_k{k}"] = (dt, io_bytes / dt / 1e9)

    # gather
    gath = jax.jit(lambda t, i: t[i])
    dt = timeit(gath, table, ids)
    results[f"gather_k{k}"] = (dt, k * C * 4 / dt / 1e9)

# scan of G=8 scatter-adds inside ONE jit (the group structure):
# measures whether XLA amortizes anything across steps.
G = 8
k = 32768
ids_g = jax.random.randint(key, (G, k), 0, R, jnp.int32)
delta_g = jnp.ones((G, k, C), jnp.float32)


@functools.partial(jax.jit, donate_argnums=0)
def scan_scatter(t, ids, deltas):
    def body(t, xs):
        i, d = xs
        return t.at[i].add(d), 0.0
    t, _ = jax.lax.scan(body, t, (ids, deltas))
    return t


tt = jnp.zeros((R, C), jnp.float32)
scan_scatter(tt, ids_g, delta_g)
times = []
for _ in range(4):
    tt = jnp.zeros((R, C), jnp.float32)
    force(tt)
    t0 = time.perf_counter()
    tt = scan_scatter(tt, ids_g, delta_g)
    force(tt)
    times.append(time.perf_counter() - t0)
dt = min(times)
results[f"scan{G}_scatter_k{k}"] = (dt / G, 2 * k * C * 4 / (dt / G) / 1e9)

for name, (dt, gbps) in results.items():
    print(f"{name:28s} {dt*1e3:9.3f} ms  {gbps:8.2f} GB/s(io)")
