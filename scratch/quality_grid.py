"""Quality grid on the real TPU: topic separation vs (C, neg_block,
epochs). Target: reach the C++ baseline's 3-epoch separation (~1.03)
in minimal wall clock."""
import sys
import tempfile
import time

sys.path.insert(0, "/root/repo")
import bench  # noqa: E402
bench._enable_compilation_cache()

import numpy as np  # noqa: E402

corpus = tempfile.mkdtemp() + "/corpus.txt"
bench.write_corpus(corpus)
prebuilt = bench._build(corpus)
print(f"vocab={prebuilt[0].size}", flush=True)

from multiverso_tpu.models.wordembedding import (  # noqa: E402
    DeviceCorpusTrainer, Word2Vec, Word2VecConfig)

CPP_SEP = 1.0305


def run(centers, neg_block, epochs, lr=0.025, dispatch=16, K=bench.NEG):
    config = Word2VecConfig(embedding_size=bench.DIM, window=5,
                            negative=K, epochs=epochs,
                            sample=1e-3, init_learning_rate=lr,
                            neg_block=neg_block)
    model = Word2Vec(config, prebuilt[0])
    trainer = DeviceCorpusTrainer(model, prebuilt[1], centers, dispatch)
    # warm
    trainer.train_epoch(seed=99, max_steps=2 * dispatch)
    float(model._emb_in[0, 0])
    model = Word2Vec(config, prebuilt[0])
    trainer = DeviceCorpusTrainer(model, prebuilt[1], centers, dispatch)
    float(model._emb_in[0, 0])
    float(trainer._corpus.flat[0])
    import jax.numpy as jnp

    def fetch_rows(ids):
        # 48-row device gather + tiny download — NEVER download the
        # full table over the tunnel (512 MB at ~3 MB/s).
        return np.asarray(model._emb_in[jnp.asarray(ids)])

    t0 = time.perf_counter()
    losses = []
    seps = []
    for e in range(epochs):
        loss, pairs = trainer.train_epoch(seed=e)
        losses.append(loss / max(pairs, 1))
        float(model._emb_in[0, 0])
        seps.append(round(float(bench.topic_separation(
            None, prebuilt[0], fetch_rows=fetch_rows)), 4))
    total = time.perf_counter() - t0
    print(f"C={centers:6d} B={neg_block:2d} ep={epochs:2d} lr={lr} "
          f"K={trainer.config.negative}: {total:6.1f}s  "
          f"losses[{losses[0]:.3f}..{losses[-1]:.3f}] seps={seps}",
          flush=True)
    return seps, total


import sys as _sys
args = _sys.argv[1:]
centers, nb, epochs = int(args[0]), int(args[1]), int(args[2])
lr = float(args[3]) if len(args) > 3 else 0.025
disp = int(args[4]) if len(args) > 4 else 16
K = int(args[5]) if len(args) > 5 else bench.NEG
run(centers, nb, epochs, lr=lr, dispatch=disp, K=K)
