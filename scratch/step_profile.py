"""Slope-time the real SGNS group step and its pieces at bench shapes.

Pieces: prep-ids (window former + negs), row gathers, loss+grads,
scatter-adds — each cumulative variant scanned G times inside one jit, so
the ~100ms readback RTT cancels in the slope.
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

import sys
sys.path.insert(0, "/root/repo")
jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

from multiverso_tpu.models.wordembedding.device_train import (
    _window_and_negs, _sgns_loss_and_grads, _apply_step)

V, D = 1_013_245, 128
N = 6_000_000          # corpus tokens
C, W, K = 32768, 5, 5
key = jax.random.PRNGKey(0)

kept = jax.random.randint(key, (N,), 0, V, jnp.int32)
ksent = jnp.repeat(jnp.arange(N // 40, dtype=jnp.int32), 40)[:N]
neg_prob = jax.random.uniform(key, (V,))
neg_alias = jax.random.randint(key, (V,), 0, V, jnp.int32)
n_kept = jnp.int32(N - 1000)


def force(x):
    return float(jnp.ravel(x)[0])


def slope_time(build, lo=4, hi=16):
    def run(G):
        emb_in = jnp.zeros((V, D), jnp.float32)
        emb_out = jnp.zeros((V, D), jnp.float32)
        fn = build(G)
        out = fn(emb_in, emb_out, jax.random.PRNGKey(1))
        force(out)
        best = float("inf")
        for _ in range(3):
            emb_in = jnp.zeros((V, D), jnp.float32)
            emb_out = jnp.zeros((V, D), jnp.float32)
            force(emb_in); force(emb_out)
            t0 = time.perf_counter()
            out = fn(emb_in, emb_out, jax.random.PRNGKey(2))
            force(out)
            best = min(best, time.perf_counter() - t0)
        return best
    t_lo, t_hi = run(lo), run(hi)
    return (t_hi - t_lo) / (hi - lo)


def variant(stage):
    def build(G):
        @functools.partial(jax.jit, donate_argnums=(0, 1),
                           static_argnums=3)
        def f(emb_in, emb_out, key, g):
            def body(carry, base):
                emb_in, emb_out, key = carry
                key, sub = jax.random.split(key)
                centers, ctx, negs, pmask = _window_and_negs(
                    C, W, K, N, kept, ksent, neg_prob, neg_alias, sub,
                    base, n_kept)
                if stage == "ids":
                    s = (centers.sum() + ctx.sum() + negs.sum()
                         + pmask.sum())
                    return (emb_in, emb_out, key), s.astype(jnp.float32)
                v = emb_in[centers]
                u_ctx = emb_out[ctx]
                u_neg = emb_out[negs]
                if stage == "gather":
                    s = v.sum() + u_ctx.sum() + u_neg.sum()
                    return (emb_in, emb_out, key), s
                loss, g_v, g_ctx, g_neg = _sgns_loss_and_grads(
                    v, u_ctx, u_neg, pmask)
                if stage == "grads":
                    s = loss + g_v.sum() + g_ctx.sum() + g_neg.sum()
                    return (emb_in, emb_out, key), s
                emb_in = emb_in.at[centers].add(-0.01 * g_v)
                out_ids = jnp.concatenate([ctx, negs], axis=1)
                g_out = jnp.concatenate([g_ctx, g_neg], axis=1)
                emb_out = emb_out.at[out_ids].add(-0.01 * g_out)
                return (emb_in, emb_out, key), loss

            bases = jnp.arange(g, dtype=jnp.int32) * C
            (emb_in, emb_out, key), outs = jax.lax.scan(
                body, (emb_in, emb_out, key), bases)
            return outs.sum() + emb_in[0, 0] + emb_out[0, 0]
        return lambda a, b, k2: f(a, b, k2, G)
    return build


for stage in ("ids", "gather", "grads", "full"):
    s = slope_time(variant(stage))
    words_per_sec = C / s
    print(f"{stage:8s} {s*1e3:8.2f} ms/step   {words_per_sec/1e6:6.2f} "
          f"M centers/s")
