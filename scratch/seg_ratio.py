"""Probe: two-server same-window ratio, broadcast vs segmented device
keys, on the real chip. Mirrors bench.run_ps_two_servers' protocol
(warm outside the window, same block count) but runs all three configs
back-to-back so launch weather cancels. Also pre-warms the segmented
programs into the persistent compile cache for the bench."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402  (corpus/config constants)

import numpy as np  # noqa: E402


def main():
    bench._enable_compilation_cache()
    import tempfile
    tmp = tempfile.mkdtemp()
    corpus = os.path.join(tmp, "corpus.txt")
    bench.SENTENCES = 60_000  # enough kept tokens for 2*G warm + 48 blocks
    print("[probe] corpus...", file=sys.stderr, flush=True)
    bench.write_corpus(corpus)
    dictionary, tokenized = bench._build(corpus)
    print(f"[probe] vocab={dictionary.size}", file=sys.stderr, flush=True)

    from multiverso_tpu.models.wordembedding import (PSDeviceCorpusTrainer,
                                                     PSWord2Vec,
                                                     Word2VecConfig)
    from multiverso_tpu.runtime.cluster import LocalCluster

    blocks = 48

    def make_body(segment):
        def body(rank):
            import multiverso_tpu as mv
            config = Word2VecConfig(embedding_size=bench.DIM, window=5,
                                    negative=bench.NEG,
                                    epochs=bench.EPOCHS,
                                    batch_size=bench.BATCH, sample=1e-3,
                                    use_ps=True,
                                    neg_block=bench.NEG_BLOCK)
            model = PSWord2Vec(config, dictionary)
            if rank == 1:
                for _ in range(2):
                    mv.current_zoo().barrier()
                return None
            trainer = PSDeviceCorpusTrainer(
                model, tokenized, bench.PS_CENTERS,
                blocks_per_dispatch=bench.PS_GROUP,
                segment_keys=segment)
            trainer.train_epoch(seed=99, max_steps=2 * bench.PS_GROUP)
            w0 = model.trained_words
            t0 = time.perf_counter()
            trainer.train_epoch(seed=0, max_steps=blocks)
            return model.trained_words - w0, time.perf_counter() - t0
        return body

    results = {}
    for name, n, segment in [("single", 1, False),
                             ("broadcast2", 2, False),
                             ("segmented2", 2, True),
                             ("single_b", 1, False)]:
        cluster = LocalCluster(n, roles=["all", "server"][:n] or ["all"])
        cluster.timeout = 900.0
        t0 = time.perf_counter()
        words, elapsed = cluster.run(make_body(segment))[0]
        results[name] = words / elapsed
        print(f"[probe] {name}: {results[name]:,.0f} words/s "
              f"(phase wall {time.perf_counter() - t0:.1f}s)",
              file=sys.stderr, flush=True)

    single = (results["single"] + results["single_b"]) / 2
    print(f"ratio broadcast2/single = {results['broadcast2'] / single:.3f}")
    print(f"ratio segmented2/single = {results['segmented2'] / single:.3f}")


if __name__ == "__main__":
    main()
