"""Quick TPU measurement of the banded local trainer at bench shapes."""
import sys
import tempfile
import time

sys.path.insert(0, "/root/repo")
import bench  # noqa: E402
bench._enable_compilation_cache()

import numpy as np  # noqa: E402

corpus = tempfile.mkdtemp() + "/corpus.txt"
t0 = time.time()
bench.write_corpus(corpus)
prebuilt = bench._build(corpus)
print(f"corpus+dict: {time.time()-t0:.1f}s, "
      f"vocab={prebuilt[0].size}", flush=True)

from multiverso_tpu.models.wordembedding import (  # noqa: E402
    DeviceCorpusTrainer, Word2Vec, Word2VecConfig)

for neg_block, centers in ((1, 16384), (8, 16384), (32, 16384),
                           (32, 32768), (8, 8192)):
    config = Word2VecConfig(embedding_size=bench.DIM, window=5,
                            negative=bench.NEG, epochs=1,
                            batch_size=bench.BATCH, sample=1e-3,
                            neg_block=neg_block)
    model = Word2Vec(config, prebuilt[0])
    trainer = DeviceCorpusTrainer(model, prebuilt[1], centers, 16)
    # warm both layout variants
    trainer.train_epoch(seed=99, max_steps=32)
    float(model._emb_in[0, 0])
    model = Word2Vec(config, prebuilt[0])
    trainer = DeviceCorpusTrainer(model, prebuilt[1], centers, 16)
    float(model._emb_in[0, 0])
    float(trainer._corpus.flat[0])
    t0 = time.perf_counter()
    loss, pairs = trainer.train_epoch(seed=0)
    el = time.perf_counter() - t0
    print(f"neg_block={neg_block:2d} C={centers:5d}: "
          f"{model.trained_words/el/1e6:6.2f} M raw words/s  "
          f"loss/pair={loss/max(pairs,1):.4f}  epoch={el:.1f}s",
          flush=True)
