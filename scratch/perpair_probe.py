"""Does per-PAIR negative drawing (the reference's exact sampling
structure) close the separation gap vs per-center shared negatives?"""
import functools
import sys
import tempfile
import time

sys.path.insert(0, "/root/repo")
import bench  # noqa: E402
bench._enable_compilation_cache()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from multiverso_tpu.models.wordembedding.model import (  # noqa: E402
    _MAX_EXP, _sigmoid_xent)
from multiverso_tpu.models.wordembedding.device_train import (  # noqa: E402
    _band_former, _pad_stream, _prep)
from multiverso_tpu.models.wordembedding import (  # noqa: E402
    Word2Vec, Word2VecConfig)

corpus = tempfile.mkdtemp() + "/corpus.txt"
bench.write_corpus(corpus)
prebuilt = bench._build(corpus)
dictionary, tokenized = prebuilt
print(f"vocab={dictionary.size}", flush=True)

C, W, K, G = int(sys.argv[1]) if len(sys.argv) > 1 else 2048, 5, 5, 32
EPOCHS = int(sys.argv[2]) if len(sys.argv) > 2 else 3
LR = float(sys.argv[3]) if len(sys.argv) > 3 else 0.025


SEQ_OFFSETS = True


def make_group(C, W, K):
    offs = [o for o in range(-W, W + 1) if o != 0]

    def step(emb_in, emb_out, kept_pad, ksent_pad, neg_prob, neg_alias,
             key, base, lr, n_kept):
        k_shrink, k_idx, k_keep = jax.random.split(key, 3)
        centers, band, pmask = _band_former(C, W, n_kept, kept_pad,
                                            ksent_pad, k_shrink, base)
        if not SEQ_OFFSETS:
            draw = jax.random.randint(k_idx, (C, 2 * W, K), 0,
                                      neg_prob.shape[0])
            keep_draw = jax.random.uniform(k_keep, (C, 2 * W, K)) \
                < neg_prob[draw]
            negs = jnp.where(keep_draw, draw, neg_alias[draw])
            v = emb_in[centers]
            u_band = emb_out[band]
            u_neg = emb_out[negs]

            def loss_fn(v, u_band, u_neg):
                pos = jnp.stack(
                    [jnp.sum(v * jax.lax.dynamic_slice_in_dim(
                        u_band, W + off, C), axis=-1) for off in offs],
                    axis=1)
                pos = jnp.clip(pos, -_MAX_EXP, _MAX_EXP)
                neg = jnp.clip(jnp.einsum("cd,cwkd->cwk", v, u_neg),
                               -_MAX_EXP, _MAX_EXP)
                xp = _sigmoid_xent(pos, 1.0) * pmask
                xn = _sigmoid_xent(neg, 0.0) * pmask[..., None]
                return xp.sum() + xn.sum()

            loss, (g_v, g_band, g_neg) = jax.value_and_grad(
                loss_fn, argnums=(0, 1, 2))(v, u_band, u_neg)
            emb_in = emb_in.at[centers].add(-lr * g_v)
            emb_out = emb_out.at[band].add(-lr * g_band)
            emb_out = emb_out.at[negs].add(-lr * g_neg)
            return emb_in, emb_out, loss, pmask.sum()

        # 2W SEQUENTIAL sub-steps: each offset's C pairs train against
        # tables already updated by the previous offsets — one notch
        # closer to the reference's pair-by-pair SGD, with per-pair
        # negatives. Unrolled python loop inside the jit.
        loss_acc = 0.0
        draw = jax.random.randint(k_idx, (2 * W, C, K), 0,
                                  neg_prob.shape[0])
        keep_draw = jax.random.uniform(k_keep, (2 * W, C, K)) \
            < neg_prob[draw]
        negs_all = jnp.where(keep_draw, draw, neg_alias[draw])
        for w, off in enumerate(offs):
            ctx = jax.lax.dynamic_slice_in_dim(band, W + off, C)
            m = pmask[:, w]
            negs = negs_all[w]                      # [C, K]
            v = emb_in[centers]
            u_pos = emb_out[ctx]
            u_neg = emb_out[negs]

            def loss_fn(v, u_pos, u_neg, m=m):
                pos = jnp.clip(jnp.sum(v * u_pos, axis=-1),
                               -_MAX_EXP, _MAX_EXP)
                neg = jnp.clip(jnp.einsum("cd,ckd->ck", v, u_neg),
                               -_MAX_EXP, _MAX_EXP)
                return (jnp.sum(_sigmoid_xent(pos, 1.0) * m)
                        + jnp.sum(_sigmoid_xent(neg, 0.0)
                                  * m[:, None]))

            loss, (g_v, g_pos, g_neg) = jax.value_and_grad(
                loss_fn, argnums=(0, 1, 2))(v, u_pos, u_neg)
            emb_in = emb_in.at[centers].add(-lr * g_v)
            emb_out = emb_out.at[ctx].add(-lr * g_pos)
            emb_out = emb_out.at[negs].add(-lr * g_neg)
            loss_acc = loss_acc + loss
        return emb_in, emb_out, loss_acc, pmask.sum()

    @functools.partial(jax.jit, donate_argnums=(0, 1))
    def group(emb_in, emb_out, kept, ksent, neg_prob, neg_alias, key,
              bases, lrs, n_kept):
        kept, ksent = _pad_stream(C, W, kept, ksent)

        def body(carry, xs):
            emb_in, emb_out, key = carry
            base, lr = xs
            key, sub = jax.random.split(key)
            emb_in, emb_out, loss, pairs = step(
                emb_in, emb_out, kept, ksent, neg_prob, neg_alias,
                sub, base, lr, n_kept)
            return (emb_in, emb_out, key), (loss, pairs)

        (emb_in, emb_out, key), (losses, pairs) = jax.lax.scan(
            body, (emb_in, emb_out, key), (bases, lrs))
        return emb_in, emb_out, losses.sum(), pairs.sum(), key

    return group


config = Word2VecConfig(embedding_size=bench.DIM, window=W, negative=K,
                        epochs=EPOCHS, sample=1e-3,
                        init_learning_rate=LR)
model = Word2Vec(config, dictionary)
group = make_group(C, W, K)

import math
from multiverso_tpu.models.wordembedding.device_train import \
    _CorpusOnDevice

corpus_dev = _CorpusOnDevice(model, tokenized)
n_tokens = corpus_dev.n_tokens


def fetch_rows(ids):
    return np.asarray(model._emb_in[jnp.asarray(ids)])


t0 = time.perf_counter()
seps = []
key = jax.random.PRNGKey(0)
for epoch in range(EPOCHS):
    ekey = jax.random.PRNGKey(1000 + epoch)
    ekey, prep_key = jax.random.split(ekey)
    kept, ksent, n_kept_dev = corpus_dev.prep_epoch(prep_key)
    n_kept = int(n_kept_dev)
    steps = max(math.ceil(n_kept / C), 1)
    raw_per_step = n_tokens / steps
    for g0 in range(0, steps, G):
        bases = np.full(G, n_kept, np.int32)
        real = min(G, steps - g0)
        bases[:real] = (np.arange(g0, g0 + real) * C).astype(np.int32)
        lrs = np.zeros(G, np.float32)
        for i in range(real):
            lrs[i] = model.learning_rate()
            model.trained_words += raw_per_step
        (model._emb_in, model._emb_out, loss, pairs, ekey) = group(
            model._emb_in, model._emb_out, kept, ksent,
            model._neg_prob_dev, model._neg_alias_dev, ekey,
            jnp.asarray(bases), jnp.asarray(lrs), n_kept_dev)
    float(model._emb_in[0, 0])
    sep = bench.topic_separation(None, dictionary, fetch_rows=fetch_rows)
    seps.append(round(float(sep), 4))
    print(f"epoch {epoch}: sep={sep:.4f} "
          f"({time.perf_counter()-t0:.1f}s)", flush=True)
print(f"per-pair negs C={C} ep={EPOCHS}: seps={seps} "
      f"total={time.perf_counter()-t0:.1f}s", flush=True)
