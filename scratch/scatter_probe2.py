"""Slope-based microbench: T(G_hi) - T(G_lo) removes the ~100ms readback
RTT; per-step cost = slope / (G_hi - G_lo)."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

jax.config.update("jax_compilation_cache_dir", "/root/repo/.jax_cache")

R, C = 1_000_000, 128
TABLE_BYTES = R * C * 4
key = jax.random.PRNGKey(0)


def force(x):
    return float(jnp.ravel(x)[0])


def run(make_fn, args_fn, G):
    fn = make_fn(G)
    args = args_fn(G)
    out = fn(*args)  # compile; consumes donated arg
    force(out if not isinstance(out, tuple) else out[0])
    best = float("inf")
    for _ in range(4):
        args = args_fn(G)
        for a in args:
            a.block_until_ready()
        force(args[0])
        t0 = time.perf_counter()
        out = fn(*args)
        force(out if not isinstance(out, tuple) else out[0])
        best = min(best, time.perf_counter() - t0)
    return best


def slope(make_fn, args_fn, lo=8, hi=32):
    t_lo = run(make_fn, args_fn, lo)
    t_hi = run(make_fn, args_fn, hi)
    return (t_hi - t_lo) / (hi - lo)


def report(name, per_step, io_bytes):
    print(f"{name:34s} {per_step*1e3:8.3f} ms/step "
          f"{io_bytes/per_step/1e9:8.2f} GB/s(io)")


# -- scatter-add into the table, k ids per step --
for k in (1024, 32768, 131072, 491520):
    def make(G, k=k):
        @functools.partial(jax.jit, donate_argnums=0, static_argnums=3)
        def f(t, ids, delta, g):
            def body(t, i):
                return t.at[i].add(delta), 0.0
            t, _ = jax.lax.scan(body, t, ids)
            return t
        return lambda t, ids, delta: f(t, ids, delta, G)

    def args(G, k=k):
        ids = jax.random.randint(key, (G, k), 0, R, jnp.int32)
        delta = jnp.ones((k, C), jnp.float32)
        return jnp.zeros((R, C), jnp.float32), ids, delta

    s = slope(make, args)
    report(f"scatter k={k}", s, 2 * k * C * 4)

# -- scatter sorted ids --
for k in (131072, 491520):
    def make(G, k=k):
        @functools.partial(jax.jit, donate_argnums=0, static_argnums=3)
        def f(t, ids, delta, g):
            def body(t, i):
                si = jnp.sort(i)
                return t.at[si].add(delta, indices_are_sorted=True), 0.0
            t, _ = jax.lax.scan(body, t, ids)
            return t
        return lambda t, ids, delta: f(t, ids, delta, G)

    def args(G, k=k):
        ids = jax.random.randint(key, (G, k), 0, R, jnp.int32)
        delta = jnp.ones((k, C), jnp.float32)
        return jnp.zeros((R, C), jnp.float32), ids, delta

    s = slope(make, args)
    report(f"scatter sorted k={k}", s, 2 * k * C * 4)

# -- gather k rows per step --
for k in (32768, 491520):
    def make(G, k=k):
        @functools.partial(jax.jit, static_argnums=2)
        def f(t, ids, g):
            def body(acc, i):
                return acc + t[i].sum(), 0.0
            acc, _ = jax.lax.scan(body, 0.0, ids)
            return acc
        return lambda t, ids: f(t, ids, G)

    def args(G, k=k):
        ids = jax.random.randint(key, (G, k), 0, R, jnp.int32)
        return jnp.zeros((R, C), jnp.float32), ids

    s = slope(make, args)
    report(f"gather k={k}", s, k * C * 4)

# -- pure sweep per step --
def make_sweep(G):
    @functools.partial(jax.jit, donate_argnums=0, static_argnums=1)
    def f(t, g):
        def body(t, _):
            return t + 1.0, 0.0
        t, _ = jax.lax.scan(body, t, jnp.arange(g))
        return t
    return lambda t: f(t, G)


s = slope(make_sweep, lambda G: (jnp.zeros((R, C), jnp.float32),))
report("sweep t+=1", s, 2 * TABLE_BYTES)
