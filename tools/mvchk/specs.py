"""mvchk invariant specs: the concurrency core under controlled
interleavings.

Two families:

* **Real-primitive specs** (``uses_model=True``) — the actual
  ``MtQueue`` / ``Waiter`` implementations run unmodified on model
  locks/conditions via the ``lock_witness.install_thread_model`` hook:
  FIFO, no lost wakeup on push/exit, ``pop_batch`` byte-cap and
  exit-drain semantics, timeout expiry through the virtual clock,
  ``_VectorClock`` monotonicity (strict BSP and backup-worker cutoff).
* **Protocol models** — hand-built replicas of runtime protocols too
  entangled with sockets to lift whole: the event-loop wake latch +
  self-pipe (``runtime/tcp.py _EventLoop``) in its current
  re-arm-first ordering AND the pre-PR-19 check-then-re-arm ordering.
  The latter is the known-bad fixture: ``expect_fail=True`` makes the
  explorer's job *refutation* — CI fails if mvchk ever stops finding
  the lost-wakeup deadlock (the analyzer self-check, mvlint-fixture
  style).

Every spec terminates in every legal schedule; a deadlock IS the bug.
"""

from __future__ import annotations

from typing import List, Optional

from .core import (MCondition, MLock, SchedPipe, SchedVar, Scheduler,
                   Spec)

# Imported at module scope ON PURPOSE: any module-level primitive
# construction in the transitive imports must happen while NO thread
# model is installed, or model locks would leak into real runtime
# state that outlives the run.
from multiverso_tpu.runtime.server import _VectorClock
from multiverso_tpu.util.mt_queue import MtQueue
from multiverso_tpu.util.waiter import Waiter


# ---------------------------------------------------------------------
# MtQueue under the model (the real class, model primitives)
# ---------------------------------------------------------------------

def _mtqueue_fifo(sched: Scheduler):
    q: MtQueue = MtQueue("chk.fifo")
    got: List[int] = []

    def producer_a():
        q.push(1)
        q.push(2)

    def producer_b():
        q.push(10)
        q.push(11)

    def consumer():
        for _ in range(4):
            item = q.pop()
            assert item is not None, "pop returned None before exit"
            got.append(item)

    sched.spawn("producer-a", producer_a)
    sched.spawn("producer-b", producer_b)
    sched.spawn("consumer", consumer)

    def check():
        assert sorted(got) == [1, 2, 10, 11], got
        assert got.index(1) < got.index(2), f"per-producer order: {got}"
        assert got.index(10) < got.index(11), \
            f"per-producer order: {got}"
    return check


def _mtqueue_pop_timeout(sched: Scheduler):
    q: MtQueue = MtQueue("chk.timeout")
    out: List[object] = []

    def consumer():
        out.append(q.pop(timeout=1.0))
        out.append(q.pop_batch(timeout=1.0))

    sched.spawn("consumer", consumer)

    def check():
        assert out == [None, []], \
            f"timed pop on an empty queue must expire empty: {out}"
    return check


def _mtqueue_pop_batch_cap(sched: Scheduler):
    q: MtQueue = MtQueue("chk.batchcap")
    pushed = [10, 60, 50, 5]
    state: dict = {}

    def producer():
        for v in pushed:
            q.push(v)

    def consumer():
        batch = q.pop_batch(max_items=8, max_bytes=100,
                            size_of=lambda v: v)
        state["batch"] = batch

    sched.spawn("producer", producer)
    sched.spawn("consumer", consumer)

    def check():
        batch = state["batch"]
        assert batch, "block-for-first must return at least one item"
        assert batch == pushed[:len(batch)], \
            f"batch must be a FIFO prefix of the pushes: {batch}"
        if len(batch) > 1:
            assert sum(batch[1:]) <= 100 - batch[0], \
                f"byte cap must bound the batch tail: {batch}"
    return check


def _mtqueue_exit_drain(sched: Scheduler):
    """exit() must never hide an item already queued: the first drain
    after exit returns the item, the next returns []."""
    q: MtQueue = MtQueue("chk.exitdrain")
    state: dict = {}

    def producer():
        q.push("a")
        q.exit()

    def consumer():
        state["b1"] = q.pop_batch()
        state["b2"] = q.pop_batch()

    sched.spawn("producer", producer)
    sched.spawn("consumer", consumer)

    def check():
        assert state["b1"] == ["a"], \
            f"exit hid a queued item: {state}"
        assert state["b2"] == [], f"post-drain must be []: {state}"
    return check


def _mtqueue_exit_wakes(sched: Scheduler):
    """stop() racing block-for-first: exit with nothing queued must
    wake the blocked pop_batch (a lost exit-notify is a deadlock the
    scheduler detects)."""
    q: MtQueue = MtQueue("chk.exitwake")
    state: dict = {}

    def consumer():
        state["batch"] = q.pop_batch()

    def stopper():
        q.exit()

    sched.spawn("consumer", consumer)
    sched.spawn("stopper", stopper)

    def check():
        assert state["batch"] == [], state
    return check


# ---------------------------------------------------------------------
# Waiter under the model
# ---------------------------------------------------------------------

def _waiter_countdown(sched: Scheduler):
    w = Waiter(2, name="chk.countdown")
    state: dict = {}

    def notifier():
        w.notify()

    def waiter_task():
        state["ok"] = w.wait()

    sched.spawn("notifier-1", notifier)
    sched.spawn("notifier-2", notifier)
    sched.spawn("waiter", waiter_task)

    def check():
        assert state["ok"] is True, "waiter missed a notify"
    return check


def _waiter_add_waits_race(sched: Scheduler):
    """The replica-repair extension racing completion: whatever the
    order, the waiter must complete (a completed waiter drops the
    extension; an outstanding one absorbs it)."""
    w = Waiter(1, name="chk.addwaits")
    state: dict = {}

    def completer():
        w.notify()

    def repairer():
        w.add_waits(1)
        w.notify()

    def waiter_task():
        state["ok"] = w.wait()

    sched.spawn("completer", completer)
    sched.spawn("repairer", repairer)
    sched.spawn("waiter", waiter_task)

    def check():
        assert state["ok"] is True, "add_waits stranded the waiter"
    return check


def _waiter_release_race(sched: Scheduler):
    w = Waiter(2, name="chk.release")
    state: dict = {}

    def notifier():
        w.notify()

    def aborter():
        w.release()

    def waiter_task():
        state["ok"] = w.wait()

    sched.spawn("notifier", notifier)
    sched.spawn("aborter", aborter)
    sched.spawn("waiter", waiter_task)

    def check():
        assert state["ok"] is True, "release must force-complete"
    return check


# ---------------------------------------------------------------------
# _VectorClock (actor-confined: ops serialized under a model lock)
# ---------------------------------------------------------------------

def _vector_clock(sched: Scheduler, n: int, num_backup: int,
                  ticks: int, expect_final: float):
    clock = _VectorClock(n, num_backup)
    lock = MLock(sched, "clock")
    observed: List[float] = []
    trues: List[float] = []

    def worker(i: int):
        def body():
            for _ in range(ticks):
                with lock:
                    level = clock.update(i)
                    observed.append(clock.global_clock)
                    if level:
                        trues.append(clock.global_clock)
            with lock:
                clock.finish_train(i)
                observed.append(clock.global_clock)
        return body

    for i in range(n):
        sched.spawn(f"worker-{i}", worker(i))

    def check():
        for a, b in zip(observed, observed[1:]):
            assert a <= b, f"global clock regressed: {observed}"
        finite = [v for v in observed if v != float("inf")]
        assert finite and max(finite) == expect_final, \
            f"global must reach {expect_final}: {observed}"
        assert trues, "no update ever reported the workers level"
    return check


def _vector_clock_strict(sched: Scheduler):
    return _vector_clock(sched, n=2, num_backup=0, ticks=2,
                         expect_final=2.0)


def _vector_clock_backup(sched: Scheduler):
    return _vector_clock(sched, n=3, num_backup=1, ticks=1,
                         expect_final=1.0)


# ---------------------------------------------------------------------
# dispatch backpressure (bounded submit, the tcp peer-queue shape)
# ---------------------------------------------------------------------

def _dispatch_backpressure(sched: Scheduler):
    lock = MLock(sched, "bp")
    cond = MCondition(sched, "bp.cond", lock)
    state = {"q": [], "used": 0, "drained": []}
    cap, total = 2, 4

    def producer():
        for i in range(total):
            with cond:
                while state["used"] >= cap:
                    cond.wait()
                state["q"].append(i)
                state["used"] += 1
                cond.notify_all()

    def drainer():
        while len(state["drained"]) < total:
            with cond:
                while not state["q"]:
                    cond.wait()
                state["drained"].append(state["q"].pop(0))
                state["used"] -= 1
                cond.notify_all()

    sched.spawn("producer", producer)
    sched.spawn("drainer", drainer)

    def check():
        assert state["drained"] == list(range(total)), state
        assert state["used"] == 0, state
    return check


# ---------------------------------------------------------------------
# the event-loop wake latch + self-pipe (runtime/tcp.py _EventLoop)
# ---------------------------------------------------------------------

def _event_loop(sched: Scheduler, pre_pr19: bool):
    """The latch/pipe/stop protocol of ``_EventLoop``:

    * ``wake()`` is the real gate: test latch, set latch, write byte.
    * the loop models ``_main``: re-arm, stop-check, ``select``,
      drain — with ``pre_pr19=True`` the re-arm happens AFTER the
      stop-check (the shipped bug's ordering), which deadlocks when a
      ``stop()`` lands in the drain-to-re-arm window and its ``wake``
      sees the stale latch.
    * the stopper models a ``call_soon`` nudge then ``stop()``
      (stop = set stopped, wake) — exactly tcp.py's sequence.
    """
    woken = SchedVar(sched, "woken", False)
    stopped = SchedVar(sched, "stopped", False)
    pipe = SchedPipe(sched, "wakepipe")
    iters = {"n": 0}

    def wake():
        if woken.read():
            return
        woken.write(True)
        pipe.write_byte()

    def loop():
        while True:
            iters["n"] += 1
            assert iters["n"] <= 10, "event loop livelocked"
            if pre_pr19:
                if stopped.read():
                    return
                woken.write(False)   # re-arm AFTER the state check
            else:
                woken.write(False)   # re-arm FIRST (tcp.py:489)
                if stopped.read():
                    return
            pipe.select()
            pipe.drain()

    def stopper():
        wake()                       # the call_soon work nudge
        stopped.write(True)          # stop(): flag, then wake
        wake()

    sched.spawn("loop", loop)
    sched.spawn("stopper", stopper)
    return None


def _event_loop_good(sched: Scheduler):
    return _event_loop(sched, pre_pr19=False)


def _event_loop_pre_pr19(sched: Scheduler):
    return _event_loop(sched, pre_pr19=True)


# ---------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------

ALL_SPECS: List[Spec] = [
    Spec("mtqueue-fifo",
         "two producers, one consumer: FIFO per producer, no lost "
         "push wakeup", _mtqueue_fifo, uses_model=True),
    Spec("mtqueue-pop-timeout",
         "timed pop/pop_batch on an empty queue expires via the "
         "virtual clock", _mtqueue_pop_timeout, uses_model=True),
    Spec("mtqueue-pop-batch-cap",
         "producer races the greedy drain at the byte cap: batch is "
         "a FIFO prefix, tail bounded", _mtqueue_pop_batch_cap,
         uses_model=True),
    Spec("mtqueue-exit-drain",
         "exit() racing a drain never hides a queued item",
         _mtqueue_exit_drain, uses_model=True),
    Spec("mtqueue-exit-wakes",
         "exit() racing block-for-first always wakes the blocked "
         "pop_batch", _mtqueue_exit_wakes, uses_model=True),
    Spec("waiter-countdown",
         "countdown latch: N notifies release the waiter in every "
         "order", _waiter_countdown, uses_model=True),
    Spec("waiter-add-waits-race",
         "add_waits racing completion never strands the waiter",
         _waiter_add_waits_race, uses_model=True),
    Spec("waiter-release-race",
         "release() force-completes against a concurrent notify",
         _waiter_release_race, uses_model=True),
    Spec("vector-clock-strict",
         "_VectorClock strict BSP: global clock monotone, levels at "
         "the common tick", _vector_clock_strict, uses_model=True),
    Spec("vector-clock-backup",
         "_VectorClock backup-worker cutoff: stragglers do not gate, "
         "clock stays monotone", _vector_clock_backup,
         uses_model=True),
    Spec("dispatch-backpressure",
         "bounded submit against a drainer: FIFO, full drain, no "
         "lost capacity wakeup", _dispatch_backpressure,
         uses_model=True),
    Spec("event-loop-wake",
         "current _EventLoop ordering (re-arm before checks): no "
         "lost wakeup in any bounded schedule", _event_loop_good),
    Spec("event-loop-pre-pr19",
         "KNOWN-BAD: the pre-PR-19 check-then-re-arm ordering — the "
         "explorer must refute it with a lost-wakeup deadlock",
         _event_loop_pre_pr19, expect_fail=True),
]

SPECS_BY_NAME = {spec.name: spec for spec in ALL_SPECS}
