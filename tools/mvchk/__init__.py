"""mvchk: deterministic-schedule model checking for the concurrency
core (the dynamic half of the PR-20 gate; mvlint is the static half).

``python -m tools.mvchk`` runs every spec through systematic
bounded-preemption exploration: real ``MtQueue``/``Waiter`` instances
on model locks (via ``lock_witness.install_thread_model``), plus
hand-built models of the event-loop wake protocol and dispatch
backpressure. The pre-PR-19 wake-drain ordering ships as a known-bad
spec the explorer must REFUTE — CI fails if the counterexample stops
reproducing, the same self-check discipline as the mvlint fixtures.

``--random N --seed S`` adds seeded-random long runs (the slow-CI
soak). ``--spec NAME`` selects one spec; ``--list`` enumerates them.
Docs: docs/STATIC_ANALYSIS.md ("The dynamic half: mvchk").
"""

from __future__ import annotations

from .core import (Deadlock, ExploreResult, MaxStepsExceeded,
                   ModelFacade, RunOutcome, Scheduler, Spec, explore,
                   format_trace, run_once, soak)
from .specs import ALL_SPECS, SPECS_BY_NAME
