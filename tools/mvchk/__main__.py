"""CLI: ``python -m tools.mvchk [--spec NAME] [--random N] [--seed S]``.

Exit status: 0 — every spec met its expectation (normal specs pass
all explored schedules, ``expect_fail`` specs are refuted with a
counterexample); 1 — a normal spec failed OR a known-bad spec was NOT
refuted (the self-check: a checker that blesses the pre-PR-19
ordering is broken and must fail CI); 2 — usage errors.
"""

from __future__ import annotations

import argparse
import sys

from .core import explore, format_trace, soak
from .specs import ALL_SPECS, SPECS_BY_NAME


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.mvchk",
        description="deterministic-schedule model checker for the "
                    "multiverso_tpu concurrency core")
    parser.add_argument("--spec", action="append", default=None,
                        help="run only this spec (repeatable)")
    parser.add_argument("--list", action="store_true",
                        help="list specs and exit")
    parser.add_argument("--random", type=int, default=0, metavar="N",
                        help="additionally run N seeded-random "
                             "schedules per spec")
    parser.add_argument("--seed", type=int, default=20260807,
                        help="base seed for --random")
    parser.add_argument("--max-schedules", type=int, default=600,
                        help="systematic exploration budget per spec")
    parser.add_argument("--preemption-bound", type=int, default=3)
    parser.add_argument("--trace", action="store_true",
                        help="print the full counterexample trace "
                             "even for expected refutations")
    args = parser.parse_args(argv)

    if args.list:
        for spec in ALL_SPECS:
            tag = "  [known-bad]" if spec.expect_fail else ""
            print(f"{spec.name:<24} {spec.describe}{tag}")
        return 0

    if args.spec:
        unknown = [n for n in args.spec if n not in SPECS_BY_NAME]
        if unknown:
            print(f"mvchk: unknown spec(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
        specs = [SPECS_BY_NAME[n] for n in args.spec]
    else:
        specs = ALL_SPECS

    failures = 0
    for spec in specs:
        result = explore(spec, preemption_bound=args.preemption_bound,
                         max_schedules=args.max_schedules)
        verdict = None
        if spec.expect_fail:
            if result.refuted:
                verdict = (f"refuted as required "
                           f"({result.schedules} schedules)")
            else:
                verdict = (f"NOT refuted in {result.schedules} "
                           f"schedules — the checker lost the "
                           f"known-bad counterexample")
                failures += 1
        else:
            if result.refuted:
                verdict = (f"FAILED at schedule {result.schedules}")
                failures += 1
            else:
                verdict = f"ok ({result.schedules} schedules)"
            if not result.refuted and args.random > 0:
                s = soak(spec, runs=args.random, seed=args.seed)
                if s.refuted:
                    verdict = (f"FAILED on random run "
                               f"{s.schedules} (seed base "
                               f"{args.seed})")
                    result = s
                    failures += 1
                else:
                    verdict += f" + {args.random} random runs"
        print(f"mvchk: {spec.name:<24} {verdict}")
        if result.counterexample is not None and (
                args.trace or not spec.expect_fail or
                (spec.expect_fail and not result.refuted)):
            print(format_trace(result.counterexample))
        elif result.counterexample is not None and spec.expect_fail:
            # Always show the refutation's last steps: the readable
            # interleaving is the point of the self-check.
            print(format_trace(result.counterexample, limit=24))
    if failures:
        print(f"mvchk: FAILED ({failures} spec(s))")
        return 1
    print(f"mvchk: OK ({len(specs)} specs)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
