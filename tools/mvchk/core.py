"""mvchk core: a deterministic cooperative scheduler for model-checking
the runtime's concurrency primitives.

The design is the classic baton scheduler: every logical thread of a
spec runs on a real OS thread, but exactly ONE is ever runnable — each
shared-memory operation funnels through :meth:`Scheduler.yield_point`,
which parks the task on a per-task event and hands the baton back to
the scheduler, so the scheduler alone decides the global interleaving.
A program under test is therefore a *deterministic function of the
schedule* (the choice sequence), which is what makes systematic replay,
bounded-preemption enumeration, and counterexample reproduction
possible at all.

Blocking is a predicate, not a park: a blocked task publishes
``pred()`` and the scheduler re-evaluates it each step (nothing else
runs concurrently, so evaluation is race-free). Deadlock is then a
*decided* property — no task runnable, none timed, some unfinished —
and the trace up to that point IS the counterexample. Timeouts use
virtual time: a timed wait expires only when nothing else is runnable
(the scheduler advances ``vtime`` and delivers ``timed_out``), which
matches the primitives' deadline loops without patching ``time``.

:class:`ModelFacade` adapts the scheduler to the
``lock_witness.install_thread_model`` hook, so the REAL ``MtQueue``
and ``Waiter`` run unmodified on model locks/conditions with the
virtual clock.
"""

from __future__ import annotations

import dataclasses
import random
import threading
from typing import (Callable, Dict, List, Optional, Sequence, Tuple)

DEFAULT_MAX_STEPS = 4000


class Deadlock(Exception):
    """No runnable task, no timed wait, unfinished tasks remain."""

    def __init__(self, blocked: List[Tuple[str, str]]):
        self.blocked = blocked
        detail = "; ".join(f"{name} blocked at {label}"
                           for name, label in blocked)
        super().__init__(f"deadlock: {detail}")


class MaxStepsExceeded(Exception):
    pass


class _Killed(BaseException):
    """Unwinds a task thread when a run is torn down early (deadlock,
    failed invariant); BaseException so spec code cannot catch it."""


_current = threading.local()


class _Task:
    __slots__ = ("tid", "name", "fn", "thread", "go", "done", "exc",
                 "label", "pred", "timeout_ok", "timed_out", "killed")

    def __init__(self, tid: int, name: str, fn: Callable[[], None]):
        self.tid = tid
        self.name = name
        self.fn = fn
        self.thread: Optional[threading.Thread] = None
        self.go = threading.Event()
        self.done = False
        self.exc: Optional[BaseException] = None
        self.label = "start"
        self.pred: Optional[Callable[[], bool]] = None
        self.timeout_ok = False
        self.timed_out = False
        self.killed = False


@dataclasses.dataclass
class Choice:
    """One scheduling decision (the explorer branches on these)."""
    runnable: Tuple[int, ...]
    chosen: int
    prev: Optional[int]
    preempt: bool     # prev was still runnable but a different task ran


class Scheduler:
    """One deterministic run. ``choose(step, runnable_tids, prev_tid)``
    picks the next task id each step."""

    def __init__(self, choose: Callable[[int, Sequence[int],
                                         Optional[int]], int],
                 max_steps: int = DEFAULT_MAX_STEPS):
        self._choose = choose
        self.max_steps = max_steps
        self.tasks: List[_Task] = []
        self.vtime = 0.0
        self.trace: List[Tuple[str, str]] = []
        self.choices: List[Choice] = []
        self._resume = threading.Event()

    # -- spec-facing -------------------------------------------------
    def spawn(self, name: str, fn: Callable[[], None]) -> None:
        self.tasks.append(_Task(len(self.tasks), name, fn))

    def yield_point(self, label: str,
                    pred: Optional[Callable[[], bool]] = None,
                    timeout_ok: bool = False) -> bool:
        """Hand the baton back; resume when scheduled. Returns True
        iff the wait expired via virtual time instead of ``pred``."""
        task: _Task = _current.task
        task.label = label
        task.pred = pred
        task.timeout_ok = bool(timeout_ok and pred is not None)
        task.timed_out = False
        task.go.clear()
        self._resume.set()
        task.go.wait()
        if task.killed:
            raise _Killed()
        return task.timed_out

    def wait_until(self, label: str, pred: Callable[[], bool],
                   timeout_ok: bool = False) -> bool:
        """Block until ``pred`` holds (or virtual-time expiry when
        ``timeout_ok``). Returns True iff it timed out."""
        return self.yield_point(label, pred=pred, timeout_ok=timeout_ok)

    def current_task(self) -> _Task:
        return _current.task

    # -- the run loop ------------------------------------------------
    def _task_main(self, task: _Task) -> None:
        _current.task = task
        task.go.wait()
        try:
            if not task.killed:
                task.fn()
        except _Killed:
            pass
        except BaseException as exc:  # invariant failures included
            task.exc = exc
        finally:
            task.done = True
            self._resume.set()

    def run(self) -> None:
        for task in self.tasks:
            task.thread = threading.Thread(
                target=self._task_main, args=(task,),
                name=f"mvchk-{task.name}", daemon=True)
            task.thread.start()
        prev: Optional[int] = None
        step = 0
        while True:
            unfinished = [t for t in self.tasks if not t.done]
            failed = [t for t in self.tasks if t.exc is not None]
            if failed:
                raise failed[0].exc
            if not unfinished:
                return
            runnable = [t for t in unfinished
                        if t.pred is None or t.pred()]
            timed_out = False
            if not runnable:
                timed = [t for t in unfinished if t.timeout_ok]
                if not timed:
                    raise Deadlock([(t.name, t.label)
                                    for t in unfinished])
                self.vtime += 1.0
                runnable, timed_out = timed, True
            tids = tuple(t.tid for t in runnable)
            chosen_tid = self._choose(step, tids, prev)
            if chosen_tid not in tids:
                chosen_tid = tids[0]
            chosen = self.tasks[chosen_tid]
            self.choices.append(Choice(
                tids, chosen_tid, prev,
                preempt=(prev is not None and prev in tids
                         and chosen_tid != prev
                         and not self.tasks[prev].done)))
            self.trace.append((chosen.name, chosen.label))
            step += 1
            if step > self.max_steps:
                raise MaxStepsExceeded(
                    f"{step} scheduling steps (possible livelock)")
            chosen.timed_out = timed_out
            chosen.pred = None
            chosen.timeout_ok = False
            self._resume.clear()
            chosen.go.set()
            self._resume.wait()
            prev = chosen_tid

    def shutdown(self) -> None:
        """Tear down parked task threads after an aborted run."""
        for task in self.tasks:
            if not task.done:
                task.killed = True
                task.go.set()
        for task in self.tasks:
            if task.thread is not None:
                task.thread.join(timeout=5.0)


# ---------------------------------------------------------------------
# model primitives (threading-compatible surface)
# ---------------------------------------------------------------------

class MLock:
    """Model lock: reentrant-capable, one schedule point per op."""

    def __init__(self, sched: Scheduler, name: str):
        self._sched = sched
        self._name = name
        self._holder: Optional[_Task] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        me = self._sched.current_task()
        if self._holder is me:
            self._count += 1
            return True
        timeout_ok = timeout is not None and timeout >= 0
        expired = self._sched.wait_until(
            f"acquire({self._name})",
            lambda: self._holder is None, timeout_ok=timeout_ok)
        if expired:
            return False
        self._holder = me
        self._count = 1
        return True

    def release(self) -> None:
        me = self._sched.current_task()
        if self._holder is not me:
            raise RuntimeError(f"release of unheld lock {self._name}")
        self._sched.yield_point(f"release({self._name})")
        self._count -= 1
        if self._count == 0:
            self._holder = None

    def locked(self) -> bool:
        return self._holder is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class MCondition:
    """Model condition over an :class:`MLock`. No spurious wakeups:
    a waiter returns exactly when notified or virtually timed out —
    lost-wakeup bugs surface as deadlocks, not flaky sleeps."""

    def __init__(self, sched: Scheduler, name: str, lock: MLock):
        self._sched = sched
        self._name = name
        self._lock = lock
        self._waiters: List[List[bool]] = []

    # lock surface (``with cond:`` parity with threading.Condition)
    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched = self._sched
        me = sched.current_task()
        if self._lock._holder is not me:
            raise RuntimeError(f"wait on {self._name} without lock")
        token = [False]
        self._waiters.append(token)
        # Release-and-enqueue is atomic (no schedule point), like the
        # real Condition; the reacquire below is a contended point.
        held, self._lock._count = self._lock._count, 0
        self._lock._holder = None
        expired = sched.yield_point(
            f"wait({self._name})", pred=lambda: token[0],
            timeout_ok=timeout is not None)
        if expired and token in self._waiters:
            self._waiters.remove(token)
        sched.wait_until(f"reacquire({self._name})",
                         lambda: self._lock._holder is None)
        self._lock._holder = me
        self._lock._count = held
        return token[0]

    def wait_for(self, predicate, timeout: Optional[float] = None):
        result = predicate()
        while not result:
            if not self.wait(timeout):
                return predicate()
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._sched.yield_point(f"notify({self._name})")
        for _ in range(min(n, len(self._waiters))):
            self._waiters.pop(0)[0] = True

    def notify_all(self) -> None:
        self._sched.yield_point(f"notify_all({self._name})")
        while self._waiters:
            self._waiters.pop(0)[0] = True


class ModelFacade:
    """The object handed to ``lock_witness.install_thread_model``."""

    def __init__(self, sched: Scheduler):
        self._sched = sched

    def lock(self, name: str) -> MLock:
        return MLock(self._sched, name)

    def rlock(self, name: str) -> MLock:
        return MLock(self._sched, name)

    def condition(self, name: str, lock=None) -> MCondition:
        if lock is None:
            lock = MLock(self._sched, f"{name}.mutex")
        return MCondition(self._sched, name, lock)

    def monotonic(self) -> float:
        return self._sched.vtime


# ---------------------------------------------------------------------
# shared-state helpers for hand-built protocol models (specs.py)
# ---------------------------------------------------------------------

class SchedVar:
    """A shared scalar where every read/write is a schedule point —
    the granularity at which real threads race on an attribute."""

    def __init__(self, sched: Scheduler, name: str, value):
        self._sched = sched
        self._name = name
        self.value = value

    def read(self):
        self._sched.yield_point(f"read {self._name}")
        return self.value

    def write(self, value) -> None:
        self._sched.yield_point(f"{self._name} := {value!r}")
        self.value = value


class SchedPipe:
    """The self-pipe: byte-counting, with a parking ``select``."""

    def __init__(self, sched: Scheduler, name: str = "pipe"):
        self._sched = sched
        self._name = name
        self.bytes = 0

    def write_byte(self) -> None:
        self._sched.yield_point(f"write byte -> {self._name}")
        self.bytes += 1

    def select(self) -> None:
        self._sched.wait_until(f"select({self._name})",
                               lambda: self.bytes > 0)

    def drain(self) -> None:
        self._sched.yield_point(f"drain {self._name}")
        self.bytes = 0


# ---------------------------------------------------------------------
# running specs: single runs, systematic exploration, random soak
# ---------------------------------------------------------------------

@dataclasses.dataclass
class Spec:
    """One model-checking scenario. ``setup(sched)`` spawns the tasks
    and returns an optional end-of-run invariant check. When
    ``uses_model`` is set, the run installs a :class:`ModelFacade`
    into ``lock_witness`` around setup+run so real primitives build
    model locks. ``expect_fail`` marks known-bad models the explorer
    must REFUTE (the CI self-check)."""
    name: str
    describe: str
    setup: Callable[[Scheduler], Optional[Callable[[], None]]]
    uses_model: bool = False
    expect_fail: bool = False


@dataclasses.dataclass
class RunOutcome:
    ok: bool
    error: Optional[BaseException]
    trace: List[Tuple[str, str]]
    schedule: List[int]
    choices: List[Choice]


def run_once(spec: Spec, prefix: Sequence[int] = (),
             seed: Optional[int] = None,
             max_steps: int = DEFAULT_MAX_STEPS) -> RunOutcome:
    """One deterministic run: replay ``prefix``, then continue with
    the default strategy (stay on the current task, else lowest tid)
    or — when ``seed`` is given — uniform random choices."""
    rng = random.Random(seed) if seed is not None else None

    def choose(step: int, runnable: Sequence[int],
               prev: Optional[int]) -> int:
        if step < len(prefix) and prefix[step] in runnable:
            return prefix[step]
        if step >= len(prefix) and rng is not None:
            return rng.choice(list(runnable))
        if prev is not None and prev in runnable:
            return prev
        return runnable[0]

    sched = Scheduler(choose, max_steps=max_steps)
    installed = False
    error: Optional[BaseException] = None
    check: Optional[Callable[[], None]] = None
    try:
        if spec.uses_model:
            from multiverso_tpu.util import lock_witness
            lock_witness.install_thread_model(ModelFacade(sched))
            installed = True
        check = spec.setup(sched)
        sched.run()
        if check is not None:
            check()
    except (Deadlock, MaxStepsExceeded, AssertionError) as exc:
        error = exc
    except _Killed:  # pragma: no cover - never escapes tasks
        raise
    except Exception as exc:
        error = exc
    finally:
        sched.shutdown()
        if installed:
            from multiverso_tpu.util import lock_witness
            lock_witness.clear_thread_model()
    return RunOutcome(ok=error is None, error=error,
                      trace=list(sched.trace),
                      schedule=[c.chosen for c in sched.choices],
                      choices=list(sched.choices))


@dataclasses.dataclass
class ExploreResult:
    refuted: bool
    counterexample: Optional[RunOutcome]
    schedules: int


def explore(spec: Spec, preemption_bound: int = 3,
            max_schedules: int = 400,
            max_steps: int = DEFAULT_MAX_STEPS) -> ExploreResult:
    """Iterative-context-bounding exploration: depth-first over
    schedule prefixes, branching to every runnable alternative at
    every step, pruned by the number of *preemptions* (switching away
    from a still-runnable task) a prefix spends. Bound 2-3 covers the
    classic lost-wakeup/TOCTOU interleavings at a tiny fraction of
    the full factorial space."""
    stack: List[Tuple[int, ...]] = [()]
    explored = 0
    while stack and explored < max_schedules:
        prefix = stack.pop()
        out = run_once(spec, prefix=prefix, max_steps=max_steps)
        explored += 1
        if not out.ok:
            return ExploreResult(True, out, explored)
        preempts = 0
        for i, choice in enumerate(out.choices):
            if i >= len(prefix):
                for alt in choice.runnable:
                    if alt == choice.chosen:
                        continue
                    cost = preempts + (
                        1 if choice.prev is not None
                        and choice.prev in choice.runnable
                        and alt != choice.prev else 0)
                    if cost <= preemption_bound:
                        stack.append(tuple(out.schedule[:i]) + (alt,))
            if choice.preempt:
                preempts += 1
    return ExploreResult(False, None, explored)


def soak(spec: Spec, runs: int, seed: int,
         max_steps: int = DEFAULT_MAX_STEPS) -> ExploreResult:
    """Seeded-random long runs: same determinism guarantee (a failing
    seed replays exactly), wider reach than the bounded frontier."""
    for i in range(runs):
        out = run_once(spec, seed=seed + i, max_steps=max_steps)
        if not out.ok:
            return ExploreResult(True, out, i + 1)
    return ExploreResult(False, None, runs)


def format_trace(out: RunOutcome, limit: int = 80) -> str:
    lines = []
    tail = out.trace[-limit:]
    if len(out.trace) > limit:
        lines.append(f"  ... {len(out.trace) - limit} earlier steps")
    for i, (name, label) in enumerate(tail,
                                      len(out.trace) - len(tail) + 1):
        lines.append(f"  step {i:3d}  {name:<14} {label}")
    if out.error is not None:
        lines.append(f"  => {type(out.error).__name__}: {out.error}")
    return "\n".join(lines)
