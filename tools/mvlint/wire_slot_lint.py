"""wire-slot lint: reserved header slots are named, registered, and
documented.

Rules, everywhere except ``core/message.py`` (the registry itself):

* ``<expr>.header[...]`` may only be indexed by a NAME that appears in
  the ``WIRE_SLOTS`` registry (``ERROR_SLOT``/``CODEC_SLOT``/
  ``VERSION_SLOT``). A raw integer index — the PR-3 wire-break class —
  or any computed index is a violation: slots 0-4 go through the
  property accessors, 5-7 through their registered names.
* The slot table in ``docs/WIRE_FORMAT.md`` is cross-checked against
  the registry: every registered slot must appear in the doc's
  ``| <n> | `NAME` |`` table with the same number, and vice versa, so
  the doc cannot silently drift from the wire.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional

from .framework import LintPass, ModuleInfo, Violation

DOC_ROW_RE = re.compile(r"^\|\s*(\d+)\s*\|\s*`([A-Z_]+)`\s*\|")

#: A message-type registry row is | `Type_Name` | <int> | — name first
#: (the slot table is number-first, so the two cannot cross-match).
DOC_MSG_ROW_RE = re.compile(
    r"^\|\s*`([A-Za-z][A-Za-z0-9_]*)`\s*\|\s*(-?\d+)\s*\|")


def load_msg_types(message_path: Path) -> Dict[str, int]:
    """The MsgType enum values, by AST parse of core/message.py (the
    lint parses, it never imports)."""
    tree = ast.parse(message_path.read_text(encoding="utf-8"))
    out: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "MsgType":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) \
                        and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    try:
                        out[stmt.targets[0].id] = int(
                            ast.literal_eval(stmt.value))
                    except (ValueError, TypeError):
                        pass
    if not out:
        raise RuntimeError(f"no MsgType enum in {message_path}")
    return out


def parse_doc_msg_types(doc_path: Path) -> Dict[str, int]:
    """``| `Request_Get` | 1 |`` rows from the doc's message-type
    registry table."""
    out: Dict[str, int] = {}
    if not doc_path.exists():
        return out
    for line in doc_path.read_text(encoding="utf-8").splitlines():
        m = DOC_MSG_ROW_RE.match(line.strip())
        if m:
            out[m.group(1)] = int(m.group(2))
    return out


def load_wire_slots(message_path: Path) -> Dict[str, int]:
    """The WIRE_SLOTS literal, by AST parse of core/message.py."""
    tree = ast.parse(message_path.read_text(encoding="utf-8"))
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for target in targets:
            if isinstance(target, ast.Name) and target.id == "WIRE_SLOTS":
                value = ast.literal_eval(node.value)
                if isinstance(value, dict):
                    return value
    raise RuntimeError(f"no WIRE_SLOTS dict literal in {message_path}")


def parse_doc_slots(doc_path: Path) -> Dict[str, int]:
    """``| 5 | `ERROR_SLOT` |`` rows from the doc's slot-registry table."""
    slots: Dict[str, int] = {}
    if not doc_path.exists():
        return slots
    for line in doc_path.read_text(encoding="utf-8").splitlines():
        m = DOC_ROW_RE.match(line.strip())
        if m and m.group(2).endswith("_SLOT"):
            slots[m.group(2)] = int(m.group(1))
    return slots


class WireSlotLint(LintPass):
    name = "wire-slot"

    def __init__(self, slots: Dict[str, int], doc_path: Path,
                 doc_rel: str = "docs/WIRE_FORMAT.md",
                 msg_types: Optional[Dict[str, int]] = None):
        self.slots = slots
        self.doc_path = doc_path
        self.doc_rel = doc_rel
        #: MsgType enum values (None = skip the msg-type doc check —
        #: unit tests exercising only the slot half pass None).
        self.msg_types = msg_types
        self._doc_checked = False

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        if not self._doc_checked:
            self._doc_checked = True
            yield from self._check_doc()
        if module.path.name == "message.py" \
                and "core" in module.path.parts:
            return  # the registry / accessor layer itself
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Subscript):
                continue
            base = node.value
            if not (isinstance(base, ast.Attribute)
                    and base.attr == "header"):
                continue
            index = node.slice
            if isinstance(index, ast.Name):
                if index.id in self.slots:
                    continue
                yield Violation(
                    module.rel, node.lineno, node.col_offset, self.name,
                    f"header indexed by {index.id!r}, which is not a "
                    f"registered wire slot (core/message.py WIRE_SLOTS: "
                    f"{', '.join(sorted(self.slots))})")
            elif isinstance(index, ast.Constant):
                yield Violation(
                    module.rel, node.lineno, node.col_offset, self.name,
                    f"raw header[{index.value!r}] indexing outside "
                    f"core/message.py — use the src/dst/type/table_id/"
                    f"msg_id accessors or a registered WIRE_SLOTS name")
            else:
                yield Violation(
                    module.rel, node.lineno, node.col_offset, self.name,
                    "computed header index outside core/message.py — "
                    "wire slots must be lexically auditable names")

    def _check_doc(self) -> Iterator[Violation]:
        doc = parse_doc_slots(self.doc_path)
        if not self.doc_path.exists():
            yield Violation(
                self.doc_rel, 1, 0, self.name,
                "wire-format doc missing: the slot registry must be "
                "documented (| <slot> | `NAME` | table)")
            return
        for name, slot in sorted(self.slots.items()):
            if name not in doc:
                yield Violation(
                    self.doc_rel, 1, 0, self.name,
                    f"registered slot {name}={slot} missing from the "
                    f"doc's slot-registry table (| {slot} | `{name}` |)")
            elif doc[name] != slot:
                yield Violation(
                    self.doc_rel, 1, 0, self.name,
                    f"doc says {name} is slot {doc[name]} but "
                    f"core/message.py WIRE_SLOTS says {slot} — the doc "
                    f"drifted from the wire")
        for name, slot in sorted(doc.items()):
            if name not in self.slots:
                yield Violation(
                    self.doc_rel, 1, 0, self.name,
                    f"doc documents slot {name}={slot} which is not in "
                    f"core/message.py WIRE_SLOTS — stale doc entry")
        yield from self._check_doc_msg_types()

    def _check_doc_msg_types(self) -> Iterator[Violation]:
        """Both-direction cross-check of the doc's message-type
        registry table against the MsgType enum (the slot-8/9
        precedent, extended to types: a new control message that never
        lands in the doc, or a stale doc row, is a violation)."""
        if self.msg_types is None:
            return
        doc = parse_doc_msg_types(self.doc_path)
        for name, value in sorted(self.msg_types.items()):
            if name == "Default":
                continue  # the unset header value, not a wire type
            if name not in doc:
                yield Violation(
                    self.doc_rel, 1, 0, self.name,
                    f"message type {name}={value} missing from the "
                    f"doc's message-type registry table "
                    f"(| `{name}` | {value} | row)")
            elif doc[name] != value:
                yield Violation(
                    self.doc_rel, 1, 0, self.name,
                    f"doc says {name} is {doc[name]} but "
                    f"core/message.py MsgType says {value} — the doc "
                    f"drifted from the wire")
        for name, value in sorted(doc.items()):
            if name not in self.msg_types:
                yield Violation(
                    self.doc_rel, 1, 0, self.name,
                    f"doc documents message type {name}={value} which "
                    f"is not in core/message.py MsgType — stale doc "
                    f"entry")
