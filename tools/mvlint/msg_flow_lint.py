"""msg-flow lint (pass 11, interprocedural): the message protocol
graph — construction sites, handler dispatch, reply pairing — checked
against the flow table in ``docs/WIRE_FORMAT.md``, both directions.

The two recurring hand-debugged failure classes in an actor system are
"nobody answers this request" (a waiter blocks forever) and "the reply
arrives but the waiter is never counted down" (PR-6/9/12 starvation
was the transport-level cousin; the repair/rejoin paths keep flirting
with the protocol-level one). Both are *extractable* facts: the PR-16
call graph resolves handler bodies, and ``register_handler`` /
intercept-by-name sites enumerate exactly who answers what. The pass:

* **Registry hygiene** — no duplicate ``MsgType`` ints (``IntEnum``
  silently aliases duplicates — the second name becomes a ghost), and
  no dead types (an enum member mentioned nowhere in the package
  outside ``core/message.py`` is abandoned protocol surface).
* **Flow table, BOTH directions** — ``docs/WIRE_FORMAT.md`` gains a
  message-flow table classifying every type ``request`` / ``reply`` /
  ``fire-and-forget`` with its paired reply and its handlers; every
  enum member needs a row and every row an enum member (the wire-slot
  registry precedent). The ``handled by`` column must equal the
  *computed* handler set: ``register_handler`` sites (actor classes,
  resolved through the MRO so ``SyncServer`` rows read ``server``) and
  intercept-by-name sites (``Communicator._local_forward``,
  ``ShmNet.recv``). ``zoo`` marks the mailbox-pop types
  (``Control_Reply_Barrier`` / ``Control_Reply_Register``) that have
  no in-actor handler by design.
* **Exactly-one-handler discipline** — a type registered twice in one
  actor class is a silent overwrite (the dispatch dict keeps the
  last); a ``request``-kind type with no handler anywhere strands its
  requester's waiter.
* **Reply paths reach the waiter** — every worker-band reply handler
  (``-32 < type < 0``) must *reach* (call-graph closure) a
  ``Waiter.notify``/``release`` AND a ``take_error`` inspection: the
  error path (``mark_error``) must count the same waiter down the
  success path does, or a failed request hangs instead of raising.
* **Requests get answered** — every ``request``-kind type needs at
  least one handler whose closure constructs the paired reply
  (``create_reply_message()`` or a literal ``Message(msg_type=...)``
  of the paired type); fire-and-forget types are exempt *because the
  table says so* — the declaration is the reviewed artifact.

Fixture files (outside the package) are checked per-class with a graph
overlay, like pass 9: duplicate registrations, waiter-less reply
handlers and reply-less request handlers are flagged locally.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .framework import LintPass, ModuleInfo, Violation

PKG_PREFIX = "multiverso_tpu/"
MSG_REL = "multiverso_tpu/core/message.py"
DOC_REL = "docs/WIRE_FORMAT.md"

KINDS = ("request", "reply", "fire-and-forget")

#: Message-flow rows: | `Type` | kind | `Reply` or — | handlers |.
#: The kind keyword in column 2 keeps these from ever cross-matching
#: the registry table (int column 2) or the slot table (int column 1).
FLOW_ROW_RE = re.compile(
    r"^\|\s*`([A-Za-z_][A-Za-z0-9_]*)`\s*"
    r"\|\s*(request|reply|fire-and-forget)\s*"
    r"\|\s*(?:`([A-Za-z_][A-Za-z0-9_]*)`|—|-)\s*"
    r"\|\s*([a-z, \-]*?)\s*\|")

#: Handler names the table may use: actor classes resolve to the four
#: roles; module-level intercepts resolve to their module stem; `zoo`
#: marks the mailbox-pop reply types with no in-actor handler.
HANDLER_NAMES = frozenset(
    {"worker", "server", "controller", "communicator", "shm", "zoo"})

#: Worker-band replies (-32 < t < 0) complete a blocked Waiter; their
#: handlers owe the notify/take_error discipline checked below.
WORKER_BAND = (-32, 0)


def load_msg_type_lines(path: Path) -> Dict[str, Tuple[int, int]]:
    """``MsgType`` members parsed (never imported): name ->
    (value, line). Negative values arrive as ``UnaryOp(USub)``."""
    out: Dict[str, Tuple[int, int]] = {}
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return out
    for node in ast.walk(tree):
        if not (isinstance(node, ast.ClassDef) and node.name == "MsgType"):
            continue
        for stmt in node.body:
            if not (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)):
                continue
            value = stmt.value
            sign = 1
            if isinstance(value, ast.UnaryOp) and \
                    isinstance(value.op, ast.USub):
                sign, value = -1, value.operand
            if isinstance(value, ast.Constant) and \
                    isinstance(value.value, int):
                out[stmt.targets[0].id] = (sign * value.value,
                                           stmt.lineno)
    return out


def load_flow_table(path: Path) -> Dict[str, Tuple[str, Optional[str],
                                                   Tuple[str, ...], int]]:
    """docs/WIRE_FORMAT.md flow rows: name ->
    (kind, paired reply or None, handler names, line)."""
    out: Dict[str, Tuple[str, Optional[str], Tuple[str, ...], int]] = {}
    try:
        lines = path.read_text(encoding="utf-8").splitlines()
    except OSError:
        return out
    for i, line in enumerate(lines, 1):
        m = FLOW_ROW_RE.match(line.strip())
        if m is None:
            continue
        handlers = tuple(sorted(h.strip() for h in m.group(4).split(",")
                                if h.strip()))
        out[m.group(1)] = (m.group(2), m.group(3), handlers, i)
    return out


def _msgtype_attr(node: ast.AST) -> Optional[str]:
    """``MsgType.X`` -> ``"X"``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and \
            node.value.id == "MsgType":
        return node.attr
    return None


def _compared_types(node: ast.Compare) -> List[str]:
    """Every MsgType name a comparison tests against (handles the
    ``== int(MsgType.X)`` and ``in (int(MsgType.X), ...)`` shapes)."""
    names: List[str] = []
    for comp in node.comparators:
        for sub in ast.walk(comp):
            name = _msgtype_attr(sub)
            if name is not None:
                names.append(name)
    return names


class _Handler:
    """One resolved dispatch site for a message type."""

    __slots__ = ("actor", "cls", "fn", "rel", "line", "kind")

    def __init__(self, actor: str, cls: Optional[str],
                 fn: Optional[FuncInfo], rel: str, line: int,
                 kind: str):
        self.actor = actor      # short handler name for the doc column
        self.cls = cls          # registering/intercepting class
        self.fn = fn            # handler body (None if unresolved)
        self.rel = rel
        self.line = line
        self.kind = kind        # "register" | "intercept"


class MsgFlowLint(LintPass):
    name = "msg-flow"

    def __init__(self, root: Path, graph: CallGraph):
        self.root = root
        self.graph = graph
        self.types = load_msg_type_lines(root / MSG_REL)
        self.flow = load_flow_table(root / DOC_REL)
        self.doc_exists = (root / DOC_REL).is_file()
        self._by_module: Dict[str, List[Violation]] = {}
        #: type name -> handler sites (package-wide)
        self._handlers: Dict[str, List[_Handler]] = {}
        #: type name -> every package mention outside message.py
        self._mentions: Dict[str, List[Tuple[str, int]]] = {}
        self._discover_package()

    # -- package discovery -------------------------------------------
    def _add(self, v: Violation) -> None:
        self._by_module.setdefault(v.path, []).append(v)

    def _discover_package(self) -> None:
        for rel, tree in sorted(self.graph.module_trees.items()):
            if not rel.startswith(PKG_PREFIX):
                continue
            self._scan_module(self.graph, rel, tree,
                              self._handlers, self._mentions,
                              self._add)
        self._check_handler_sets(self.graph, self._handlers, self._add,
                                 package=True)

    def _scan_module(self, graph: CallGraph, rel: str, tree: ast.AST,
                     handlers: Dict[str, List[_Handler]],
                     mentions: Dict[str, List[Tuple[str, int]]],
                     add) -> None:
        register_args: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "register_handler" and node.args:
                self._record_register(graph, rel, node, handlers, add)
                register_args.add(id(node.args[0]))
            elif isinstance(node, ast.Compare):
                self._record_intercepts(graph, rel, node, handlers)
        if rel == MSG_REL:
            return  # the enum itself is not a use
        for node in ast.walk(tree):
            name = _msgtype_attr(node)
            if name is not None and id(node) not in register_args:
                mentions.setdefault(name, []).append((rel, node.lineno))

    def _record_register(self, graph: CallGraph, rel: str,
                         node: ast.Call,
                         handlers: Dict[str, List[_Handler]],
                         add) -> None:
        type_name = _msgtype_attr(node.args[0])
        if type_name is None:
            return  # dynamic registration: out of scope
        if type_name not in self.types:
            add(Violation(
                rel, node.lineno, node.col_offset, self.name,
                f"register_handler for unknown message type "
                f"MsgType.{type_name} — not a member of the "
                f"core/message.py registry"))
            return
        fn = self._enclosing(graph, rel, node)
        cls = fn.cls if fn is not None else None
        handler_fn: Optional[FuncInfo] = None
        if len(node.args) > 1 and cls is not None:
            target = node.args[1]
            if isinstance(target, ast.Attribute) and \
                    isinstance(target.value, ast.Name) and \
                    target.value.id == "self":
                handler_fn = graph.lookup_method(cls, target.attr, rel)
        actor = self._actor_name(graph, cls, rel) if cls else \
            Path(rel).stem
        site = _Handler(actor, cls, handler_fn, rel, node.lineno,
                        "register")
        prior = [h for h in handlers.get(type_name, ())
                 if h.kind == "register" and h.cls == cls]
        if prior:
            add(Violation(
                rel, node.lineno, node.col_offset, self.name,
                f"duplicate register_handler for MsgType.{type_name} "
                f"in class {cls} (first at {prior[0].rel}:"
                f"{prior[0].line}) — the dispatch table keeps only "
                f"the last registration; the first handler silently "
                f"never runs"))
        handlers.setdefault(type_name, []).append(site)

    def _record_intercepts(self, graph: CallGraph, rel: str,
                           node: ast.Compare,
                           handlers: Dict[str, List[_Handler]]) -> None:
        """Intercept-by-name dispatch: type comparisons inside the
        sanctioned routing interceptors (``_local_forward``; the shm
        transport's below-the-router announce consumption)."""
        fn = self._enclosing(graph, rel, node)
        if fn is None:
            return
        if fn.name != "_local_forward" and \
                not rel.endswith("runtime/shm.py"):
            return
        for type_name in _compared_types(node):
            if type_name not in self.types:
                continue
            actor = Path(rel).stem
            sites = handlers.setdefault(type_name, [])
            if any(h.kind == "intercept" and h.rel == rel and
                   h.fn is fn for h in sites):
                continue  # one interceptor, many comparisons: one site
            sites.append(_Handler(actor, fn.cls, fn, rel, node.lineno,
                                  "intercept"))

    def _actor_name(self, graph: CallGraph, cls: str, rel: str) -> str:
        """Doc-column name for a registering class: the topmost
        concrete actor below ``Actor`` in the MRO (``SyncServer`` ->
        ``server``), else the class name itself."""
        mro = graph.mro(cls, rel)
        for info in mro:
            if "Actor" in info.bases:
                return info.name.lower()
        return cls.lower()

    def _enclosing(self, graph: CallGraph, rel: str,
                   node: ast.AST) -> Optional[FuncInfo]:
        best: Optional[FuncInfo] = None
        for fn in graph.functions.values():
            if fn.rel != rel:
                continue
            lo = fn.node.lineno
            hi = getattr(fn.node, "end_lineno", lo) or lo
            if lo <= node.lineno <= hi:
                if best is None or fn.node.lineno > best.node.lineno:
                    best = fn
        return best

    # -- reachability helpers ----------------------------------------
    def _reaches(self, graph: CallGraph, fn: FuncInfo,
                 binding: Optional[str], pred) -> bool:
        for _where, call, _path in graph.reachable_calls(fn, binding):
            if pred(call):
                return True
        return False

    @staticmethod
    def _is_notify(call: ast.Call) -> bool:
        return isinstance(call.func, ast.Attribute) and \
            call.func.attr in ("notify", "release")

    @staticmethod
    def _is_take_error(call: ast.Call) -> bool:
        name = call.func.attr if isinstance(call.func, ast.Attribute) \
            else (call.func.id if isinstance(call.func, ast.Name)
                  else None)
        return name == "take_error"

    @staticmethod
    def _builds_reply(call: ast.Call, paired: Optional[str]) -> bool:
        name = call.func.attr if isinstance(call.func, ast.Attribute) \
            else (call.func.id if isinstance(call.func, ast.Name)
                  else None)
        if name == "create_reply_message":
            return True
        if name == "Message" and paired is not None:
            for kw in call.keywords:
                if kw.arg == "msg_type" and \
                        _msgtype_attr(kw.value) == paired:
                    return True
        return False

    def _class_lexical(self, graph: CallGraph, cls: str, rel: str,
                       pred) -> bool:
        """Fallback when the closure walk cannot resolve a path: does
        ANY method of the class (MRO-wide) contain a matching call?"""
        for info in graph.mro(cls, rel):
            for fn in info.methods.values():
                for node in ast.walk(fn.node):
                    if isinstance(node, ast.Call) and pred(node):
                        return True
        return False

    def _handler_reaches(self, graph: CallGraph, site: _Handler,
                         pred) -> bool:
        if site.fn is not None and site.cls is not None:
            bindings = [site.cls] + [
                info.name for info in graph.subclasses(site.cls)
                if info.name != site.cls]
            for binding in bindings:
                if self._reaches(graph, site.fn, binding, pred):
                    return True
        if site.cls is not None:
            return self._class_lexical(graph, site.cls, site.rel, pred)
        return False

    # -- the behavioral checks ---------------------------------------
    def _check_handler_sets(self, graph: CallGraph,
                            handlers: Dict[str, List[_Handler]],
                            add, package: bool) -> None:
        """Waiter discipline + request-reply reachability. In package
        mode a request is satisfied when ANY of its handlers replies;
        fixture mode checks each class on its own."""
        for type_name, sites in sorted(handlers.items()):
            value = self.types.get(type_name, (None, 1))[0]
            if value is None:
                continue
            kind, paired = (self.flow.get(type_name) or
                            (None, None, (), 1))[:2]
            if WORKER_BAND[0] < value < WORKER_BAND[1]:
                for site in sites:
                    if site.kind != "register":
                        continue
                    where = site.fn if site.fn is not None else None
                    line = where.node.lineno if where else site.line
                    rel = where.rel if where else site.rel
                    if not self._handler_reaches(graph, site,
                                                 self._is_notify):
                        add(Violation(
                            rel, line, 0, self.name,
                            f"worker-band reply handler for "
                            f"MsgType.{type_name} in {site.cls} never "
                            f"reaches Waiter.notify/release — the "
                            f"requester's waiter blocks forever"))
                    if not self._handler_reaches(graph, site,
                                                 self._is_take_error):
                        add(Violation(
                            rel, line, 0, self.name,
                            f"reply handler for MsgType.{type_name} "
                            f"in {site.cls} never inspects "
                            f"take_error() — a mark_error reply must "
                            f"count the same waiter down the success "
                            f"path does, not vanish"))
            if kind == "request":
                answering = [
                    s for s in sites
                    if self._handler_reaches(
                        graph, s,
                        lambda c: self._builds_reply(c, paired))]
                if sites and not answering:
                    first = sites[0]
                    line = first.fn.node.lineno if first.fn is not None \
                        else first.line
                    rel = first.fn.rel if first.fn is not None \
                        else first.rel
                    add(Violation(
                        rel, line, 0, self.name,
                        f"request type MsgType.{type_name} has "
                        f"{len(sites)} handler(s) but none reaches "
                        f"create_reply_message() or a "
                        f"Message(msg_type=MsgType.{paired}) "
                        f"construction — nobody answers; declare it "
                        f"fire-and-forget in docs/WIRE_FORMAT.md or "
                        f"wire the reply"))

    # -- registry/doc directions (emitted scanning message.py) -------
    def _registry_checks(self) -> Iterator[Violation]:
        by_value: Dict[int, str] = {}
        for name, (value, line) in sorted(self.types.items(),
                                          key=lambda kv: kv[1][1]):
            if value in by_value:
                yield Violation(
                    MSG_REL, line, 0, self.name,
                    f"duplicate message-type int {value}: "
                    f"MsgType.{name} aliases MsgType.{by_value[value]} "
                    f"(IntEnum folds duplicate values into silent "
                    f"aliases — dispatch and band routing cannot tell "
                    f"them apart)")
            else:
                by_value[value] = name
        for name, (value, line) in sorted(self.types.items()):
            if name not in self._mentions and \
                    name not in self._handlers:
                yield Violation(
                    MSG_REL, line, 0, self.name,
                    f"dead message type MsgType.{name} ({value}): "
                    f"constructed and handled nowhere in the package "
                    f"— wire it up or delete it")
            kind = (self.flow.get(name) or (None,))[0]
            if kind == "request" and not self._handlers.get(name):
                yield Violation(
                    MSG_REL, line, 0, self.name,
                    f"request type MsgType.{name} ({value}) has no "
                    f"handler: no register_handler site and no "
                    f"intercept — its requester's waiter can never "
                    f"complete")

    def _doc_checks(self) -> Iterator[Violation]:
        if not self.doc_exists or not self.flow:
            yield Violation(
                DOC_REL, 1, 0, self.name,
                "docs/WIRE_FORMAT.md has no message-flow table "
                "(| `Type` | kind | `Reply` | handlers |) — every "
                "message type must be classified "
                "request/reply/fire-and-forget")
            return
        for name, (value, _line) in sorted(self.types.items()):
            if name not in self.flow:
                yield Violation(
                    DOC_REL, 1, 0, self.name,
                    f"MsgType.{name} ({value}) has no row in the "
                    f"docs/WIRE_FORMAT.md message-flow table — "
                    f"classify it request/reply/fire-and-forget")
        for name, (kind, paired, doc_handlers, line) in \
                sorted(self.flow.items()):
            if name not in self.types:
                yield Violation(
                    DOC_REL, line, 0, self.name,
                    f"message-flow row {name!r} matches no MsgType "
                    f"member — remove the stale row or register the "
                    f"type")
                continue
            bad = [h for h in doc_handlers if h not in HANDLER_NAMES]
            if bad:
                yield Violation(
                    DOC_REL, line, 0, self.name,
                    f"message-flow row {name!r} names unknown "
                    f"handler(s) {', '.join(bad)} — valid: "
                    f"{', '.join(sorted(HANDLER_NAMES))}")
            if kind == "request":
                if paired is None:
                    yield Violation(
                        DOC_REL, line, 0, self.name,
                        f"request row {name!r} names no paired reply "
                        f"— a request either has a reply type or is "
                        f"fire-and-forget")
                elif paired not in self.types:
                    yield Violation(
                        DOC_REL, line, 0, self.name,
                        f"request row {name!r} pairs with {paired!r} "
                        f"which is not a MsgType member")
                elif (self.flow.get(paired) or (None,))[0] != "reply":
                    yield Violation(
                        DOC_REL, line, 0, self.name,
                        f"request row {name!r} pairs with {paired!r} "
                        f"whose kind is not 'reply'")
            elif paired is not None:
                yield Violation(
                    DOC_REL, line, 0, self.name,
                    f"{kind} row {name!r} must not name a paired "
                    f"reply (column 3 is for request rows)")
            computed = sorted({h.actor for h in
                               self._handlers.get(name, ())})
            declared = sorted(doc_handlers)
            if "zoo" in declared:
                if declared != ["zoo"] or computed:
                    yield Violation(
                        DOC_REL, line, 0, self.name,
                        f"row {name!r}: 'zoo' marks a mailbox-pop "
                        f"type with NO in-actor handler, but the "
                        f"package computes handlers "
                        f"[{', '.join(computed) or 'none'}]")
            elif computed != declared:
                yield Violation(
                    DOC_REL, line, 0, self.name,
                    f"row {name!r} declares handlers "
                    f"[{', '.join(declared) or 'none'}] but the "
                    f"package computes [{', '.join(computed) or 'none'}] "
                    f"(register_handler + intercept sites) — the "
                    f"table and the code must agree both directions")

    # -- framework hook ----------------------------------------------
    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        rel = module.rel
        if rel.startswith("tests/") or rel == "bench.py":
            return
        if rel.startswith(PKG_PREFIX):
            yield from self._by_module.get(rel, [])
            if rel == MSG_REL:
                yield from self._registry_checks()
                yield from self._doc_checks()
            return
        # Fixture mode: overlay the module, check its classes locally.
        overlay = self.graph.with_module(rel, module.tree)
        local: List[Violation] = []
        handlers: Dict[str, List[_Handler]] = {}
        mentions: Dict[str, List[Tuple[str, int]]] = {}
        self._scan_module(overlay, rel, module.tree, handlers,
                          mentions, local.append)
        self._check_handler_sets(overlay, handlers, local.append,
                                 package=False)
        yield from local

    def tree_report(self) -> List[str]:
        n_handlers = sum(len(v) for v in self._handlers.values())
        return [f"msg-flow: {len(self.types)} message types, "
                f"{n_handlers} handler sites, "
                f"{len(self.flow)} flow rows proved both directions"]
