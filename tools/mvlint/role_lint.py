"""thread-role lint (pass 9): every thread declares a role; no
DISPATCH/LIVENESS thread can *reach* a blocking primitive.

The dispatch-thread-starvation class bit PRs 6, 9 and 12; the lexical
send-discipline pass (6) bans the one call shape that caused them,
but a blocking call two frames deep sails through lexical matching.
This pass is the interprocedural version, built on
:mod:`tools.mvlint.callgraph`:

* **Spawn discipline** — raw ``threading.Thread(...)`` inside
  ``multiverso_tpu`` is banned (``runtime/thread_roles.py`` itself,
  tests and bench are exempt); threads start through
  ``thread_roles.spawn(ROLE, target=...)``.
* **Role resolution** — the role argument must be a literal role
  constant, or ``self.ROLE``: then the *binding* decides, and the
  spawn expands over the enclosing class plus every package subclass
  with a resolvable literal ``ROLE`` attribute (``Actor.start``
  spawns ``Communicator._main`` as DISPATCH but ``Worker._main`` as
  ACTOR from the same line).
* **Registry cross-check, BOTH directions** — the spawn-derived
  (entry -> role) table must equal the literal ``THREAD_ROLES`` in
  ``runtime/thread_roles.py``, and that registry must equal the
  ``docs/THREADS.md`` inventory table (the WIRE_FORMAT.md registry
  precedent: code, registry and doc can never drift apart silently).
* **Blocking reachability** — from every DISPATCH/LIVENESS/EVENTLOOP
  entry the transitive call closure must not reach a blocking
  primitive: blocking ``net.send``, socket ``recv``/``recv_into``/
  ``accept``/``connect``/``create_connection``, frame reads
  (``_read_exact``/``_recv_into_exact``), or ``join``/``wait``/
  ``wait_for``/queue-``get`` without a timeout. ``net.recv`` (the
  communicator's inbox drain) and ``mailbox.pop`` are the *idle
  states* of those loops, not blocking bugs, and are excluded —
  as is ``selector.select(timeout)``, the event loop's one sanctioned
  park (its entry frame, which the watchdog reads as idle). Handler
  calls the graph cannot resolve statically (the loop's generic
  ``job()`` closures) are the runtime watchdog's territory
  (``-debug_locks`` + ``-role_block_budget_ms`` backstops dynamically
  whatever this walk cannot see). Findings are deduplicated per call
  site and report the full call path — one pragma at the site covers
  every root that reaches it.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .framework import LintPass, ModuleInfo, Violation
from .lock_lint import _has_timeout

ROLE_NAMES = ("DISPATCH", "ACTOR", "LIVENESS", "WRITER", "BACKGROUND",
              "EVENTLOOP")
CRITICAL_ROLES = ("DISPATCH", "LIVENESS", "EVENTLOOP")
NET_NAMES = {"net", "_net"}

PKG_PREFIX = "multiverso_tpu/"
ROLES_REL = "multiverso_tpu/runtime/thread_roles.py"
DOC_REL = "docs/THREADS.md"

#: docs/THREADS.md inventory rows: | `entry` | ROLE | budget |
DOC_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|\s*([A-Z]+)\s*\|")


def _strip_pkg(rel: str) -> str:
    return rel[len(PKG_PREFIX):] if rel.startswith(PKG_PREFIX) else rel


def _chain_tail(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _func_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return None


def classify_blocking(call: ast.Call) -> Optional[str]:
    """A short description when ``call`` is a blocking primitive,
    else None. Mirrors the lock-discipline taxonomy plus the
    transport shapes the send-discipline pass bans."""
    fn = call.func
    if isinstance(fn, ast.Name):
        if fn.id in ("_read_exact", "_recv_into_exact"):
            return f"{fn.id}() frame read"
        if fn.id == "create_connection":
            return "create_connection()"
        return None
    if not isinstance(fn, ast.Attribute):
        return None
    method = fn.attr
    tail = _chain_tail(fn.value)
    if method == "send" and tail in NET_NAMES:
        return "blocking net.send()"
    if method in ("recv", "recv_into") and tail not in NET_NAMES:
        # net.recv is the communicator's inbox drain (its idle
        # state); any other receive is a socket-level block.
        return f"socket .{method}()"
    if method == "accept":
        return ".accept()"
    if method in ("connect", "create_connection"):
        return f".{method}()"
    if method in ("join", "wait", "wait_for") \
            and not _has_timeout(call, method):
        return f".{method}() without timeout"
    if method == "get" and not call.args \
            and not _has_timeout(call, method):
        # Zero-positional-arg .get() is the queue/future shape;
        # dict.get(key[, default]) always passes the key positionally
        # and never blocks. A class-name receiver (FlagRegister.get())
        # is a classmethod accessor, never a queue pop.
        recv = fn.value
        if isinstance(recv, ast.Name) and recv.id[:1].isupper():
            return None
        return ".get() without timeout"
    return None


def load_thread_roles(root: Path) -> Tuple[Dict[str, str], int]:
    """The literal THREAD_ROLES registry (parsed, never imported)."""
    path = root / ROLES_REL
    try:
        tree = ast.parse(path.read_text(encoding="utf-8"))
    except (OSError, SyntaxError):
        return {}, 1
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "THREAD_ROLES"
                for t in node.targets) \
                and isinstance(node.value, ast.Dict):
            table: Dict[str, str] = {}
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and \
                        isinstance(k.value, str) and \
                        isinstance(v, ast.Name):
                    table[k.value] = v.id
            return table, node.lineno
    return {}, 1


def load_doc_roles(root: Path) -> Dict[str, Tuple[str, int]]:
    """docs/THREADS.md inventory: entry -> (role, line)."""
    path = root / DOC_REL
    out: Dict[str, Tuple[str, int]] = {}
    try:
        lines = path.read_text(encoding="utf-8").splitlines()
    except OSError:
        return out
    for i, line in enumerate(lines, 1):
        m = DOC_ROW_RE.match(line.strip())
        if m and m.group(2) in ROLE_NAMES:
            out[m.group(1)] = (m.group(2), i)
    return out


class _Spawn:
    """One resolved spawn site -> (entry key, role) bindings."""

    __slots__ = ("node", "rel", "entries", "problems")

    def __init__(self, node: ast.Call, rel: str):
        self.node = node
        self.rel = rel
        #: entry key (package-relative) -> role
        self.entries: Dict[str, str] = {}
        #: (line, col, message) for unresolvable role/target
        self.problems: List[Tuple[int, int, str]] = []


class ThreadRoleLint(LintPass):
    name = "thread-role"

    def __init__(self, root: Path, graph: CallGraph):
        self.root = root
        self.graph = graph
        self.registry, self.registry_line = load_thread_roles(root)
        self.doc_roles = load_doc_roles(root)
        self.doc_exists = (root / DOC_REL).is_file()
        # Package-wide discovery once: spawn table + reachability
        # findings grouped by the module each site lives in, so the
        # site's own pragmas can suppress (the framework only applies
        # a module's pragmas to findings in that module).
        self._by_module: Dict[str, List[Violation]] = {}
        self._package_entries: Dict[str, Tuple[str, str, int]] = {}
        self._discover_package()
        self._funcs_by_rel: Dict[str, List[FuncInfo]] = {}

    # -- package discovery -------------------------------------------
    def _discover_package(self) -> None:
        spawns: List[_Spawn] = []
        for rel, tree in sorted(self.graph.module_trees.items()):
            if not rel.startswith(PKG_PREFIX):
                continue
            spawns.extend(self._scan_module(self.graph, rel, tree))
        for spawn in spawns:
            for line, col, msg in spawn.problems:
                self._add(Violation(spawn.rel, line, col, self.name,
                                    msg))
            for entry, role in spawn.entries.items():
                known = self._package_entries.get(entry)
                if known and known[0] != role:
                    self._add(Violation(
                        spawn.rel, spawn.node.lineno,
                        spawn.node.col_offset, self.name,
                        f"thread entry {entry!r} spawned as {role} "
                        f"here but as {known[0]} at {known[1]}:"
                        f"{known[2]} — one entry point, one role"))
                    continue
                self._package_entries[entry] = (role, spawn.rel,
                                                spawn.node.lineno)
                declared = self.registry.get(entry)
                if declared is None:
                    self._add(Violation(
                        spawn.rel, spawn.node.lineno,
                        spawn.node.col_offset, self.name,
                        f"thread entry {entry!r} (role {role}) is "
                        f"not declared in THREAD_ROLES "
                        f"(runtime/thread_roles.py) — the registry "
                        f"is the canonical inventory"))
                elif declared != role:
                    self._add(Violation(
                        spawn.rel, spawn.node.lineno,
                        spawn.node.col_offset, self.name,
                        f"thread entry {entry!r} spawns with role "
                        f"{role} but THREAD_ROLES declares "
                        f"{declared}"))
        self._reach_check(self.graph, spawns, add=self._add)

    def _add(self, v: Violation) -> None:
        self._by_module.setdefault(v.path, []).append(v)

    # -- per-module scan ---------------------------------------------
    def _scan_module(self, graph: CallGraph, rel: str,
                     tree: ast.AST) -> List[_Spawn]:
        """Spawn sites (and raw-Thread violations) in one module."""
        spawns: List[_Spawn] = []
        exempt_raw = rel.endswith("runtime/thread_roles.py")
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _func_name(node)
            has_target = any(kw.arg == "target"
                             for kw in node.keywords)
            if name == "Thread" and not exempt_raw:
                self._add(Violation(
                    rel, node.lineno, node.col_offset, self.name,
                    "raw threading.Thread() in the package — spawn "
                    "through thread_roles.spawn(ROLE, target=...) so "
                    "the thread carries a declared role (watchdog + "
                    "reachability gate, docs/THREADS.md)"))
                continue
            if name != "spawn" or not has_target:
                continue
            spawns.append(self._resolve_spawn(graph, rel, node))
        return spawns

    def _resolve_spawn(self, graph: CallGraph, rel: str,
                       node: ast.Call) -> _Spawn:
        spawn = _Spawn(node, rel)
        fn = self._enclosing(graph, rel, node)
        target = next(kw.value for kw in node.keywords
                      if kw.arg == "target")
        role_expr = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "role"),
            None)
        if role_expr is None:
            spawn.problems.append((node.lineno, node.col_offset,
                                   "spawn(...) without a role"))
            return spawn
        # self.ROLE: the binding decides — expand over subclasses.
        if isinstance(role_expr, ast.Attribute) and \
                isinstance(role_expr.value, ast.Name) and \
                role_expr.value.id == "self":
            if fn is None or fn.cls is None or not isinstance(
                    target, ast.Attribute):
                spawn.problems.append((
                    node.lineno, node.col_offset,
                    "self-attribute role outside a method with a "
                    "self.<method> target cannot be resolved"))
                return spawn
            method = target.attr
            for info in graph.subclasses(fn.cls):
                role = graph.class_attr(info.name, role_expr.attr,
                                        info.rel)
                entry_fn = graph.lookup_method(info.name, method,
                                               info.rel)
                if role not in ROLE_NAMES or entry_fn is None:
                    spawn.problems.append((
                        node.lineno, node.col_offset,
                        f"subclass {info.name} ({info.rel}) has no "
                        f"literal {role_expr.attr} role or no "
                        f"{method}() — every binding of this spawn "
                        f"needs one"))
                    continue
                key = f"{_strip_pkg(info.rel)}::{info.name}.{method}"
                spawn.entries[key] = role
            return spawn
        role = self._literal_role(role_expr)
        if role is None:
            spawn.problems.append((
                node.lineno, node.col_offset,
                f"spawn role {ast.dump(role_expr)[:60]!r} is not a "
                f"literal role constant from runtime/thread_roles.py"))
            return spawn
        key = self._entry_key(graph, rel, fn, target)
        if key is None:
            spawn.problems.append((
                node.lineno, node.col_offset,
                "spawn target does not resolve to a known function "
                "(name a def/method, or functools.partial of one)"))
            return spawn
        spawn.entries[key] = role
        return spawn

    @staticmethod
    def _literal_role(expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Name) and expr.id in ROLE_NAMES:
            return expr.id
        if isinstance(expr, ast.Attribute) and expr.attr in ROLE_NAMES:
            return expr.attr
        if isinstance(expr, ast.Constant) and expr.value in ROLE_NAMES:
            return expr.value
        return None

    def _entry_key(self, graph: CallGraph, rel: str,
                   fn: Optional[FuncInfo],
                   target: ast.AST) -> Optional[str]:
        if fn is not None:
            resolved = graph.resolve_callable(target, fn, None)
            if resolved:
                entry, _ = resolved[0]
                return f"{_strip_pkg(entry.rel)}::{entry.qual}"
        if isinstance(target, ast.Attribute):
            # Unresolvable receiver (stdlib callables like
            # httpd.serve_forever): key by attribute name.
            return f"{_strip_pkg(rel)}::{target.attr}"
        return None

    def _enclosing(self, graph: CallGraph, rel: str,
                   node: ast.AST) -> Optional[FuncInfo]:
        best: Optional[FuncInfo] = None
        for fn in graph.functions.values():
            if fn.rel != rel:
                continue
            lo = fn.node.lineno
            hi = getattr(fn.node, "end_lineno", lo) or lo
            if lo <= node.lineno <= hi:
                if best is None or fn.node.lineno > best.node.lineno:
                    best = fn
        return best

    # -- reachability -------------------------------------------------
    def _reach_check(self, graph: CallGraph, spawns: List[_Spawn],
                     add) -> None:
        #: (path, line, col) -> [desc, roots, shortest chain]
        sites: Dict[Tuple[str, int, int], List] = {}
        for spawn in spawns:
            for entry, role in spawn.entries.items():
                if role not in CRITICAL_ROLES:
                    continue
                fn, binding = self._entry_func(graph, entry)
                if fn is None:
                    continue
                for where, call, path in graph.reachable_calls(
                        fn, binding,
                        prune=lambda f, c: classify_blocking(c)
                        is not None):
                    desc = classify_blocking(call)
                    if desc is None:
                        continue
                    site = (where.rel, call.lineno, call.col_offset)
                    chain = tuple(path) + (f"{where.rel}::"
                                           f"{where.qual}",)
                    root = f"{role} {entry}"
                    if site not in sites:
                        sites[site] = [desc, {root}, chain, entry]
                    else:
                        sites[site][1].add(root)
                        if len(chain) < len(sites[site][2]):
                            sites[site][2] = chain
        for (path, line, col), (desc, roots, chain, entry) \
                in sorted(sites.items()):
            rendered = " -> ".join(
                f"{Path(k.split('::')[0]).name}:{k.split('::')[1]}"
                for k in chain)
            add(Violation(
                path, line, col, self.name,
                f"{desc} reachable from latency-critical thread(s) "
                f"[{', '.join(sorted(roots))}] via {rendered} — "
                f"DISPATCH/LIVENESS/EVENTLOOP threads must never "
                f"block (docs/THREADS.md); route through send_async "
                f"or an event-loop timer/queue"))

    def _entry_func(self, graph: CallGraph,
                    entry: str) -> Tuple[Optional[FuncInfo],
                                         Optional[str]]:
        rel, qual = entry.split("::", 1)
        for prefix in (PKG_PREFIX, ""):
            fn = graph.functions.get(f"{prefix}{rel}::{qual}")
            if fn is not None:
                return fn, fn.cls
        # Virtual binding: Worker._main lives on Actor — resolve the
        # method through the MRO, carry the subclass as binding.
        if "." in qual:
            cls, method = qual.rsplit(".", 1)
            fn = graph.lookup_method(cls, method)
            if fn is not None:
                return fn, cls
        return None, None

    # -- framework hook ----------------------------------------------
    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        rel = module.rel
        if rel.startswith("tests/") or rel == "bench.py":
            return
        if rel.startswith(PKG_PREFIX):
            yield from self._by_module.get(rel, [])
            if rel == ROLES_REL:
                yield from self._registry_direction()
                yield from self._doc_direction()
            return
        # Outside the package (fixtures): overlay and self-check.
        overlay = self.graph.with_module(rel, module.tree)
        local: List[Violation] = []
        spawns = self._scan_local(overlay, rel, module.tree,
                                  local.append)
        self._reach_check(overlay, spawns, local.append)
        yield from local

    def _scan_local(self, graph: CallGraph, rel: str, tree: ast.AST,
                    add) -> List[_Spawn]:
        saved = self._add
        try:
            self._add = add  # type: ignore[assignment]
            spawns = self._scan_module(graph, rel, tree)
            for spawn in spawns:
                for line, col, msg in spawn.problems:
                    add(Violation(rel, line, col, self.name, msg))
                for entry, role in spawn.entries.items():
                    if role not in ROLE_NAMES:
                        add(Violation(rel, spawn.node.lineno,
                                      spawn.node.col_offset,
                                      self.name,
                                      f"unknown role {role!r}"))
        finally:
            self._add = saved  # type: ignore[assignment]
        return spawns

    def _registry_direction(self) -> Iterator[Violation]:
        for entry, role in sorted(self.registry.items()):
            if role not in ROLE_NAMES:
                yield Violation(
                    ROLES_REL, self.registry_line, 0, self.name,
                    f"THREAD_ROLES[{entry!r}] declares unknown role "
                    f"{role!r}")
            if entry not in self._package_entries:
                yield Violation(
                    ROLES_REL, self.registry_line, 0, self.name,
                    f"THREAD_ROLES entry {entry!r} matches no spawn "
                    f"site in the package — stale registry rows are "
                    f"drift (remove it or fix the spawn)")

    def _doc_direction(self) -> Iterator[Violation]:
        if not self.doc_exists:
            yield Violation(
                DOC_REL, 1, 0, self.name,
                "docs/THREADS.md is missing — the thread-role "
                "inventory table must document every THREAD_ROLES "
                "entry")
            return
        for entry, role in sorted(self.registry.items()):
            doc = self.doc_roles.get(entry)
            if doc is None:
                yield Violation(
                    DOC_REL, 1, 0, self.name,
                    f"THREAD_ROLES entry {entry!r} ({role}) has no "
                    f"row in the docs/THREADS.md inventory table")
            elif doc[0] != role:
                yield Violation(
                    DOC_REL, doc[1], 0, self.name,
                    f"docs/THREADS.md lists {entry!r} as {doc[0]} "
                    f"but THREAD_ROLES declares {role}")
        for entry, (role, line) in sorted(self.doc_roles.items()):
            if entry not in self.registry:
                yield Violation(
                    DOC_REL, line, 0, self.name,
                    f"docs/THREADS.md row {entry!r} ({role}) matches "
                    f"no THREAD_ROLES entry — remove the stale row "
                    f"or register the thread")

    def tree_report(self) -> List[str]:
        n_crit = sum(1 for r, _, _ in self._package_entries.values()
                     if r in CRITICAL_ROLES)
        return [f"thread-role: {len(self._package_entries)} entries "
                f"({n_crit} latency-critical) proved against "
                f"{len(self.registry)} registry rows"]
