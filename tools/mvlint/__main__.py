"""CLI: ``python -m tools.mvlint [--baseline] [paths...]``.

Default paths: ``multiverso_tpu tests bench.py`` relative to the repo
root. Exit status: 0 when no (non-pragma'd) violation was found, 1
otherwise. ``--baseline`` prints the per-pass violation + suppression
counts and always exits 0 — the drift-at-a-glance mode future PRs diff
against.
"""

from __future__ import annotations

import argparse
import sys

from . import DEFAULT_PATHS, REPO_ROOT, run


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.mvlint",
        description="project-invariant static analysis "
                    "(see docs/STATIC_ANALYSIS.md)")
    parser.add_argument("paths", nargs="*", default=list(DEFAULT_PATHS),
                        help="files/directories to scan "
                             f"(default: {' '.join(DEFAULT_PATHS)})")
    parser.add_argument("--baseline", action="store_true",
                        help="print per-pass counts, always exit 0")
    parser.add_argument("--report-unused-pragmas", action="store_true",
                        help="warn about '# mvlint: ignore[...]' "
                             "pragmas that suppressed zero findings "
                             "(stale suppressions are drift); "
                             "informational, never changes the exit "
                             "status")
    args = parser.parse_args(argv)

    try:
        result = run(args.paths or DEFAULT_PATHS, REPO_ROOT)
    except FileNotFoundError as exc:
        print(str(exc), file=sys.stderr)
        return 2
    if not result.files_scanned and not result.violations:
        # Zero files parsed and nothing to report: a vacuous pass must
        # not look like a clean one (e.g. a directory of no .py files).
        print("mvlint: no files scanned — bad path set?",
              file=sys.stderr)
        return 2

    for violation in result.violations:
        print(violation.render())
    for line in result.info:
        print(f"note: {line}")
    if args.report_unused_pragmas:
        for rel, line, name in result.unused_pragmas:
            print(f"warning: {rel}:{line}: unused pragma "
                  f"[{name}] — suppresses no finding")
        print(f"mvlint: {len(result.unused_pragmas)} unused "
              f"pragma(s)")
    print(f"mvlint: scanned {result.files_scanned} files")
    for name in sorted(set(result.per_pass) | set(result.per_pass_suppressed)):
        count = result.per_pass.get(name, 0)
        sup = result.per_pass_suppressed.get(name, 0)
        print(f"  {name:18s} {count:3d} violations"
              f"  ({sup} pragma-suppressed)")
    if args.baseline:
        return 0
    if result.failed:
        print(f"mvlint: FAILED with {len(result.violations)} "
              f"violation(s)", file=sys.stderr)
        return 1
    print("mvlint: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
