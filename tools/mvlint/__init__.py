"""mvlint: project-invariant static analysis for the actor/PS runtime.

Twelve passes over ``multiverso_tpu/``, ``bench.py`` and ``tests/``
(see each module's docstring for the precise rules):

* ``flag-lint`` — every flag access names a canonical registered flag
  with the canonical default (``util/configure.py CANONICAL_FLAGS``).
* ``wire-slot`` — reserved header slots 5-9 are accessed by registered
  name only (``core/message.py WIRE_SLOTS``), and the registry matches
  the slot table in ``docs/WIRE_FORMAT.md``.
* ``device-dispatch`` — multi-zoo-reachable eager dispatch sits inside
  a ``device_lock.guard()``-class context (the PR-1/PR-4 XLA wedge).
* ``lock-discipline`` — registered locks are ``with``-scoped and never
  lexically wrap a blocking call.
* ``metric-name`` — every ``monitor``/``samples``/``count`` literal
  names a canonical metric (``util/dashboard.py METRIC_NAMES``,
  cross-checked against the table in ``docs/OBSERVABILITY.md``).
* ``send-discipline`` — blocking ``net.send`` stays inside the
  transport layer; liveness/control frames ride ``send_async`` (the
  PR-6/PR-9 dispatch-thread-starvation class, now machine-checked).
* ``tunable-lint`` — every ``TUNABLE_FLAGS`` entry names a canonical
  flag and has a ``register_tunable_hook`` call site; every autotune
  policy's metric input names a canonical metric
  (``util/configure.py`` / ``runtime/autotune.py``; docs/AUTOTUNE.md).
* ``copy-lint`` — ``.tobytes()`` / ``bytes(...)`` / ``b"".join`` are
  banned on the zero-copy wire-path modules outside pragma-sanctioned
  sites, and the module list is cross-checked against the table in
  ``docs/MEMORY.md`` in both directions.
* ``thread-role`` — every thread spawns through
  ``thread_roles.spawn(ROLE, ...)``; the spawn-derived inventory
  matches ``THREAD_ROLES`` and ``docs/THREADS.md`` both directions;
  and no DISPATCH/LIVENESS entry can *reach* a blocking primitive
  through the interprocedural call graph (``callgraph.py`` — the
  proof-strength successor to the lexical send-discipline ban).
* ``guarded-by`` — ``# guarded_by: <lock>`` annotated fields are only
  touched under their witness-registered lock, lexically or via the
  caller-holds analysis (Clang ``-Wthread-safety`` adapted to
  ``lock_witness``).
* ``msg-flow`` — the message-protocol graph (``register_handler``
  dispatch, intercept-by-name, reply pairing) checked against the
  flow table in ``docs/WIRE_FORMAT.md`` both directions: every
  request type has a handler that answers, every worker-band reply
  handler reaches its ``Waiter`` notify AND inspects ``take_error``,
  no duplicate type ints, no dead types.
* ``wake-protocol`` — the gated wake-latch idiom (self-pipe /
  condition wake with a boolean gate) must re-arm the latch before
  the state checks and the park, in the lexical order the event loop
  uses post-PR-19 (the lost-wakeup ordering is rejected).

Run locally: ``python -m tools.mvlint multiverso_tpu tests bench.py``
(``--baseline`` prints per-pass counts without failing;
``--report-unused-pragmas`` lists suppressions that matched nothing).
The runtime complement — the ``-debug_locks`` lock-order witness and
the thread-role blocking watchdog — lives in
``multiverso_tpu/util/lock_witness.py`` and
``multiverso_tpu/runtime/thread_roles.py``. Docs:
``docs/STATIC_ANALYSIS.md``, ``docs/THREADS.md``.
"""

from __future__ import annotations

from pathlib import Path
from typing import List, Sequence

from .callgraph import CallGraph
from .copy_lint import CopyLint
from .device_dispatch_lint import DeviceDispatchLint
from .flag_lint import FlagLint, load_canonical_flags
from .framework import LintPass, RunResult, Violation, run_passes
from .guard_lint import GuardedByLint
from .lock_lint import LockDisciplineLint
from .metric_lint import MetricNameLint, load_metric_names
from .msg_flow_lint import MsgFlowLint
from .role_lint import ThreadRoleLint
from .send_lint import SendDisciplineLint
from .tunable_lint import (TunableLint, load_autotune_policies,
                           load_tunable_flags, scan_hook_sites)
from .wake_lint import WakeProtocolLint
from .wire_slot_lint import (WireSlotLint, load_msg_types,
                             load_wire_slots)

#: Repo root = two levels above this package (tools/mvlint/__init__.py).
REPO_ROOT = Path(__file__).resolve().parent.parent.parent

DEFAULT_PATHS = ("multiverso_tpu", "tests", "bench.py")


def build_passes(root: Path = REPO_ROOT) -> List[LintPass]:
    canonical = load_canonical_flags(
        root / "multiverso_tpu" / "util" / "configure.py")
    slots = load_wire_slots(
        root / "multiverso_tpu" / "core" / "message.py")
    msg_types = load_msg_types(
        root / "multiverso_tpu" / "core" / "message.py")
    metrics = load_metric_names(
        root / "multiverso_tpu" / "util" / "dashboard.py")
    tunables = load_tunable_flags(
        root / "multiverso_tpu" / "util" / "configure.py")
    policies = load_autotune_policies(
        root / "multiverso_tpu" / "runtime" / "autotune.py")
    hook_sites = scan_hook_sites(root / "multiverso_tpu")
    graph = CallGraph.build(root / "multiverso_tpu", root)
    return [
        FlagLint(canonical),
        WireSlotLint(slots, root / "docs" / "WIRE_FORMAT.md",
                     msg_types=msg_types),
        DeviceDispatchLint(),
        LockDisciplineLint(),
        MetricNameLint(metrics, root / "docs" / "OBSERVABILITY.md"),
        SendDisciplineLint(),
        TunableLint(tunables, canonical, metrics, policies,
                    hook_sites),
        CopyLint(root / "docs" / "MEMORY.md"),
        ThreadRoleLint(root, graph),
        GuardedByLint(graph),
        MsgFlowLint(root, graph),
        WakeProtocolLint(),
    ]


def run(paths: Sequence[str] = DEFAULT_PATHS,
        root: Path = REPO_ROOT) -> RunResult:
    return run_passes(build_passes(root), paths, root)
