"""guarded-by lint (pass 10): annotated instance fields stay under
their lock — lexically or because every caller holds it.

Clang ``-Wthread-safety`` / Java ``@GuardedBy`` adapted to this
codebase's ``lock_witness`` registry. A field opts in with a comment
on (or immediately above) the assignment that creates it:

    self._frames: list = []  # guarded_by: _cond

Then, in that module:

* the named lock must itself be **witness-registered** in the same
  class — assigned from ``named_lock``/``named_rlock``/
  ``named_condition`` (``util/lock_witness.py``) — so an annotation
  can never name a lock the runtime witness doesn't know;
* ``named_condition(name, lock)`` SHARES the passed lock, so the
  condition and its lock form an **alias group**: holding either
  satisfies an annotation naming the other (the MtQueue pattern);
* every read/write of ``self.<field>`` in the annotated class must
  sit under ``with <lock>`` (or ``acquire_timeout(<lock>, ...)``)
  **lexically**, or in a function whose every resolvable call site —
  found through the package call graph, same module only — is itself
  under the lock (**caller-holds**, bounded depth; the
  ``_store_locked``/``_report_locked`` idiom);
* ``__init__`` is exempt (the construction window publishes the
  object only at the end), and calls *from* ``__init__`` count as
  holding for the same reason.

Scope is deliberately module-local and name-matched (a ``with
x._lock`` on another object's lock of the same attribute name
passes): the pass proves the discipline the module declares for
itself and errs toward silence past that — ``-debug_locks``'s
runtime witness backstops the rest.
"""

from __future__ import annotations

import ast
import re
import tokenize
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .callgraph import CallGraph, FuncInfo
from .framework import LintPass, ModuleInfo, Violation

GUARD_RE = re.compile(r"#\s*guarded_by:\s*([A-Za-z_][A-Za-z0-9_]*)")

WITNESS_FACTORIES = {"named_lock", "named_rlock", "named_condition"}

#: caller-holds recursion bound (a chain deeper than this is not
#: evidence, it's a maze).
HOLD_DEPTH = 4


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class _ClassFacts:
    """Per-class annotation/lock tables for one module."""

    def __init__(self) -> None:
        #: field -> (lock name, annotation line)
        self.guards: Dict[str, Tuple[str, int]] = {}
        #: witness-registered lock attrs -> factory name
        self.locks: Dict[str, str] = {}
        #: lock attr -> full alias closure (incl. itself)
        self.aliases: Dict[str, Set[str]] = {}


class GuardedByLint(LintPass):
    name = "guarded-by"

    def __init__(self, graph: CallGraph):
        self.graph = graph
        self._fields_total = 0
        self._modules_with: Set[str] = set()
        self._caller_holds_uses = 0

    # -- comment collection ------------------------------------------
    @staticmethod
    def _guard_comments(module: ModuleInfo) -> Dict[int, str]:
        out: Dict[int, str] = {}
        try:
            tokens = tokenize.generate_tokens(
                iter(module.source.splitlines(keepends=True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = GUARD_RE.search(tok.string)
                if m:
                    out[tok.start[0]] = m.group(1)
        except tokenize.TokenError:
            pass
        return out

    # -- main ---------------------------------------------------------
    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        comments = self._guard_comments(module)
        if not comments:
            return
        graph = self.graph
        if module.rel not in graph.module_trees:
            graph = graph.with_module(module.rel, module.tree)
        facts, errors = self._collect(module, comments, graph)
        yield from errors
        n_fields = sum(len(f.guards) for f in facts.values())
        if n_fields:
            self._fields_total += n_fields
            self._modules_with.add(module.rel)
        # Lexical held-sets for every function in the module (also
        # feeds caller-holds), then the access check.
        held_at: Dict[ast.Call, frozenset] = {}
        accesses: List[Tuple[str, FuncInfo, ast.Attribute,
                             frozenset]] = []
        funcs = [fn for fn in graph.functions.values()
                 if fn.rel == module.rel]
        for fn in funcs:
            if fn.cls is None and "." in fn.qual:
                continue  # nested defs are scanned inside their parent
            self._scan_fn(fn, fn.node, frozenset(), held_at, accesses)
        holds_cache: Dict[Tuple[str, str, str], Optional[bool]] = {}
        for cls, fn, node, held in accesses:
            cf = facts.get(cls)
            if cf is None:
                continue
            guard = cf.guards.get(node.attr)
            if guard is None:
                continue
            lock, _ = guard
            wanted = cf.aliases.get(lock, {lock})
            if held & wanted:
                continue
            if fn.name == "__init__":
                continue  # construction window
            if self._caller_holds(module, graph, fn, wanted, held_at,
                                  holds_cache, HOLD_DEPTH):
                self._caller_holds_uses += 1
                continue
            kind = "write" if isinstance(node.ctx,
                                         (ast.Store, ast.Del)) \
                else "read"
            yield Violation(
                module.rel, node.lineno, node.col_offset, self.name,
                f"{kind} of {cls}.{node.attr} (guarded_by {lock}) "
                f"outside 'with self.{lock}' in {fn.qual}() — not "
                f"lexically held and not every caller holds it "
                f"(docs/STATIC_ANALYSIS.md pass 10)")

    # -- tables -------------------------------------------------------
    def _collect(self, module: ModuleInfo, comments: Dict[int, str],
                 graph: CallGraph):
        facts: Dict[str, _ClassFacts] = {}
        errors: List[Violation] = []
        #: line -> (class, field) for every self.<field> assignment
        assign_at: Dict[int, Tuple[str, str]] = {}
        for fn in graph.functions.values():
            if fn.rel != module.rel or fn.cls is None:
                continue
            cf = facts.setdefault(fn.cls, _ClassFacts())
            for node in ast.walk(fn.node):
                target = value = None
                if isinstance(node, ast.Assign) \
                        and len(node.targets) == 1:
                    target, value = node.targets[0], node.value
                elif isinstance(node, ast.AnnAssign):
                    target, value = node.target, node.value
                if not (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    continue
                assign_at.setdefault(node.lineno,
                                     (fn.cls, target.attr))
                if isinstance(value, ast.Call):
                    factory = _root_name(value.func)
                    if factory in WITNESS_FACTORIES:
                        cf.locks[target.attr] = factory
                        if factory == "named_condition" \
                                and len(value.args) >= 2:
                            other = _root_name(value.args[1])
                            if other:
                                group = (cf.aliases.get(target.attr,
                                                        set())
                                         | cf.aliases.get(other,
                                                          set())
                                         | {target.attr, other})
                                for name in group:
                                    cf.aliases[name] = group
        for cls, cf in facts.items():
            for lock in cf.locks:
                cf.aliases.setdefault(lock, {lock})
        for line, lock in sorted(comments.items()):
            hit = assign_at.get(line) or assign_at.get(line + 1)
            if hit is None:
                errors.append(Violation(
                    module.rel, line, 0, self.name,
                    "guarded_by annotation is not attached to a "
                    "self.<field> assignment (same line or the line "
                    "below)"))
                continue
            cls, field = hit
            cf = facts[cls]
            known = cf.guards.get(field)
            if known is not None and known[0] != lock:
                errors.append(Violation(
                    module.rel, line, 0, self.name,
                    f"{cls}.{field} annotated guarded_by {lock} here "
                    f"but guarded_by {known[0]} at line {known[1]} — "
                    f"one field, one lock"))
                continue
            cf.guards[field] = (lock, line)
            if lock not in cf.locks:
                errors.append(Violation(
                    module.rel, line, 0, self.name,
                    f"guarded_by names {lock!r} but {cls} registers "
                    f"no such lock with the witness (named_lock/"
                    f"named_rlock/named_condition, "
                    f"util/lock_witness.py) — the annotation must "
                    f"name a lock the witness knows"))
        return facts, errors

    # -- lexical scan -------------------------------------------------
    def _scan_fn(self, fn: FuncInfo, node: ast.AST, held: frozenset,
                 held_at: Dict[ast.Call, frozenset],
                 accesses: List) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                # Runs later: locks held here are not held there.
                self._scan_fn(fn, child, frozenset(), held_at,
                              accesses)
                continue
            if isinstance(child, ast.With):
                new_held = set(held)
                for item in child.items:
                    self._scan_fn(fn, item.context_expr, held,
                                  held_at, accesses)
                    expr = item.context_expr
                    name = _root_name(expr)
                    if isinstance(expr, ast.Call):
                        # acquire_timeout(self._lock, ...) holds it.
                        if _root_name(expr.func) == "acquire_timeout" \
                                and expr.args:
                            name = _root_name(expr.args[0])
                        else:
                            name = None
                    if name:
                        new_held.add(name)
                frozen = frozenset(new_held)
                for stmt in child.body:
                    self._scan_fn(fn, stmt, frozen, held_at, accesses)
                continue
            if isinstance(child, ast.Call):
                held_at[child] = held
            if isinstance(child, ast.Attribute) and \
                    isinstance(child.value, ast.Name) and \
                    child.value.id == "self" and fn.cls is not None:
                accesses.append((fn.cls, fn, child, held))
            self._scan_fn(fn, child, held, held_at, accesses)

    # -- caller-holds -------------------------------------------------
    def _caller_holds(self, module: ModuleInfo, graph: CallGraph,
                      fn: FuncInfo, wanted: Set[str],
                      held_at: Dict[ast.Call, frozenset],
                      cache: Dict, depth: int) -> bool:
        key = (fn.key, tuple(sorted(wanted)))
        if key in cache:
            return bool(cache[key])
        if depth <= 0:
            return False
        cache[key] = False  # cycle: a recursive chain is not evidence
        callers: List[Tuple[FuncInfo, ast.Call]] = []
        for other in graph.functions.values():
            if other.rel != module.rel or other is fn:
                continue
            for call in graph._calls_in(other):
                for callee, _ in graph.resolve_call(call, other, None):
                    if callee.key == fn.key:
                        callers.append((other, call))
                        break
        if not callers:
            cache[key] = False
            return False
        for caller, call in callers:
            if caller.name == "__init__":
                continue  # construction window counts as held
            held = held_at.get(call)
            if held is None:
                cache[key] = False
                return False
            if held & wanted:
                continue
            if not self._caller_holds(module, graph, caller, wanted,
                                      held_at, cache, depth - 1):
                cache[key] = False
                return False
        cache[key] = True
        return True

    def tree_report(self) -> List[str]:
        return [f"guarded-by: {self._fields_total} annotated fields "
                f"across {len(self._modules_with)} modules; "
                f"caller-holds satisfied "
                f"{self._caller_holds_uses} accesses"]
