"""metric-name lint: every metric literal names a canonical metric.

Source of truth: the ``METRIC_NAMES`` literal in
``multiverso_tpu/util/dashboard.py`` (parsed, never imported). Checked
per scanned file:

* ``monitor("X")`` / ``samples("X")`` / ``count("X")`` /
  ``count_event("X")`` — called as a PLAIN NAME with a literal string
  first argument — must name a registry entry. A trailing-``*`` family
  entry (``DISPATCH_MS[d*]``) covers its per-destination/per-table
  instances (``DISPATCH_MS[d3]``). A typo'd metric name otherwise
  splits a signal into two registries nobody correlates — the metric
  twin of the flag-lint's silently-ignored flag.
* Attribute calls (``str.count("x")``, ``report.count(...)``) are NOT
  matched — ``count`` is a common method name; the dashboard counters
  are only ever imported as plain names. Non-literal names (f-string
  families, module constants) are skipped, same contract as flag-lint's
  dynamic names.
* The metric table in ``docs/OBSERVABILITY.md`` is cross-checked
  against the registry in BOTH directions (| `NAME` | rows), so the
  doc cannot drift from the code.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, Iterator, Optional

from .framework import LintPass, ModuleInfo, Violation

METRIC_FNS = {"monitor", "samples", "count", "count_event"}

#: A metric-table row is `NAME` followed by its KIND (monitor /
#: samples / counter) — the kind column is what distinguishes the
#: metric registry table from the doc's other backticked tables (span
#: schema, endpoints), which must not be cross-checked as metrics.
DOC_ROW_RE = re.compile(
    r"^\|\s*`([A-Za-z0-9_.\[\]*]+)`\s*\|\s*(monitor|samples|counter)\b")


def load_metric_names(dashboard_path: Path) -> Dict[str, str]:
    """The METRIC_NAMES literal, by AST parse of util/dashboard.py."""
    tree = ast.parse(dashboard_path.read_text(encoding="utf-8"))
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for target in targets:
            if isinstance(target, ast.Name) \
                    and target.id == "METRIC_NAMES":
                value = ast.literal_eval(node.value)
                if isinstance(value, dict):
                    return value
    raise RuntimeError(
        f"no METRIC_NAMES dict literal in {dashboard_path}")


def parse_doc_metrics(doc_path: Path) -> Dict[str, int]:
    """``| `NAME` | ...`` rows from the doc's metric table (name ->
    first line seen)."""
    names: Dict[str, int] = {}
    if not doc_path.exists():
        return names
    for lineno, line in enumerate(
            doc_path.read_text(encoding="utf-8").splitlines(), 1):
        m = DOC_ROW_RE.match(line.strip())
        if m:
            names.setdefault(m.group(1), lineno)
    return names


def family_match(name: str, registry: Dict[str, str]) -> bool:
    """Exact entry, or covered by a trailing-``*`` family entry."""
    if name in registry:
        return True
    for pattern in registry:
        star = pattern.find("*")
        if star < 0:
            continue
        prefix, suffix = pattern[:star], pattern[star + 1:]
        if name.startswith(prefix) and name.endswith(suffix) \
                and len(name) >= len(prefix) + len(suffix):
            return True
    return False


def _literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


class MetricNameLint(LintPass):
    name = "metric-name"

    def __init__(self, registry: Dict[str, str], doc_path: Path,
                 doc_rel: str = "docs/OBSERVABILITY.md"):
        self.registry = registry
        self.doc_path = doc_path
        self.doc_rel = doc_rel
        self._doc_checked = False

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        if not self._doc_checked:
            self._doc_checked = True
            yield from self._check_doc()
        if module.path.name == "dashboard.py" \
                and "util" in module.path.parts:
            return  # the registry / accessor layer itself
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            # Plain-name calls only: `x.count("y")` is str/list.count,
            # not the dashboard counter (PR-5 `.get(key)` precedent).
            if not isinstance(fn, ast.Name) or fn.id not in METRIC_FNS:
                continue
            name = _literal_str(node.args[0])
            if name is None:
                continue  # dynamic name (f-string family): out of scope
            if family_match(name, self.registry):
                continue
            import difflib
            close = difflib.get_close_matches(
                name, sorted(self.registry), n=1)
            hint = f" — did you mean {close[0]!r}?" if close else ""
            yield Violation(
                module.rel, node.lineno, node.col_offset, self.name,
                f"{fn.id}({name!r}): not in the canonical metric "
                f"registry (util/dashboard.py METRIC_NAMES){hint}")

    def _check_doc(self) -> Iterator[Violation]:
        if not self.doc_path.exists():
            yield Violation(
                self.doc_rel, 1, 0, self.name,
                "observability doc missing: the metric registry must "
                "be documented (| `NAME` | table)")
            return
        doc = parse_doc_metrics(self.doc_path)
        for name in sorted(self.registry):
            if name not in doc:
                yield Violation(
                    self.doc_rel, 1, 0, self.name,
                    f"registered metric {name} missing from the doc's "
                    f"metric table (| `{name}` | row)")
        for name, lineno in sorted(doc.items()):
            if name not in self.registry:
                yield Violation(
                    self.doc_rel, lineno, 0, self.name,
                    f"doc documents metric {name} which is not in "
                    f"util/dashboard.py METRIC_NAMES — stale doc entry")
