"""device-dispatch fixture for server-side request fusion (filename
ends in device_train.py so the pass scopes it). Never imported, only
parsed.

A fused dispatch site gathers rows for MANY requests in one device
program (runtime/fusion.py; docs/SERVER_ENGINE.md) — so an unguarded
fused gather races every request in the batch at once. The pass must
see fused call sites exactly like serial ones.

Expected findings:
  line D: unguarded fused concat+gather dispatch -> violation
  line E: unguarded device_put of fused ids      -> violation
Clean: the fused group body under `with self._lock_for(table):` (the
guard Server._run_fused_group actually holds), and a whole-def pragma
on a fused helper.
"""

import jax
import jax.numpy as jnp


def fused_gather_bad(self, requests):
    ids = jnp.concatenate([r.keys for r in requests])        # D
    padded = jax.device_put(ids)                             # E
    return self._gather(self._data, padded)


def fused_gather_guarded(self, table, requests):
    with self._lock_for(table):
        ids = jnp.concatenate([r.keys for r in requests])
        return self._gather(self._data, ids)


def fused_scatter_caller_holds(self, stacked):  # mvlint: ignore[device-dispatch]
    return jnp.sum(stacked, axis=0)             # clean: whole-def pragma
