"""guarded-by fixture: seeded violations (never imported).

Expected findings (tests/test_mvlint.py pins the counts):
  line A: annotation names a lock the witness never
          registered for this class                  -> violation
  line B: off-lock read of a guarded field           -> violation
  line C: write of a guarded field in a helper whose
          caller does NOT hold the lock              -> violation
  line D: pragma'd off-lock write                    -> suppressed
Clean: lexical 'with self._lock' access, a caller-holds helper
(every caller holds the lock), the condition/lock alias group, and
__init__'s construction window.
"""

from multiverso_tpu.util.lock_witness import named_condition, named_lock


class SeededCache:
    def __init__(self):
        self._lock = named_lock("fixture.guards.lock")
        # named_condition(name, lock) SHARES the lock: holding either
        # satisfies annotations naming the other.
        self._cond = named_condition("fixture.guards.cond", self._lock)
        self._rows = {}  # guarded_by: _lock
        self._depth = 0  # guarded_by: _cond
        self._tag = ""  # guarded_by: _ghost   (A: unwitnessed lock)

    def ok_lexical(self, key, value):
        with self._lock:
            self._rows[key] = value

    def bad_read(self):
        return len(self._rows)                                   # B

    def bad_write_caller(self):
        # The violation lands inside _store: this caller holds
        # nothing, so caller-holds cannot vouch for the write.
        self._store(1, 2)

    def _store(self, key, value):
        self._rows[key] = value                                  # C

    def ok_caller_holds(self):
        with self._lock:
            self._bump()

    def _bump(self):
        # Clean: every caller holds _lock, and _cond aliases it.
        self._depth += 1

    def suppressed_reset(self):
        self._depth = 0  # mvlint: ignore[guarded-by]  (D)
