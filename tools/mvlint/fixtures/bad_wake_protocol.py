"""wake-protocol fixture: seeded latch-ordering violations (never
imported).

Expected findings (tests/test_mvlint.py pins the counts):
  line A: the pre-PR-19 ordering — the parking loop checks
          self._stopped BEFORE re-arming the wake latch; a
          stop() in that window sees the stale True gate,
          skips its byte, and the loop parks forever       -> violation
  line B: latch re-armed only AFTER the park               -> violation
  line C: parking loop never re-arms the latch at all      -> violation
  line D: pragma'd bad ordering (per-def)                 -> suppressed
Clean: GoodLoop re-arms first, then checks state, then parks —
the lexical order runtime/tcp.py's event loop uses.
"""

import os


class BadLoop:
    """The PR-19 lost-wakeup shape, verbatim."""

    def __init__(self, sel, rfd, wfd):
        self._sel = sel
        self._rfd = rfd
        self._wfd = wfd
        self._woken = False
        self._stopped = False

    def wake(self):
        if self._woken:
            return
        self._woken = True
        os.write(self._wfd, b"\0")

    def _main(self):
        while True:
            if self._stopped:
                return
            self._woken = False                                     # A
            self._sel.select(None)
            os.read(self._rfd, 4096)


class LateRearm:
    def __init__(self, sel):
        self._sel = sel
        self._woken = False

    def wake(self):
        if self._woken:
            return
        self._woken = True
        self._cond.notify_all()

    def _main(self):
        while True:
            self._sel.select(None)
            self._woken = False                                     # B


class NeverRearms:
    def __init__(self, sel, wfd):
        self._sel = sel
        self._wfd = wfd
        self._woken = False

    def wake(self):
        if self._woken:
            return
        self._woken = True
        os.write(self._wfd, b"\0")

    def _main(self):
        while True:                                                 # C
            self._sel.select(None)


class PragmaLoop:
    def __init__(self, sel, wfd):
        self._sel = sel
        self._wfd = wfd
        self._woken = False
        self._quit = False

    def wake(self):
        if self._woken:
            return
        self._woken = True
        os.write(self._wfd, b"\0")

    def _main(self):  # mvlint: ignore[wake-protocol]  (D)
        while True:
            if self._quit:
                return
            self._woken = False
            self._sel.select(None)


class GoodLoop:
    """Clean: re-arm FIRST, then the state checks, then the park."""

    def __init__(self, sel, wfd):
        self._sel = sel
        self._wfd = wfd
        self._woken = False
        self._stopped = False

    def wake(self):
        if self._woken:
            return
        self._woken = True
        os.write(self._wfd, b"\0")

    def _main(self):
        while True:
            self._woken = False
            if self._stopped:
                return
            self._sel.select(None)
