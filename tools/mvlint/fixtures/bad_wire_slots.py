"""wire-slot fixture: seeded violations (never imported, only parsed).

Expected findings:
  line A: raw int index into msg.header         -> violation
  line B: unregistered name index               -> violation
  line C: computed index                        -> violation
  line D: pragma'd raw index                    -> suppressed (counted)
Clean lines: registered slot names.
"""

from multiverso_tpu.core.message import CODEC_SLOT, ERROR_SLOT

MY_SLOT = 5


def seeded(msg, i):
    a = msg.header[5]                       # A: raw int
    b = msg.header[MY_SLOT]                 # B: unregistered name
    c = msg.header[i + 1]                   # C: computed
    d = msg.header[2]  # mvlint: ignore[wire-slot]
    ok1 = msg.header[ERROR_SLOT]            # clean
    msg.header[CODEC_SLOT] = 1              # clean (store)
    return a, b, c, d, ok1
