"""Seeded tunable-lint violations — the pass must keep firing on these
(ci.sh self-check: mvlint over fixtures/ must exit 1)."""

from multiverso_tpu.util.configure import register_tunable_hook


def _hook(value):
    pass


# VIOLATION: not a TUNABLE_FLAGS entry (typo'd name).
register_tunable_hook("max_get_stalness", _hook)

# VIOLATION: canonical but not declared tunable — would raise at
# import time in production; must fail statically here too.
register_tunable_hook("port", _hook)
