"""msg-flow fixture: seeded protocol-graph violations (never imported).

Expected findings (tests/test_mvlint.py pins the counts):
  line A: duplicate register_handler for one type in one
          class (dispatch dict keeps only the last)      -> violation
  line B: worker-band reply handler that checks
          take_error but never reaches Waiter.notify     -> violation
  line C: worker-band reply handler that notifies but
          never inspects take_error (mark_error replies
          vanish instead of raising)                     -> violation
  line D: request handler that never constructs the
          paired reply (nobody answers)                  -> violation
  line E: pragma'd duplicate registration               -> suppressed
Clean: EchoServer answers its request through
create_reply_message, notify+take_error both present in
FullReplies.
"""

from multiverso_tpu.core.message import MsgType, create_reply_message


class DoubleRegister:
    def __init__(self):
        self.register_handler(MsgType.Control_Metrics, self._on_a)
        self.register_handler(MsgType.Control_Metrics, self._on_b)  # A

    def register_handler(self, msg_type, fn):
        pass

    def _on_a(self, msg):
        pass

    def _on_b(self, msg):
        pass


class NoNotifyReplies:
    """Reply_Get handler loses the waiter: the requester's
    Waiter.wait() blocks forever even though the reply arrived."""

    def __init__(self, waiter):
        self._waiter = waiter
        self.register_handler(MsgType.Reply_Get, self._on_reply_get)

    def register_handler(self, msg_type, fn):
        pass

    def _on_reply_get(self, msg):                                   # B
        err = msg.take_error()
        if err is not None:
            raise RuntimeError(err)


class NoErrorReplies:
    """Reply_Add handler counts the waiter down but never looks at
    take_error: a mark_error reply reads as success."""

    def __init__(self, waiter):
        self._waiter = waiter
        self.register_handler(MsgType.Reply_Add, self._on_reply_add)

    def register_handler(self, msg_type, fn):
        pass

    def _on_reply_add(self, msg):                                   # C
        self._waiter.notify()


class DeafServer:
    """Request_Get is a request (the flow table pairs it with
    Reply_Get) but this handler never answers."""

    def __init__(self):
        self.register_handler(MsgType.Request_Get, self._on_get)

    def register_handler(self, msg_type, fn):
        pass

    def _on_get(self, msg):                                         # D
        self.rows = msg.blob(0)


class PragmaDouble:
    def __init__(self):
        self.register_handler(MsgType.Control_Barrier, self._on_a)
        self.register_handler(  # mvlint: ignore[msg-flow]  (E)
            MsgType.Control_Barrier, self._on_b)

    def register_handler(self, msg_type, fn):
        pass

    def _on_a(self, msg):
        return create_reply_message(msg)

    def _on_b(self, msg):
        return create_reply_message(msg)


class EchoServer:
    """Clean: the request handler constructs the paired reply."""

    def __init__(self):
        self.register_handler(MsgType.Request_Add, self._on_add)

    def register_handler(self, msg_type, fn):
        pass

    def _on_add(self, msg):
        return create_reply_message(msg)


class FullReplies:
    """Clean: notify AND take_error on the worker-band reply path."""

    def __init__(self, waiter):
        self._waiter = waiter
        self.register_handler(MsgType.Reply_BatchAdd, self._on_reply)

    def register_handler(self, msg_type, fn):
        pass

    def _on_reply(self, msg):
        err = msg.take_error()
        if err is not None:
            self._errors = err
        self._waiter.notify()
