"""lock-discipline fixture: seeded violations (never imported).

Expected findings:
  line A: bare .acquire() on a registered lock       -> violation
  line B: bare .release() on a registered lock       -> violation
  line C: blocking .pop() under a registered lock    -> violation
  line D: blocking .join() under a registered lock   -> violation
  line E: foreign condition .wait() under a lock     -> violation
  line E2: .wait_for(pred) — predicate is NOT a timeout -> violation
  line E3: sock.recv(n) — bufsize is NOT a timeout   -> violation
  line F: pragma'd bare acquire                      -> suppressed
Clean: with-scoped locks, the held condition's own wait, timeouts,
unregistered objects' acquire/release, and a def nested in a with.
"""

import threading

from multiverso_tpu.util.lock_witness import named_condition, named_lock


class Seeded:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = named_condition("fixture.cond")
        self._other = named_condition("fixture.other")
        self._pool = [named_lock(f"fixture.pool[{i}]") for i in range(4)]

    def bad(self, queue, thread, sock):
        self._lock.acquire()                     # A
        self._lock.release()                     # B
        with self._cond:
            item = queue.pop()                   # C
            thread.join()                        # D
            self._other.wait()                   # E
            self._other.wait_for(lambda: item)   # E2
            data = sock.recv(65536)              # E3
        self._pool[0].acquire()  # mvlint: ignore[lock-discipline]  (F)
        return item, data

    def good(self, queue, thread, waiter, net):
        with self._lock:
            x = queue.pop(timeout=1.0)
            y = net.recv(timeout=1.0)            # clean: bounded recv
        with self._pool[1]:
            thread.join(timeout=2.0)
        with self._cond:
            self._cond.wait(timeout=0.5)
            self._cond.wait()                    # clean: held cond
            self._other.wait_for(lambda: 1, 0.5)  # clean: pos. timeout
        waiter.release()                         # clean: unregistered
        with self._lock:
            def later():
                return queue.pop()               # clean: runs later
            return x, later
