"""thread-role fixture: seeded violations (never imported).

Expected findings (tests/test_mvlint.py pins the counts):
  line A: raw threading.Thread() in scanned code      -> violation
  line B: LIVENESS entry reaches net.send two helpers
          deep (the PR-6 heartbeat regression, caught
          interprocedurally at the send site)          -> violation
  line C: role is not a literal role constant         -> violation
  line D: spawn(...) without a role                   -> violation
  line E: target does not resolve to a known def      -> violation
  line F: pragma'd raw Thread                         -> suppressed
Clean: BACKGROUND spawns (may block), a DISPATCH entry that only
uses send_async, and a functools.partial target.
"""

import functools
import threading

from multiverso_tpu.runtime.thread_roles import (
    BACKGROUND, DISPATCH, LIVENESS, spawn)

UNKNOWN_CALLABLE = None


class SeededMonitor:
    """The PR-6 failure class, reachability edition: the blocking
    send hides two helpers below the LIVENESS entry point, so the
    old lexical send-ban never sees it from the spawn site."""

    def __init__(self, net):
        self._net = net
        self._raw = threading.Thread(target=self._hb_main)       # A
        self._thread = spawn(LIVENESS, target=self._hb_main)

    def _hb_main(self):
        while True:
            self._emit({"hb": 1})

    def _emit(self, frame):
        self._push(frame)

    def _push(self, frame):
        # B: the lexical pass-6 ban is pragma'd away on purpose —
        # pass 9 must still catch this through the call graph.
        self._net.send(frame)  # mvlint: ignore[send-discipline]

    def bad_spawns(self):
        spawn("TURBO", target=self._hb_main)                     # C
        spawn(target=self._hb_main)                              # D
        spawn(BACKGROUND, target=UNKNOWN_CALLABLE)               # E

    def legacy(self):
        return threading.Thread(  # mvlint: ignore[thread-role]  (F)
            target=self._fill)

    def start_ok(self):
        # Clean: BACKGROUND threads may block; the registry gate
        # applies to package spawn sites only.
        spawn(BACKGROUND, target=self._fill)
        spawn(BACKGROUND, target=functools.partial(self._fill, 3))
        # Clean: DISPATCH entry whose whole reachable surface is
        # non-blocking (send_async is the sanctioned form).
        spawn(DISPATCH, target=self._drain)

    def _fill(self, n=1):
        return [{}] * n

    def _drain(self):
        while True:
            self._net.send_async({"d": 1})
