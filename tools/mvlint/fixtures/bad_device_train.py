"""device-dispatch fixture (filename ends in device_train.py so the
pass scopes it). Never imported, only parsed.

Expected findings:
  line A: unguarded jnp dispatch                -> violation
  line B: unguarded jax.device_put              -> violation
  line C: unguarded immediate jit invocation    -> violation
Clean: guarded dispatch (guard() / _lock_for / lock-variable), traced
function bodies (jit-decorated, jit-by-name, called-from-traced), and
a whole-def pragma.
"""

import jax
import jax.numpy as jnp

from multiverso_tpu.runtime import device_lock


def eager_bad(x):
    a = jnp.concatenate(x)                       # A
    b = jax.device_put(x)                        # B
    c = jax.jit(lambda v: v + 1)(x)              # C
    return a, b, c


def eager_guarded(self, x, table):
    with device_lock.guard():
        ok1 = device_lock.settle(jnp.concatenate(x))
    with self._lock_for(table):
        ok2 = jnp.sum(x)
    lock = self._table_lock if x else self._no_lock
    with lock:
        ok3 = jnp.sum(x)
    return ok1, ok2, ok3


@jax.jit
def traced_decorated(x):
    return jnp.sum(x)          # clean: traced


def helper(x):
    return jnp.where(x > 0, x, 0)  # clean: called from traced_by_name


def traced_by_name(x):
    return helper(x) + jnp.sum(x)  # clean: jitted below


TRACED = jax.jit(traced_by_name)


def caller_holds_lock(x):  # mvlint: ignore[device-dispatch]
    return jnp.sum(x)          # clean: whole-def pragma
