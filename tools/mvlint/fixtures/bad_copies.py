"""Seeded copy-lint violations (tools/mvlint/copy_lint.py).

Each banned wire-path copy pattern appears once; the pragma'd site must
count as suppressed, and the view-reading idioms must stay silent.
"""

import numpy as np


def frame_the_slow_way(arr, chunks):
    payload = arr.tobytes()                 # violation: tobytes copy
    body = b"".join(chunks)                 # violation: flat-frame join
    head = bytes(memoryview(body)[:8])      # violation: bytes() copy
    return head, payload


def sanctioned(arr):
    # A deliberate legacy-path copy keeps the annotated escape hatch.
    return arr.tobytes()  # mvlint: ignore[copy-lint]


def stays_silent(arr, parts):
    views = [memoryview(p) for p in parts]  # view list: fine
    flat = np.frombuffer(parts[0], np.uint8)  # zero-copy wrap: fine
    empty = bytes()                         # no-arg: copies nothing
    return views, flat, empty
