"""Seeded violations for the send-discipline pass (tests/test_mvlint.py
pins the counts). NOT importable production code."""


class Monitor:
    def __init__(self, zoo, net):
        self._zoo = zoo
        self._net = net

    def tick_bad_direct(self, msg):
        # VIOLATION 1: blocking send of a liveness frame on a net
        # attribute chain.
        self._zoo.net.send(msg)

    def tick_bad_own_net(self, msg):
        # VIOLATION 2: same class through the actor's own _net handle.
        self._net.send(msg)

    def tick_ok_async(self, msg):
        self._zoo.net.send_async(msg)  # the required form — silent

    def tick_ok_socket(self, sock, frame):
        sock.send(frame)  # not a net chain — silent

    def tick_ok_generator(self, gen):
        gen.send(None)  # coroutine resume — silent

    def tick_suppressed(self, msg):
        self._net.send(msg)  # mvlint: ignore[send-discipline]
