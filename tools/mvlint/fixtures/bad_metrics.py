"""Seeded metric-name violations (mvlint self-check fixture).

Every block below must keep firing the ``metric-name`` pass — pinned
counts live in tests/test_mvlint.py. The registry the pass checks
against is ``multiverso_tpu/util/dashboard.py METRIC_NAMES``.
"""

from multiverso_tpu.util.dashboard import count, monitor, samples


def unknown_monitor():
    # Violation: typo'd monitor name (suggestion should name the real
    # SERVER_PROCESS_GET).
    with monitor("SERVER_PROCES_GET"):
        pass


def unknown_samples_family():
    # Violation: DISPATCH_MS[d*] covers d-suffixed instances only —
    # a q-keyed family member is not registered.
    samples("DISPATCH_MS[q9]").add(1.0)


def unknown_counter():
    # Violation: bare count() with an unregistered literal.
    count("TOTALLY_MADE_UP_COUNTER")


def family_instance_is_fine():
    # NOT a violation: covered by the DISPATCH_MS[d*] family entry.
    samples("DISPATCH_MS[d3]").add(1.0)


def method_count_is_fine(text: str) -> int:
    # NOT a violation: attribute call — str.count, not the dashboard
    # counter.
    return text.count("SERVER_PROCES_GET")


def pragma_suppressed():
    # Annotated exception: counted as suppressed, not as a violation.
    with monitor("FIXTURE_ONLY_REGION"):  # mvlint: ignore[metric-name]
        pass
