"""flag-lint fixture: seeded violations (never imported, only parsed).

Expected findings (tests/test_mvlint.py pins the count):
  line A: get_flag with a typo'd name           -> violation
  line B: get_flag with a drifted default       -> violation
  line C: define_int with a drifted default     -> violation
  line D: set_flag with an unknown name         -> violation
  line E: pragma'd unknown name                 -> suppressed (counted)
Clean lines (no finding): canonical name + canonical default; dynamic
name expression.
"""

from multiverso_tpu.util.configure import (define_int, get_flag,
                                           set_flag)


def seeded():
    a = get_flag("allreduce_windw")                   # A: typo
    b = get_flag("allreduce_window", 8)               # B: default drift
    define_int("send_queue_mb", 64)                   # C: define drift
    set_flag("wire_codec_lossyy", True)               # D: unknown
    e = get_flag("totally_dynamic_knob")  # mvlint: ignore[flag-lint]
    ok = get_flag("allreduce_window", 4)              # clean
    name = "allreduce" + "_window"
    dyn = get_flag(name)                              # clean (dynamic)
    return a, b, e, ok, dyn
