"""copy-lint: the zero-copy wire path stays zero-copy.

PR 15 removed the per-payload copies from the transport hot path — the
send side serializes scatter-gather views drained by vectored
``sendmsg`` writes, the receive side cuts read-only Blob views out of
pooled frame buffers (docs/MEMORY.md). The three patterns that
reintroduce a payload copy are banned ON THE WIRE-PATH MODULES:

* ``x.tobytes()`` — materializes a private bytes copy of an array;
* ``bytes(x)`` (with arguments) — copies any buffer into a bytes
  object (``bytes()`` no-arg and ``bytes(n)`` allocation are copies of
  nothing, but the lint cannot tell an int from a buffer statically,
  so both forms are flagged and sanctioned sites carry the pragma);
* ``b"...".join(...)`` — the flat-frame join.

Sanctioned sites (the legacy ``-zero_copy=0`` serializer kept as the
golden baseline, the codec's flat-frame compat wrapper) carry
``# mvlint: ignore[copy-lint]`` pragmas — counted, visible exceptions.
Everything outside the wire-path module list is out of scope: tables,
models and snapshots copy for their own good reasons.

The wire-path module list below is cross-checked against the module
table in ``docs/MEMORY.md`` in BOTH directions (| `path` | wire-path |
rows), so the doc cannot drift from what the lint enforces — the same
contract as the metric-name and wire-slot doc checks.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Iterator

from .framework import LintPass, ModuleInfo, Violation

#: THE wire-path module set — every module a payload byte crosses
#: between a table op and the socket. Kept in lockstep with the table
#: in docs/MEMORY.md (both-direction cross-check below).
WIRE_PATH_MODULES = (
    "multiverso_tpu/core/blob.py",
    "multiverso_tpu/core/message.py",
    "multiverso_tpu/runtime/tcp.py",
    "multiverso_tpu/runtime/shm.py",
    "multiverso_tpu/runtime/communicator.py",
    "multiverso_tpu/runtime/allreduce_engine.py",
    "multiverso_tpu/util/wire_codec.py",
    "multiverso_tpu/util/buffer_pool.py",
)

#: The seeded-violation fixture self-checks this pass (tests/test_mvlint).
FIXTURE = "tools/mvlint/fixtures/bad_copies.py"

#: A doc-table row is `path` followed by the literal kind "wire-path" —
#: the marker that distinguishes the module table from the doc's other
#: backticked tables (size classes, copy counts).
DOC_ROW_RE = re.compile(r"^\|\s*`([A-Za-z0-9_/\.]+)`\s*\|\s*wire-path\b")


def parse_doc_modules(doc_path: Path) -> dict:
    """``| `path` | wire-path |`` rows from docs/MEMORY.md (path ->
    first line seen)."""
    rows: dict = {}
    if not doc_path.exists():
        return rows
    for lineno, line in enumerate(
            doc_path.read_text(encoding="utf-8").splitlines(), 1):
        m = DOC_ROW_RE.match(line.strip())
        if m:
            rows.setdefault(m.group(1), lineno)
    return rows


class CopyLint(LintPass):
    name = "copy-lint"

    def __init__(self, doc_path: Path,
                 doc_rel: str = "docs/MEMORY.md"):
        self.doc_path = doc_path
        self.doc_rel = doc_rel
        self._doc_checked = False

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        if not self._doc_checked:
            self._doc_checked = True
            yield from self._check_doc()
        rel = module.rel
        if rel not in WIRE_PATH_MODULES and rel != FIXTURE:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if isinstance(fn, ast.Attribute) and fn.attr == "tobytes":
                yield self._violation(
                    module, node,
                    ".tobytes() copies the whole payload on the "
                    "zero-copy wire path — serialize views "
                    "(Blob.wire_views / serialize_views) instead")
            elif isinstance(fn, ast.Name) and fn.id == "bytes" \
                    and (node.args or node.keywords):
                yield self._violation(
                    module, node,
                    "bytes(...) copies its buffer on the zero-copy "
                    "wire path — read through memoryview/numpy views "
                    "(Message.text_payload for text payloads) instead")
            elif isinstance(fn, ast.Attribute) and fn.attr == "join" \
                    and isinstance(fn.value, ast.Constant) \
                    and isinstance(fn.value.value, bytes):
                yield self._violation(
                    module, node,
                    "bytes-join builds a flat frame copy on the "
                    "zero-copy wire path — emit a view list for the "
                    "vectored sendmsg write instead")

    def _violation(self, module: ModuleInfo, node: ast.AST,
                   message: str) -> Violation:
        return Violation(
            module.rel, node.lineno, node.col_offset, self.name,
            message + " (sanctioned sites: # mvlint: "
                      "ignore[copy-lint]; docs/MEMORY.md)")

    def _check_doc(self) -> Iterator[Violation]:
        if not self.doc_path.exists():
            yield Violation(
                self.doc_rel, 1, 0, self.name,
                "memory doc missing: the wire-path module list must be "
                "documented (| `path` | wire-path | table)")
            return
        doc = parse_doc_modules(self.doc_path)
        for path in WIRE_PATH_MODULES:
            if path not in doc:
                yield Violation(
                    self.doc_rel, 1, 0, self.name,
                    f"wire-path module {path} missing from the doc's "
                    f"module table (| `{path}` | wire-path | row)")
        for path, lineno in sorted(doc.items()):
            if path not in WIRE_PATH_MODULES:
                yield Violation(
                    self.doc_rel, lineno, 0, self.name,
                    f"doc lists {path} as a wire-path module but "
                    f"tools/mvlint/copy_lint.py WIRE_PATH_MODULES "
                    f"does not — stale doc entry")
