"""wake-protocol lint (pass 12): the check-flag-then-block idiom that
produced the PR-19 lost wakeup, enforced in the lexical order the
event loop now uses.

The hazardous shape is a *wake latch*: a boolean attribute guarding a
wake side-channel so N wake() calls cost one pipe byte / one notify —

    def wake(self):
        if self._woken:          # gate: someone already paid the byte
            return
        self._woken = True
        os.write(self._wake_w, b"\\0")

paired with a consumer loop that re-arms the latch (``self._woken =
False``) and then parks (``select`` / condition ``wait`` /
``os.read``). The PR-19 bug was pure *ordering*: the loop drained the
pipe, THEN checked ``self._stopped``, THEN re-armed. A ``stop()``
landing in the drain→re-arm window saw the stale ``True`` latch,
skipped its byte, and the loop parked forever on an empty pipe. The
fix — and the idiom this pass enforces — re-arms FIRST, before any
state check and before the park: a stale-latch window then never
overlaps a park, because any wake that set the latch after the last
drain also left its byte in the pipe.

Detection is lexical, per class, no call graph needed:

* a **latch** is an attribute with the gate shape above (an
  ``if self.X: return`` guard plus ``self.X = True`` in one method,
  followed by a wake side-effect call — ``write``/``notify``/
  ``notify_all``/``set``). The side-effect requirement keeps
  idempotent-close guards (``if self._closed: return``), which are
  one-way flags and never re-armed, out of scope.
* every ``while`` loop in the same class that **parks** (calls
  ``select``/``wait``/``wait_for``/``os.read``) must re-arm the
  latch, and the re-arm must lexically precede every ``if`` statement
  and every park in the loop body.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .framework import LintPass, ModuleInfo, Violation

#: Call names that count as the gate's wake side-effect.
WAKE_EFFECTS = frozenset({"write", "notify", "notify_all", "set"})

#: Call names that park the calling thread.
PARK_CALLS = frozenset({"select", "wait", "wait_for"})


def _call_name(call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        return call.func.attr
    if isinstance(call.func, ast.Name):
        return call.func.id
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.x`` -> ``"x"``."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _is_park(call: ast.Call) -> bool:
    name = _call_name(call)
    if name in PARK_CALLS:
        return True
    # os.read(fd, n): the raw self-pipe drain.
    return name == "read" and isinstance(call.func, ast.Attribute) and \
        isinstance(call.func.value, ast.Name) and \
        call.func.value.id == "os"


def _walk_no_defs(node: ast.AST) -> Iterator[ast.AST]:
    """Recursive walk that does not descend into nested defs/lambdas
    (their bodies run on their own schedule, not in this loop)."""
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _walk_no_defs(child)


class WakeProtocolLint(LintPass):
    name = "wake-protocol"

    # -- latch discovery ---------------------------------------------
    def _gate_latches(self, cls: ast.ClassDef) -> Dict[str, int]:
        """Attr name -> gate line, for every wake-latch gate in the
        class: ``if self.X: return`` + ``self.X = True`` + a wake
        side-effect call after the set, all in one method."""
        latches: Dict[str, int] = {}
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            guards: Dict[str, int] = {}
            sets: Dict[str, int] = {}
            effects: List[int] = []
            for node in _walk_no_defs(item):
                if isinstance(node, ast.If):
                    attr = _self_attr(node.test)
                    if attr is not None and any(
                            isinstance(s, ast.Return)
                            for s in node.body):
                        guards.setdefault(attr, node.lineno)
                elif isinstance(node, ast.Assign) and \
                        len(node.targets) == 1:
                    attr = _self_attr(node.targets[0])
                    if attr is not None and \
                            isinstance(node.value, ast.Constant) and \
                            node.value.value is True:
                        sets.setdefault(attr, node.lineno)
                elif isinstance(node, ast.Call) and \
                        _call_name(node) in WAKE_EFFECTS:
                    effects.append(node.lineno)
            for attr, gline in guards.items():
                sline = sets.get(attr)
                if sline is None:
                    continue
                if any(e >= sline for e in effects):
                    latches.setdefault(attr, gline)
        return latches

    # -- loop checks -------------------------------------------------
    def _check_loop(self, rel: str, cls_name: str, latch: str,
                    loop: ast.While) -> Iterator[Violation]:
        parks: List[int] = []
        rearms: List[int] = []
        checks: List[int] = []
        for node in _walk_no_defs(loop):
            if isinstance(node, ast.Call) and _is_park(node):
                parks.append(node.lineno)
            elif isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    _self_attr(node.targets[0]) == latch and \
                    isinstance(node.value, ast.Constant) and \
                    node.value.value is False:
                rearms.append(node.lineno)
            elif isinstance(node, ast.If):
                checks.append(node.lineno)
        if not parks:
            return
        if not rearms:
            yield Violation(
                rel, loop.lineno, loop.col_offset, self.name,
                f"{cls_name}: loop parks (select/wait/os.read) but "
                f"never re-arms wake latch self.{latch} — after the "
                f"first wake the gate stays True, every later wake "
                f"is skipped, and the park never returns")
            return
        rearm = min(rearms)
        bad_park = min(parks) < rearm
        bad_check = any(c < rearm for c in checks)
        if bad_park or bad_check:
            what = "the park" if bad_park and not bad_check else (
                "a state check" if bad_check and not bad_park
                else "a state check and the park")
            yield Violation(
                rel, rearm, 0, self.name,
                f"{cls_name}: wake latch self.{latch} is re-armed "
                f"AFTER {what} in the parking loop — a wake landing "
                f"in the drain-to-re-arm window sees the stale True "
                f"gate, skips its wake byte, and the next park "
                f"blocks forever (the PR-19 lost-wakeup shape); "
                f"re-arm first, then check state, then park")

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            latches = self._gate_latches(node)
            if not latches:
                continue
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for sub in _walk_no_defs(item):
                    if isinstance(sub, ast.While):
                        for latch in sorted(latches):
                            yield from self._check_loop(
                                module.rel, node.name, latch, sub)
