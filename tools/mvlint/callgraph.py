"""Interprocedural core: a package-wide call graph over multiverso_tpu.

Generalizes (and hoists) the device-dispatch pass's jit-seed closure
into a real name-resolved call graph the reachability passes share:

* **Class table** — every ``class`` in the package, its base names,
  its methods, and literal class attributes (``ROLE = DISPATCH``);
  method lookup walks the MRO by name and subclass sets are
  enumerable (the virtual ``self._main`` binding: ``Actor.start``
  spawns ``target=self._main``, and the role depends on which
  subclass the receiver is).
* **Type inference, deliberately shallow** — ``self._x = Cls(...)``
  assignments in any method, local ``x = Cls(...)``, parameter and
  return annotations naming package classes. When a receiver's class
  is KNOWN the method resolves in that class only; when unknown, a
  restricted fallback resolves by method name across the package
  *only if* at most :data:`FALLBACK_CLASS_LIMIT` classes define it —
  more would be guessing, and a lint must err toward silence
  (runtime witnesses backstop what the static side skips).
* **Edges** — plain calls, ``self.m()`` via the binding's MRO,
  ``mod.f()`` via the import map, class instantiation (an edge to
  ``__init__``), ``functools.partial(f, ...)`` (an edge to ``f``),
  and ``threading.Thread(target=...)`` *spawn* references — exposed
  via :meth:`CallGraph.resolve_callable` but NOT treated as
  same-thread call edges (the spawned body runs on another thread).
* **Bounded closure** — :meth:`CallGraph.reachable_calls` walks the
  graph depth-first from an entry function carrying the class
  binding, bounded by ``depth`` and a visited set, yielding every
  call site with the path that reached it (violation messages print
  the chain — an interprocedural finding is useless without it).

Everything here is pure ``ast``: the package is parsed, never
imported (the literal-registry principle all mvlint passes follow).
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

#: Unknown-receiver fallback: resolve a method name globally only when
#: at most this many classes define it (err toward silence past that).
FALLBACK_CLASS_LIMIT = 3

#: Default bound on the depth-first closure.
DEPTH_LIMIT = 16


def _ann_name(node: Optional[ast.AST]) -> Optional[str]:
    """The terminal class name an annotation spells, if any:
    ``_ShmPeerWriter``, ``"_ShmPeerWriter"``, ``Optional[_ShmPeerWriter]``."""
    if node is None:
        return None
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value.strip().split("[")[0].split(".")[-1] or None
    if isinstance(node, ast.Subscript):
        # Optional[T] / List[T]: the payload is the interesting part.
        return _ann_name(node.slice)
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


@dataclasses.dataclass
class FuncInfo:
    """One function/method definition."""
    key: str                      # "<rel>::<qualname>"
    rel: str                      # module path relative to repo root
    qual: str                     # dotted qualname within the module
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    cls: Optional[str] = None     # enclosing class name, if a method

    @property
    def name(self) -> str:
        return self.qual.rsplit(".", 1)[-1]


@dataclasses.dataclass
class ClassInfo:
    name: str
    rel: str
    bases: List[str]
    methods: Dict[str, FuncInfo]
    #: literal (constant/Name) class attributes, e.g. ROLE = DISPATCH
    class_attrs: Dict[str, str]


class CallGraph:
    """Package-wide, name-resolved, deliberately conservative."""

    def __init__(self) -> None:
        self.functions: Dict[str, FuncInfo] = {}
        #: class name -> definitions (collisions keep every one)
        self.classes: Dict[str, List[ClassInfo]] = {}
        #: (rel, top-level def name) -> FuncInfo
        self.module_funcs: Dict[Tuple[str, str], FuncInfo] = {}
        #: (rel, local name) -> ("class"|"func"|"module", target)
        self.imports: Dict[Tuple[str, str], Tuple[str, str]] = {}
        #: module alias -> rel of the package module it names
        self._module_rels: Dict[str, str] = {}
        #: (class name, attr) -> class name of the object stored there
        self.attr_types: Dict[Tuple[str, str], str] = {}
        #: rel -> parsed module (the passes re-walk spawn sites)
        self.module_trees: Dict[str, ast.AST] = {}
        #: callee key -> [(caller FuncInfo, call node)]
        self._callers: Optional[Dict[str, List[Tuple[FuncInfo, ast.Call]]]] = None
        self._local_types_cache: Dict[str, Dict[str, str]] = {}

    # ------------------------------------------------------------------
    # construction
    # ------------------------------------------------------------------
    @classmethod
    def build(cls, pkg_root: Path, repo_root: Path) -> "CallGraph":
        graph = cls()
        for path in sorted(pkg_root.rglob("*.py")):
            try:
                source = path.read_text(encoding="utf-8")
                tree = ast.parse(source, filename=str(path))
            except (OSError, SyntaxError):
                continue
            graph.add_module(path.relative_to(repo_root).as_posix(), tree)
        graph.finish()
        return graph

    def add_module(self, rel: str, tree: ast.AST) -> None:
        """Index one module (also used to overlay fixture files)."""
        self.module_trees[rel] = tree
        self._index_imports(rel, tree)
        self._index_defs(rel, tree)

    def finish(self) -> None:
        """Second pass once every class is known: infer self-attr
        types (the RHS class names must resolve first)."""
        for infos in self.classes.values():
            for info in infos:
                for fn in info.methods.values():
                    self._index_attr_types(info, fn)
        self._callers = None
        self._local_types_cache.clear()

    def with_module(self, rel: str, tree: ast.AST) -> "CallGraph":
        """A shallow overlay including one extra module — how the
        passes analyze fixture files without polluting the package
        graph shared across modules."""
        overlay = CallGraph()
        overlay.functions = dict(self.functions)
        overlay.classes = {k: list(v) for k, v in self.classes.items()}
        overlay.module_funcs = dict(self.module_funcs)
        overlay.imports = dict(self.imports)
        overlay._module_rels = dict(self._module_rels)
        overlay.attr_types = dict(self.attr_types)
        overlay.module_trees = dict(self.module_trees)
        overlay.add_module(rel, tree)
        overlay.finish()
        return overlay

    def _index_imports(self, rel: str, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.imports[(rel, local)] = ("name", alias.name)
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    self.imports[(rel, local)] = \
                        ("module", alias.name)

    def _index_defs(self, rel: str, tree: ast.AST) -> None:
        def visit(node: ast.AST, stack: List[str],
                  cls_stack: List[Optional[ClassInfo]]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    bases = [b for b in
                             (_ann_name(base) for base in child.bases)
                             if b]
                    info = ClassInfo(child.name, rel, bases, {}, {})
                    for stmt in child.body:
                        if isinstance(stmt, ast.Assign) and \
                                isinstance(stmt.value,
                                           (ast.Constant, ast.Name,
                                            ast.Attribute)):
                            if isinstance(stmt.value, ast.Name):
                                value = stmt.value.id
                            elif isinstance(stmt.value, ast.Attribute):
                                value = stmt.value.attr
                            else:
                                value = repr(stmt.value.value)
                            for tgt in stmt.targets:
                                if isinstance(tgt, ast.Name):
                                    info.class_attrs[tgt.id] = value
                    self.classes.setdefault(child.name, []).append(info)
                    visit(child, stack + [child.name],
                          cls_stack + [info])
                elif isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef)):
                    qual = ".".join(stack + [child.name])
                    cls = cls_stack[-1]
                    fn = FuncInfo(f"{rel}::{qual}", rel, qual, child,
                                  cls.name if cls else None)
                    self.functions[fn.key] = fn
                    if cls is not None and len(stack) == 1:
                        cls.methods[child.name] = fn
                    if not stack:
                        self.module_funcs[(rel, child.name)] = fn
                    visit(child, stack + [child.name],
                          cls_stack + [None])
                else:
                    visit(child, stack, cls_stack)

        visit(tree, [], [None])

    def _index_attr_types(self, info: ClassInfo, fn: FuncInfo) -> None:
        for node in ast.walk(fn.node):
            target = None
            value = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                target, value = node.target, node.value
                ann = _ann_name(node.annotation)
                if ann and ann in self.classes and \
                        isinstance(target, ast.Attribute) and \
                        isinstance(target.value, ast.Name) and \
                        target.value.id == "self":
                    self.attr_types[(info.name, target.attr)] = ann
            if not (isinstance(target, ast.Attribute)
                    and isinstance(target.value, ast.Name)
                    and target.value.id == "self" and value is not None):
                continue
            cls_name = self._rhs_class(fn.rel, value)
            if cls_name:
                self.attr_types[(info.name, target.attr)] = cls_name

    def _rhs_class(self, rel: str, value: ast.AST) -> Optional[str]:
        """The class instantiated somewhere in an assignment RHS
        (conditional expressions included — the flag-gated
        pattern is ``X(...) if flag else None``)."""
        for sub in ast.walk(value):
            if isinstance(sub, ast.Call):
                name = _ann_name(sub.func) if isinstance(
                    sub.func, (ast.Name, ast.Attribute)) else None
                if name and self._class_named(rel, name):
                    return name
        return None

    # ------------------------------------------------------------------
    # lookup
    # ------------------------------------------------------------------
    def _class_named(self, rel: str,
                     name: str) -> Optional[ClassInfo]:
        infos = self.classes.get(name)
        if not infos:
            target = self.imports.get((rel, name))
            if target and target[0] == "name":
                infos = self.classes.get(target[1].split(".")[-1])
        if not infos:
            return None
        for info in infos:
            if info.rel == rel:
                return info
        return infos[0]

    def mro(self, cls_name: str,
            rel: Optional[str] = None) -> List[ClassInfo]:
        """Linearized-by-name base walk (good enough for this
        package's single-inheritance classes)."""
        out: List[ClassInfo] = []
        seen: Set[str] = set()

        def walk(name: str, at: Optional[str]) -> None:
            if name in seen:
                return
            seen.add(name)
            info = self._class_named(at or "", name)
            if info is None:
                return
            out.append(info)
            for base in info.bases:
                walk(base, info.rel)

        walk(cls_name, rel)
        return out

    def lookup_method(self, cls_name: str, method: str,
                      rel: Optional[str] = None) -> Optional[FuncInfo]:
        for info in self.mro(cls_name, rel):
            fn = info.methods.get(method)
            if fn is not None:
                return fn
        return None

    def class_attr(self, cls_name: str, attr: str,
                   rel: Optional[str] = None) -> Optional[str]:
        for info in self.mro(cls_name, rel):
            if attr in info.class_attrs:
                return info.class_attrs[attr]
        return None

    def subclasses(self, cls_name: str) -> List[ClassInfo]:
        """``cls_name`` plus every transitive subclass in the package."""
        out: List[ClassInfo] = []
        names = {cls_name}
        changed = True
        while changed:
            changed = False
            for infos in self.classes.values():
                for info in infos:
                    if info.name in names:
                        continue
                    if any(base in names for base in info.bases):
                        names.add(info.name)
                        changed = True
        for name in names:
            out.extend(self.classes.get(name, []))
        return sorted(out, key=lambda i: (i.rel, i.name))

    # ------------------------------------------------------------------
    # resolution
    # ------------------------------------------------------------------
    def _local_types(self, fn: FuncInfo) -> Dict[str, str]:
        """Parameter annotations + ``x = Cls(...)`` locals +
        call-returns whose callee annotates a package class."""
        cached = self._local_types_cache.get(fn.key)
        if cached is not None:
            return cached
        types: Dict[str, str] = {}
        self._local_types_cache[fn.key] = types
        args = fn.node.args
        for arg in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            ann = _ann_name(arg.annotation)
            if ann and self._class_named(fn.rel, ann):
                types[arg.arg] = ann
        for node in ast.walk(fn.node):
            if not (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                continue
            name = node.targets[0].id
            cls = self._rhs_class(fn.rel, node.value)
            if cls:
                types[name] = cls
                continue
            if isinstance(node.value, ast.Call):
                for target, _ in self._resolve(node.value.func, fn,
                                               None, as_call=True):
                    ret = _ann_name(getattr(target.node, "returns",
                                            None))
                    if ret and self._class_named(target.rel, ret):
                        types[name] = ret
                        break
        return types

    def resolve_call(self, call: ast.Call, fn: FuncInfo,
                     binding: Optional[str]) -> List[Tuple[FuncInfo,
                                                           Optional[str]]]:
        """Resolve a call site to (callee, callee class binding)
        pairs. ``binding`` is the concrete class ``self`` is bound to
        in ``fn`` (for virtual methods: ``Actor._main`` walked with
        binding ``Communicator`` resolves ``self._dispatch`` to the
        override)."""
        return self._resolve(call.func, fn, binding, as_call=True)

    def resolve_callable(self, expr: ast.AST, fn: FuncInfo,
                         binding: Optional[str]) -> List[Tuple[
                             FuncInfo, Optional[str]]]:
        """Resolve a callable *reference* (Thread target, partial
        payload, callback argument) without calling it."""
        if isinstance(expr, ast.Call):
            # functools.partial(f, ...) used as the callable.
            name = expr.func.attr if isinstance(expr.func, ast.Attribute) \
                else (expr.func.id if isinstance(expr.func, ast.Name)
                      else None)
            if name == "partial" and expr.args:
                return self.resolve_callable(expr.args[0], fn, binding)
            return []
        return self._resolve(expr, fn, binding, as_call=False)

    def _resolve(self, func: ast.AST, fn: FuncInfo,
                 binding: Optional[str],
                 as_call: bool) -> List[Tuple[FuncInfo, Optional[str]]]:
        if isinstance(func, ast.Name):
            return self._resolve_name(func.id, fn, binding, as_call)
        if isinstance(func, ast.Attribute):
            return self._resolve_attr(func, fn, binding, as_call)
        return []

    def _resolve_name(self, name: str, fn: FuncInfo,
                      binding: Optional[str],
                      as_call: bool) -> List[Tuple[FuncInfo,
                                                   Optional[str]]]:
        # Nested def in the enclosing function's scope chain.
        parts = fn.qual.split(".")
        for depth in range(len(parts), 0, -1):
            key = f"{fn.rel}::{'.'.join(parts[:depth] + [name])}"
            nested = self.functions.get(key)
            if nested is not None:
                return [(nested, binding)]
        top = self.module_funcs.get((fn.rel, name))
        if top is not None:
            return [(top, None)]
        info = self._class_named(fn.rel, name)
        if info is not None:
            if not as_call:
                return []
            init = self.lookup_method(info.name, "__init__", info.rel)
            return [(init, info.name)] if init else []
        target = self.imports.get((fn.rel, name))
        if target and target[0] == "name":
            leaf = target[1].split(".")[-1]
            for (rel, fname), other in self.module_funcs.items():
                if fname == leaf and rel != fn.rel:
                    return [(other, None)]
        return []

    def _resolve_attr(self, func: ast.Attribute, fn: FuncInfo,
                      binding: Optional[str],
                      as_call: bool) -> List[Tuple[FuncInfo,
                                                   Optional[str]]]:
        method = func.attr
        recv = func.value
        # self.m() / self.attr.m()
        if isinstance(recv, ast.Name):
            if recv.id == "self" and fn.cls is not None:
                cls = binding or fn.cls
                target = self.lookup_method(cls, method, fn.rel)
                if target is not None:
                    return [(target, cls)]
                return self._fallback(method)
            local = self._local_types(fn).get(recv.id)
            if local:
                target = self.lookup_method(local, method, fn.rel)
                return [(target, local)] if target else []
            imported = self.imports.get((fn.rel, recv.id))
            if imported and imported[0] == "module":
                leaf = imported[1].split(".")[-1]
                for (rel, fname), other in self.module_funcs.items():
                    if fname == method and \
                            rel.endswith(f"/{leaf}.py"):
                        return [(other, None)]
                return []
            return self._fallback(method)
        if isinstance(recv, ast.Attribute) and \
                isinstance(recv.value, ast.Name) and \
                recv.value.id == "self" and fn.cls is not None:
            holder = binding or fn.cls
            for info in self.mro(holder, fn.rel):
                typed = self.attr_types.get((info.name, recv.attr))
                if typed:
                    target = self.lookup_method(typed, method, fn.rel)
                    return [(target, typed)] if target else []
        return self._fallback(method)

    #: Method names shared with builtin containers/IO: an unknown
    #: receiver bearing one is far more likely a dict/list/socket
    #: than a package class — resolving would fabricate edges.
    _BUILTIN_LIKE = frozenset({
        "get", "pop", "push", "append", "add", "clear", "update",
        "copy", "items", "keys", "values", "extend", "remove",
        "discard", "insert", "close", "join", "start", "sort",
        "count", "index", "put", "send", "recv", "read", "write",
        "flush", "stop",
    })

    def _fallback(self, method: str) -> List[Tuple[FuncInfo,
                                                   Optional[str]]]:
        """Unknown receiver: resolve by method name package-wide only
        when few classes define it (err toward silence)."""
        if method.startswith("__") or method in self._BUILTIN_LIKE:
            return []
        owners = [info for infos in self.classes.values()
                  for info in infos if method in info.methods]
        if not owners or len(owners) > FALLBACK_CLASS_LIMIT:
            return []
        return [(info.methods[method], info.name) for info in owners]

    # ------------------------------------------------------------------
    # closure
    # ------------------------------------------------------------------
    def reachable_calls(self, fn: FuncInfo, binding: Optional[str],
                        depth: int = DEPTH_LIMIT,
                        prune=None) -> Iterator[Tuple[FuncInfo,
                                                      ast.Call,
                                                      Tuple[str, ...]]]:
        """Depth-first closure from ``fn``: yields every reachable
        call site as (enclosing function, call node, path of function
        keys from the entry). ``prune(func, call)`` returning True
        stops traversal INTO that call's resolutions (but the site is
        still yielded first) — pass 9 prunes at blocking primitives
        so transport internals below a finding stay quiet."""
        visited: Set[Tuple[str, Optional[str]]] = set()

        def walk(cur: FuncInfo, bound: Optional[str],
                 path: Tuple[str, ...],
                 budget: int) -> Iterator[Tuple[FuncInfo, ast.Call,
                                                Tuple[str, ...]]]:
            if budget <= 0 or (cur.key, bound) in visited:
                return
            visited.add((cur.key, bound))
            here = path + (cur.key,)
            for call in self._calls_in(cur):
                yield cur, call, here
                if prune is not None and prune(cur, call):
                    continue
                if self._spawns_thread(call):
                    continue  # runs on another thread, not this path
                for callee, callee_bound in self.resolve_call(
                        call, cur, bound):
                    yield from walk(callee, callee_bound, here,
                                    budget - 1)

        yield from walk(fn, binding, (), depth)

    def _calls_in(self, fn: FuncInfo) -> List[ast.Call]:
        """Call sites lexically inside ``fn`` but not inside a nested
        def (those run when the nested function does)."""
        out: List[ast.Call] = []

        def visit(node: ast.AST) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                if isinstance(child, ast.Call):
                    out.append(child)
                visit(child)

        visit(fn.node)
        return out

    @staticmethod
    def _spawns_thread(call: ast.Call) -> bool:
        name = call.func.attr if isinstance(call.func, ast.Attribute) \
            else (call.func.id if isinstance(call.func, ast.Name)
                  else None)
        return name in ("Thread", "spawn") and \
            any(kw.arg == "target" for kw in call.keywords)

    def callers_of(self, key: str) -> List[Tuple[FuncInfo, ast.Call]]:
        """Reverse edges: every call site in the graph that resolves
        to ``key`` (used by the guarded-by caller-holds analysis)."""
        if self._callers is None:
            self._callers = {}
            for fn in list(self.functions.values()):
                for call in self._calls_in(fn):
                    for callee, _ in self.resolve_call(call, fn, None):
                        self._callers.setdefault(
                            callee.key, []).append((fn, call))
        return self._callers.get(key, [])
