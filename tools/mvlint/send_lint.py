"""send-discipline lint: blocking ``net.send`` stays in the transport.

The dispatch-thread-starvation class bit THREE separate times (PR 6
heartbeats twice, PR 9 metrics; ROADMAP "Recurring theme"): anything
that routes a liveness/control frame through a path that can BLOCK —
the communicator's single dispatch thread parked in a
``-connect_timeout_s`` connect-retry toward a dead peer, or a direct
blocking ``net.send`` doing the same — starves the frame past
``-heartbeat_timeout_s`` and the controller declares a perfectly
healthy rank dead. The fix is always the same: liveness/control frames
ride non-blocking ``send_async`` (per-destination writer threads).
This pass enforces it statically so shard-map broadcasts, heartbeats
and their successors can never reintroduce the class:

* a call whose callee is ``<chain>.send(...)`` where the chain ends in
  a ``net``/``_net`` attribute or name (``self._zoo.net.send``,
  ``zoo.net.send``, ``self._net.send``, ``net.send``) is banned
  OUTSIDE the allowlisted transport/engine modules — everything else
  must use ``send_async`` (or route through the communicator actor,
  whose mailbox push never blocks);
* the transport layer itself (``runtime/net.py``, ``runtime/tcp.py``),
  the communicator's single outbound tail
  (``runtime/communicator.py``), the allreduce engine's collective
  data plane (``runtime/allreduce_engine.py``) and test code are
  allowlisted — those are the sites where a blocking send is the
  deliberate backpressure, not an accident;
* ``x.send_async(...)`` and unrelated ``send`` methods (socket
  ``sendall``, generator ``send`` on a non-net chain) are not matched.
"""

from __future__ import annotations

import ast
from typing import Iterator

from .framework import LintPass, ModuleInfo, Violation

#: Modules where a blocking net send is the transport's own business.
ALLOWED_SUFFIXES = (
    "multiverso_tpu/runtime/net.py",
    "multiverso_tpu/runtime/tcp.py",
    "multiverso_tpu/runtime/shm.py",
    "multiverso_tpu/runtime/communicator.py",
    "multiverso_tpu/runtime/allreduce_engine.py",
)

ALLOWED_PREFIXES = ("tests/",)

NET_NAMES = {"net", "_net"}


def _chain_tail(node: ast.AST):
    """The attribute/name the ``.send`` receiver ends in: for
    ``self._zoo.net.send`` the receiver is ``self._zoo.net`` and the
    tail is ``net``."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


class SendDisciplineLint(LintPass):
    name = "send-discipline"

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        rel = module.rel
        if rel.endswith(ALLOWED_SUFFIXES) or \
                any(rel.startswith(p) for p in ALLOWED_PREFIXES):
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (isinstance(fn, ast.Attribute) and fn.attr == "send"):
                continue
            tail = _chain_tail(fn.value)
            if tail not in NET_NAMES:
                continue
            yield Violation(
                rel, node.lineno, node.col_offset, self.name,
                "blocking net.send() outside the transport layer: "
                "liveness/control frames must ride send_async (the "
                "dispatch-thread-starvation class, PR-6/PR-9 — "
                "docs/STATIC_ANALYSIS.md) or route through the "
                "communicator actor")
