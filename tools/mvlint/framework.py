"""mvlint framework: module model, pragma handling, pass protocol, runner.

The project-invariant static analyzer for the actor/PS runtime. Each
pass is an AST visitor over one :class:`ModuleInfo`; the runner walks
the requested paths, applies every pass, filters pragma-suppressed
findings, and renders ``path:line:col: [pass] message`` diagnostics.

Pragma syntax (honored on the violating line, or — for whole-function
scope — on the ``def``/``class`` line enclosing it):

    something_flagged()  # mvlint: ignore[pass-name]
    def traced_kernel(x):  # mvlint: ignore[device-dispatch]

Several passes separate with commas: ``# mvlint: ignore[a,b]``.
Suppressions are counted and shown in the summary — an ignore is an
annotated exception, not an invisible one.
"""

from __future__ import annotations

import ast
import dataclasses
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

PRAGMA_RE = re.compile(r"#\s*mvlint:\s*ignore\[([a-z0-9_,\s-]+)\]")


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str
    line: int
    col: int
    pass_name: str
    message: str

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.pass_name}] {self.message}")


class ModuleInfo:
    """One parsed source file plus its pragma map."""

    def __init__(self, path: Path, root: Path):
        self.path = path
        self.rel = path.relative_to(root).as_posix() \
            if path.is_relative_to(root) else path.as_posix()
        self.source = path.read_text(encoding="utf-8")
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=str(path))
        #: line -> set of pass names suppressed there ('*' = all)
        self.pragmas: Dict[int, Set[str]] = {}
        self._collect_pragmas()
        #: line ranges suppressed per pass via a pragma on a def/class
        #: line: pass -> list of (first_line, last_line, pragma_line)
        self.pragma_spans: Dict[str, List[tuple]] = {}
        self._collect_spans()

    def _collect_pragmas(self) -> None:
        # tokenize, not regex-over-lines: '# mvlint: ignore[...]' inside
        # a string literal must not become a live pragma.
        try:
            tokens = tokenize.generate_tokens(
                iter(self.source.splitlines(keepends=True)).__next__)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                m = PRAGMA_RE.search(tok.string)
                if m:
                    names = {p.strip() for p in m.group(1).split(",")
                             if p.strip()}
                    self.pragmas.setdefault(
                        tok.start[0], set()).update(names)
        except tokenize.TokenError:
            pass

    def _collect_spans(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.ClassDef)):
                continue
            names = self.pragmas.get(node.lineno, set())
            if not names:
                continue
            span = (node.lineno, node.end_lineno or node.lineno,
                    node.lineno)
            for name in names:
                self.pragma_spans.setdefault(name, []).append(span)

    def matching_pragmas(self, violation: Violation) -> List[tuple]:
        """Every pragma entry — as (pragma line, pass name) — that
        suppresses ``violation`` (used-pragma accounting for
        ``--report-unused-pragmas``)."""
        hits: List[tuple] = []
        names = self.pragmas.get(violation.line, set())
        for name in (violation.pass_name, "*"):
            if name in names:
                hits.append((violation.line, name))
        for name in (violation.pass_name, "*"):
            for lo, hi, origin in self.pragma_spans.get(name, []):
                if lo <= violation.line <= hi:
                    hits.append((origin, name))
        return hits

    def suppressed(self, violation: Violation) -> bool:
        return bool(self.matching_pragmas(violation))

    def segment(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.source, node) or ""


class LintPass:
    """Base: subclass, set ``name``, implement ``check``."""

    name = "base"

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        raise NotImplementedError

    # Tree-wide hook: runs once after every module was scanned, for
    # cross-file facts (dead flags). Returns informational lines.
    def tree_report(self) -> List[str]:
        return []


def walk_paths(paths: Sequence[str], root: Path) -> List[Path]:
    files: List[Path] = []
    for raw in paths:
        p = (root / raw) if not Path(raw).is_absolute() else Path(raw)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py" and p.is_file():
            files.append(p)
        else:
            # A missing/non-.py path must be a hard error: silently
            # skipping it would let the CI gate pass VACUOUSLY (zero
            # files scanned -> zero violations) after a rename.
            raise FileNotFoundError(
                f"mvlint: {raw!r} is neither a directory nor an "
                f"existing .py file (resolved to {p})")
    return files


@dataclasses.dataclass
class RunResult:
    violations: List[Violation]
    suppressed: List[Violation]
    per_pass: Dict[str, int]
    per_pass_suppressed: Dict[str, int]
    info: List[str]
    files_scanned: int
    #: (rel path, line, pass name) of every pragma that suppressed
    #: ZERO findings this run — stale suppressions are drift.
    unused_pragmas: List[tuple] = dataclasses.field(
        default_factory=list)

    @property
    def failed(self) -> bool:
        return bool(self.violations)


def run_passes(passes: Iterable[LintPass], paths: Sequence[str],
               root: Path) -> RunResult:
    passes = list(passes)
    files = walk_paths(paths, root)
    violations: List[Violation] = []
    suppressed: List[Violation] = []
    per_pass = {p.name: 0 for p in passes}
    per_sup = {p.name: 0 for p in passes}
    scanned = 0
    all_pragmas: Set[tuple] = set()
    used_pragmas: Set[tuple] = set()
    for path in files:
        try:
            module = ModuleInfo(path, root)
        except SyntaxError as exc:
            violations.append(Violation(
                str(path), exc.lineno or 0, exc.offset or 0, "parse",
                f"syntax error: {exc.msg}"))
            per_pass["parse"] = per_pass.get("parse", 0) + 1
            continue
        scanned += 1
        for line, names in module.pragmas.items():
            for name in names:
                all_pragmas.add((module.rel, line, name))
        for lint in passes:
            for v in lint.check(module):
                # A pass may report against ANOTHER file (the wire-slot
                # doc cross-check); only this module's own pragmas may
                # suppress its own findings.
                if v.path == module.rel and module.suppressed(v):
                    suppressed.append(v)
                    per_sup[lint.name] += 1
                    for line, name in module.matching_pragmas(v):
                        used_pragmas.add((module.rel, line, name))
                else:
                    violations.append(v)
                    per_pass[lint.name] += 1
    info: List[str] = []
    for lint in passes:
        info.extend(lint.tree_report())
    violations.sort(key=lambda v: (v.path, v.line, v.col))
    return RunResult(violations, suppressed, per_pass, per_sup,
                     info, scanned,
                     unused_pragmas=sorted(all_pragmas - used_pragmas))
