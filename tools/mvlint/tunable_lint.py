"""tunable-lint: the live-retunable flag registry is closed and wired.

Source of truth: the ``TUNABLE_FLAGS`` literal in
``multiverso_tpu/util/configure.py`` and the ``AUTOTUNE_POLICIES``
literal in ``multiverso_tpu/runtime/autotune.py`` (both parsed, never
imported). Checked:

* every ``TUNABLE_FLAGS`` entry must name a ``CANONICAL_FLAGS`` flag —
  a tunable that is not canonical could be broadcast but never parsed
  or linted anywhere else;
* every ``TUNABLE_FLAGS`` entry must have at least one
  ``register_tunable_hook("name", ...)`` call site in the runtime tree
  (pre-scanned at pass construction) — a tunable with no apply hook is
  the exact bug the dynamic-flag layer exists to prevent: the
  broadcast lands in the flag registry while the hot path keeps its
  construction-time copy. Reported against configure.py;
* every ``register_tunable_hook`` call with a literal name must name a
  ``TUNABLE_FLAGS`` entry (per scanned file — a typo'd registration
  raises at import time in production, but fixtures and dead code
  paths must fail in CI too);
* every ``AUTOTUNE_POLICIES`` key must be a ``TUNABLE_FLAGS`` entry,
  and every policy's ``metrics`` input must name a canonical metric
  (``util/dashboard.py METRIC_NAMES``, trailing-``*`` families
  honored via ``metric_lint.family_match``) — a policy steering on a
  typo'd signal silently holds forever.

Non-literal names are skipped, the same contract as flag-lint.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Set

from .framework import LintPass, ModuleInfo, Violation
from .metric_lint import family_match

HOOK_FN = "register_tunable_hook"


def _load_dict_literal(path: Path, name: str) -> Dict[str, Any]:
    tree = ast.parse(path.read_text(encoding="utf-8"))
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for target in targets:
            if isinstance(target, ast.Name) and target.id == name:
                value = ast.literal_eval(node.value)
                if isinstance(value, dict):
                    return value
    raise RuntimeError(f"no {name} dict literal in {path}")


def load_tunable_flags(configure_path: Path) -> Dict[str, str]:
    """The TUNABLE_FLAGS literal, by AST parse of configure.py."""
    return _load_dict_literal(configure_path, "TUNABLE_FLAGS")


def load_autotune_policies(autotune_path: Path) -> Dict[str, dict]:
    """The AUTOTUNE_POLICIES literal, by AST parse of autotune.py."""
    return _load_dict_literal(autotune_path, "AUTOTUNE_POLICIES")


def _literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def scan_hook_sites(tree_root: Path) -> Set[str]:
    """Every flag name passed as a literal first argument to
    ``register_tunable_hook`` anywhere under ``tree_root`` — the
    hook-coverage fact the per-registry check needs (a hook may live
    in any layer: tables, serving, runtime, util)."""
    names: Set[str] = set()
    for path in sorted(tree_root.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:
            continue  # the runner reports parse errors itself
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            fn_name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if fn_name != HOOK_FN:
                continue
            name = _literal_str(node.args[0])
            if name is not None:
                names.add(name)
    return names


class TunableLint(LintPass):
    name = "tunable-lint"

    def __init__(self, tunables: Dict[str, str],
                 canonical: Dict[str, Any],
                 metrics: Dict[str, str],
                 policies: Dict[str, dict],
                 hook_sites: Set[str]):
        self.tunables = tunables
        self.canonical = canonical
        self.metrics = metrics
        self.policies = policies
        self.hook_sites = hook_sites

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        if module.path.name == "configure.py" \
                and "util" in module.path.parts:
            yield from self._check_registry(module)
            return  # the registry/hook layer itself defines the API
        if module.path.name == "autotune.py" \
                and "runtime" in module.path.parts:
            yield from self._check_policies(module)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            fn_name = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if fn_name != HOOK_FN:
                continue
            name = _literal_str(node.args[0])
            if name is None or name in self.tunables:
                continue
            import difflib
            close = difflib.get_close_matches(
                name, sorted(self.tunables), n=1)
            hint = f" — did you mean {close[0]!r}?" if close else ""
            yield Violation(
                module.rel, node.lineno, node.col_offset, self.name,
                f"{HOOK_FN}({name!r}): not in TUNABLE_FLAGS "
                f"(util/configure.py) — a hook for a non-tunable flag "
                f"raises at import time{hint}")

    def _check_registry(self, module: ModuleInfo) -> Iterator[Violation]:
        """Registry closure, reported against configure.py: every
        tunable is canonical AND has an apply-hook call site."""
        for name in sorted(self.tunables):
            if name not in self.canonical:
                yield Violation(
                    module.rel, 1, 0, self.name,
                    f"TUNABLE_FLAGS entry {name!r} is not in "
                    f"CANONICAL_FLAGS — a tunable must be a canonical "
                    f"flag first")
            if name not in self.hook_sites:
                yield Violation(
                    module.rel, 1, 0, self.name,
                    f"TUNABLE_FLAGS entry {name!r} has no "
                    f"register_tunable_hook(...) call site in the "
                    f"tree — a broadcast would land in the flag "
                    f"registry while every construction-time copy "
                    f"keeps the old value (docs/AUTOTUNE.md)")

    def _check_policies(self, module: ModuleInfo) -> Iterator[Violation]:
        for knob in sorted(self.policies):
            if knob not in self.tunables:
                yield Violation(
                    module.rel, 1, 0, self.name,
                    f"AUTOTUNE_POLICIES key {knob!r} is not in "
                    f"TUNABLE_FLAGS (util/configure.py) — the "
                    f"controller would broadcast a flag every rank "
                    f"rejects")
            policy = self.policies[knob]
            for metric in policy.get("metrics", ()):
                if family_match(metric, self.metrics):
                    continue
                import difflib
                close = difflib.get_close_matches(
                    metric, sorted(self.metrics), n=1)
                hint = f" — did you mean {close[0]!r}?" if close else ""
                yield Violation(
                    module.rel, 1, 0, self.name,
                    f"AUTOTUNE_POLICIES[{knob!r}] reads metric "
                    f"{metric!r} which is not in the canonical metric "
                    f"registry (util/dashboard.py METRIC_NAMES) — the "
                    f"policy would steer on a signal nobody emits"
                    f"{hint}")

    def tree_report(self) -> List[str]:
        unpolicied = sorted(set(self.tunables) - set(self.policies))
        if not unpolicied:
            return []
        return [f"tunable-lint: tunables without an autotune policy "
                f"(broadcast-able, never moved autonomously): "
                f"{', '.join(unpolicied)}"]
