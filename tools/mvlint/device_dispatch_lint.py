"""device-dispatch lint: multi-zoo-reachable dispatch sites are guarded.

The PR-1 / PR-4 deadlock class: two threads of one process (sibling
virtual ranks, or server-vs-trainer) each dispatch a multi-device XLA
program and wedge the shared CPU execution pool. The mechanical fix is
``runtime/device_lock.py``: every dispatch site serializes on the ONE
process lock while multi-zoo mode is active. This pass closes the class
going forward: in multi-zoo-reachable modules — ``runtime/``,
``tables/``, ``models/*/device_train.py`` — eager dispatch markers must
sit lexically inside an accepted guard context.

Dispatch markers:

* ``jax.device_put(...)``
* eager ``jnp.*(...)`` / ``jax.numpy.*(...)`` calls
* immediate invocation of a fresh jit: ``jax.jit(f)(x)``

Accepted guards (any enclosing ``with`` item):

* ``device_lock.guard()`` (any alias ending in ``.guard()``)
* ``self._lock_for(table)`` — the server's table-scoped guard
* ``_table_lock`` / ``device_lock.TABLE_LOCK`` — the lock object itself
* a local name bound from one of the above in the same function
  (``lock = Server._table_lock if ... else ...; with lock:``)

NOT dispatch (skipped):

* bodies of functions/lambdas passed to ``jax.jit``, of functions
  decorated with a jit, and — by an in-module call-graph closure — of
  every function a traced function calls: traced code executes under
  the *caller's* guard, it does not dispatch at its own lexical site.
  (The closure matches by bare name, which over-approximates toward
  "traced" on collisions — a lint must err toward silence here; the
  runtime lock witness backstops what lexical analysis waves through.)
* ``jax.jit(...)`` itself — building a jitted callable dispatches
  nothing.

Sites guarded one call layer up (e.g. ``ServerTable.process_*`` bodies,
always entered under ``Server._lock_for``) are intentional exceptions:
annotate the ``def`` line with ``# mvlint: ignore[device-dispatch]``
so the contract is visible where the code is.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from .framework import LintPass, ModuleInfo, Violation

SCOPE_MARKERS = ("multiverso_tpu/runtime/", "multiverso_tpu/tables/")
SCOPE_SUFFIX = "device_train.py"
SCOPE_EXCLUDE = ("device_lock.py",)

GUARD_TOKENS = (".guard()", "_lock_for(", "_table_lock", "TABLE_LOCK")


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.numpy.copy' for nested attribute chains, None otherwise."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        dotted = _dotted(node.func)
        if dotted is not None and dotted.split(".")[-1] == "jit":
            return True
        # functools.partial(jax.jit, ...) decorators
        return any(_is_jit_expr(a) for a in node.args)
    dotted = _dotted(node)
    return dotted is not None and dotted.split(".")[-1] == "jit"


def _dispatch_marker(node: ast.Call) -> Optional[str]:
    dotted = _dotted(node.func)
    if dotted is not None:
        if dotted.endswith("jax.device_put") or dotted == "device_put":
            return dotted
        root = dotted.split(".")[0]
        if root == "jnp" or dotted.startswith("jax.numpy."):
            return dotted
    if isinstance(node.func, ast.Call) and _is_jit_expr(node.func):
        return "jax.jit(...)(...)"  # immediate jit invocation
    return None


def _traced_closure(tree: ast.AST) -> Set[str]:
    """Names of functions whose bodies are traced, not eagerly run:
    seeds are jit-decorated defs and names passed to ``*.jit(...)``;
    the closure adds every function a traced function calls (by bare
    name, within this module)."""
    defs: dict = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    seeds: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and any(_is_jit_expr(d) for d in node.decorator_list):
            seeds.add(node.name)
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if dotted is not None and dotted.split(".")[-1] == "jit":
                for arg in node.args:
                    if isinstance(arg, ast.Name) and arg.id in defs:
                        seeds.add(arg.id)
    traced = set()
    frontier = list(seeds)
    while frontier:
        name = frontier.pop()
        if name in traced:
            continue
        traced.add(name)
        for fn in defs.get(name, ()):
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    callee = None
                    if isinstance(sub.func, ast.Name):
                        callee = sub.func.id
                    elif isinstance(sub.func, ast.Attribute):
                        callee = sub.func.attr
                    if callee in defs and callee not in traced:
                        frontier.append(callee)
    return traced


class DeviceDispatchLint(LintPass):
    name = "device-dispatch"

    def __init__(self) -> None:
        self._traced: Set[str] = set()

    def in_scope(self, module: ModuleInfo) -> bool:
        rel = module.rel
        if any(rel.endswith(x) for x in SCOPE_EXCLUDE):
            return False
        return any(m in rel for m in SCOPE_MARKERS) \
            or rel.endswith(SCOPE_SUFFIX)

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        if not self.in_scope(module):
            return
        self._traced = _traced_closure(module.tree)
        yield from self._visit(module, module.tree, guarded=False,
                               func=None)

    def _visit(self, module: ModuleInfo, node: ast.AST, guarded: bool,
               func: Optional[ast.AST]) -> Iterator[Violation]:
        for child in ast.iter_child_nodes(node):
            yield from self._visit_one(module, child, guarded, func)

    def _visit_one(self, module: ModuleInfo, node: ast.AST,
                   guarded: bool,
                   func: Optional[ast.AST]) -> Iterator[Violation]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(_is_jit_expr(d) for d in node.decorator_list) \
                    or node.name in self._traced:
                return  # traced code: dispatched under the caller's guard
            yield from self._visit(module, node, guarded=False,
                                   func=node)
            return
        if isinstance(node, ast.Lambda):
            yield from self._visit(module, node, guarded, func)
            return
        if isinstance(node, ast.With):
            item_guard = guarded or any(
                self._is_guard(module, item.context_expr, func)
                for item in node.items)
            for item in node.items:
                yield from self._visit_one(module, item.context_expr,
                                           guarded, func)
            for stmt in node.body:
                yield from self._visit_one(module, stmt, item_guard,
                                           func)
            return
        if isinstance(node, ast.Call):
            dotted = _dotted(node.func)
            if dotted is not None and dotted.split(".")[-1] == "jit":
                # jax.jit(f) / jax.jit(lambda ...): creation only, and
                # the argument body is traced code — skip it entirely.
                return
            marker = _dispatch_marker(node)
            if marker is not None and not guarded:
                yield Violation(
                    module.rel, node.lineno, node.col_offset, self.name,
                    f"unguarded device dispatch {marker}(...) in a "
                    f"multi-zoo-reachable module — wrap the site in "
                    f"'with device_lock.guard():' (+ settle) or pragma "
                    f"the enclosing def if the caller holds the lock")
            yield from self._visit(module, node, guarded, func)
            return
        yield from self._visit(module, node, guarded, func)

    def _is_guard(self, module: ModuleInfo, expr: ast.AST,
                  func: Optional[ast.AST]) -> bool:
        segment = module.segment(expr)
        if any(tok in segment for tok in GUARD_TOKENS):
            return True
        if isinstance(expr, ast.Name) and func is not None:
            # 'with lock:' where lock = ..._table_lock... earlier in
            # the same function.
            for sub in ast.walk(func):
                if isinstance(sub, ast.Assign) and sub.value is not None:
                    for target in sub.targets:
                        if isinstance(target, ast.Name) \
                                and target.id == expr.id:
                            rhs = module.segment(sub.value)
                            if any(tok in rhs for tok in GUARD_TOKENS):
                                return True
        return False
