"""flag-lint: every flag access must name a canonical registered flag.

Source of truth: the ``CANONICAL_FLAGS`` literal in
``multiverso_tpu/util/configure.py`` (parsed, never imported). Checked
per scanned file:

* ``get_flag("name"[, default])`` / ``set_flag("name", ...)`` — the
  literal name must be canonical (catches typo'd ``-allreduce_*`` /
  ``-wire_codec_*`` / ``-send_queue_mb`` spellings that today silently
  read the caller's fallback);
* ``get_flag`` literal defaults and ``define_*("name", default)``
  registrations must match the canonical default exactly (default
  drift across call sites);
* non-literal flag names are skipped (dynamic access is rare and is the
  caller's responsibility to pragma if it wants the audit trail).

Tree-wide, the pass also emits a **dead-flag report**: canonical flags
no scanned file ever reads. Informational only — a flag can be consumed
by an unscanned embedding (and ``backup_worker_ratio`` is reserved,
defined-but-unread in the reference too).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Set

from .framework import LintPass, ModuleInfo, Violation

DEFINE_FNS = {"define_int", "define_bool", "define_string",
              "define_double"}
READ_FNS = {"get_flag", "set_flag"}


def load_canonical_flags(configure_path: Path) -> Dict[str, Any]:
    """The CANONICAL_FLAGS literal, by AST parse of configure.py."""
    tree = ast.parse(configure_path.read_text(encoding="utf-8"))
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for target in targets:
            if isinstance(target, ast.Name) \
                    and target.id == "CANONICAL_FLAGS":
                value = ast.literal_eval(node.value)
                if not isinstance(value, dict):
                    break
                return value
    raise RuntimeError(
        f"no CANONICAL_FLAGS dict literal in {configure_path}")


def _literal_str(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _call_name(node: ast.Call) -> Optional[str]:
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


class FlagLint(LintPass):
    name = "flag-lint"

    def __init__(self, canonical: Dict[str, Any]):
        self.canonical = canonical
        self.read_anywhere: Set[str] = set()

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        if module.path.name == "configure.py" \
                and "util" in module.path.parts:
            return  # the registry itself
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = _call_name(node)
            if fn in READ_FNS:
                yield from self._check_read(module, node, fn)
            elif fn in DEFINE_FNS:
                yield from self._check_define(module, node, fn)

    def _check_read(self, module: ModuleInfo, node: ast.Call,
                    fn: str) -> Iterator[Violation]:
        if not node.args:
            return
        name = _literal_str(node.args[0])
        if name is None:
            return  # dynamic name: out of scope
        self.read_anywhere.add(name)
        if name not in self.canonical:
            yield self._unknown(module, node, fn, name)
            return
        if fn == "get_flag" and len(node.args) > 1:
            default = node.args[1]
            if isinstance(default, ast.Constant) \
                    and not _matches(default.value,
                                     self.canonical[name]):
                yield Violation(
                    module.rel, node.lineno, node.col_offset, self.name,
                    f"get_flag({name!r}) falls back to "
                    f"{default.value!r} but the canonical default is "
                    f"{self.canonical[name]!r} (util/configure.py "
                    f"CANONICAL_FLAGS) — default drift")

    def _check_define(self, module: ModuleInfo, node: ast.Call,
                      fn: str) -> Iterator[Violation]:
        if not node.args:
            return
        name = _literal_str(node.args[0])
        if name is None:
            return
        if name not in self.canonical:
            yield self._unknown(module, node, fn, name)
            return
        if len(node.args) > 1:
            default = node.args[1]
            try:
                value = ast.literal_eval(default)
            except ValueError:
                return  # computed default: runtime drift check covers it
            if not _matches(value, self.canonical[name]):
                yield Violation(
                    module.rel, node.lineno, node.col_offset, self.name,
                    f"{fn}({name!r}, {value!r}) drifts from the "
                    f"canonical default {self.canonical[name]!r} "
                    f"(util/configure.py CANONICAL_FLAGS)")

    def _unknown(self, module: ModuleInfo, node: ast.Call, fn: str,
                 name: str) -> Violation:
        import difflib
        close = difflib.get_close_matches(name, sorted(self.canonical),
                                          n=1)
        hint = f" — did you mean {close[0]!r}?" if close else ""
        return Violation(
            module.rel, node.lineno, node.col_offset, self.name,
            f"{fn}({name!r}): not in the canonical flag registry "
            f"(util/configure.py CANONICAL_FLAGS){hint}")

    def tree_report(self) -> List[str]:
        dead = sorted(set(self.canonical) - self.read_anywhere)
        if not dead:
            return []
        return [f"flag-lint: dead flags (canonical, never read in the "
                f"scanned tree): {', '.join(dead)}"]


def _matches(site_value: Any, canonical: Any) -> bool:
    """Default equality with type strictness: True != 1, 0 != 0.0 —
    a drifted TYPE changes coercion semantics even when == holds."""
    return site_value == canonical \
        and type(site_value) is type(canonical)
