"""lock-discipline lint: registered locks use ``with``; no blocking
call while one is held.

A module "registers" a lock by assigning the result of
``threading.Lock/RLock/Condition`` or of the witness factories
``named_lock/named_rlock/named_condition`` (``util/lock_witness.py``)
to a name — directly or anywhere inside the RHS (list comprehensions
of per-peer locks count). For every registered name, in that module:

* ``x.acquire(...)`` / ``x.release()`` calls are violations — the
  ``with`` statement is exception-safe, a bare pair is not. Bounded
  acquisition on shutdown paths goes through
  ``lock_witness.acquire_timeout`` (or carries a pragma).
* Inside ``with x:`` bodies, lexically blocking calls are violations:
  ``recv_into``/``accept``/``_read_exact``/``select`` always;
  ``join``/``pop``/``wait`` without a timeout (keyword or first
  positional); ``recv`` without a ``timeout=`` KEYWORD
  (``sock.recv(n)``'s positional is a buffer size — socket deadlines
  come from ``settimeout``); ``wait_for`` without a timeout as keyword
  or SECOND positional (the mandatory predicate is not a timeout); and
  the Queue shapes of ``get`` — bare ``q.get()`` / ``q.get(True)`` —
  while ``d.get(key[, default])`` dict lookups stay clean. EXCEPT
  ``wait``/``wait_for`` on the very lock object being held (a
  condition's own wait releases it). A blocking call under a held lock
  is the raw material of every PS deadlock this repo has shipped.

Nested ``def``/``lambda`` bodies inside a ``with`` are skipped — they
execute later, not under the lock.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional, Set

from .framework import LintPass, ModuleInfo, Violation

LOCK_FACTORIES = {"Lock", "RLock", "Condition",
                  "named_lock", "named_rlock", "named_condition"}
ALWAYS_BLOCKING = {"recv_into", "accept", "_read_exact", "select"}
TIMEOUT_BLOCKING = {"recv", "join", "get", "pop", "wait", "wait_for"}


def _root_name(node: ast.AST) -> Optional[str]:
    """The storage name a lock expression hangs off: ``self._lock`` ->
    '_lock', ``self._out_locks[dst]`` -> '_out_locks', ``LOCK`` ->
    'LOCK'."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


_LOCKISH_NAME = re.compile(r"(lock|locks|mutex|cond|condition)$",
                           re.IGNORECASE)


def _makes_lock(rhs: ast.AST) -> bool:
    for sub in ast.walk(rhs):
        if isinstance(sub, ast.Call):
            fn = sub.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name in LOCK_FACTORIES:
                return True
        elif isinstance(sub, (ast.Attribute, ast.Name)):
            # Aliases of existing locks count too — e.g.
            # ``_table_lock = device_lock.TABLE_LOCK`` — or server.py's
            # critical sections would go entirely unchecked. A
            # lock-ish terminal name is the signal.
            terminal = sub.attr if isinstance(sub, ast.Attribute) \
                else sub.id
            if _LOCKISH_NAME.search(terminal):
                return True
    return False


def _has_timeout(call: ast.Call, method: str) -> bool:
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    if method == "wait_for":
        # wait_for(predicate, timeout): the mandatory predicate is NOT
        # a timeout — a lone positional still blocks unboundedly.
        return len(call.args) >= 2
    if method == "get":
        # '.get' is overwhelmingly dict/cache lookup (non-blocking);
        # only the Queue shapes read as blocking: bare q.get() and
        # q.get(True) (block flag, no timeout).
        if not call.args:
            return False
        return not (len(call.args) == 1
                    and isinstance(call.args[0], ast.Constant)
                    and call.args[0].value is True)
    if method == "recv":
        # socket.recv(n)'s positional is a BUFFER SIZE, not a timeout
        # (socket deadlines come from settimeout); only an explicit
        # timeout= keyword reads as bounded.
        return False
    # pop/wait/join carry the timeout first.
    return bool(call.args)


class LockDisciplineLint(LintPass):
    name = "lock-discipline"

    def check(self, module: ModuleInfo) -> Iterator[Violation]:
        if module.path.name == "lock_witness.py":
            return  # the sanctioned wrapper layer itself
        registered = self._registered_locks(module)
        if not registered:
            return
        yield from self._scan(module, module.tree, registered, held=[])

    def _registered_locks(self, module: ModuleInfo) -> Set[str]:
        names: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and _makes_lock(node.value):
                for target in node.targets:
                    name = _root_name(target)
                    if name:
                        names.add(name)
            elif isinstance(node, ast.AnnAssign) \
                    and node.value is not None \
                    and _makes_lock(node.value):
                name = _root_name(node.target)
                if name:
                    names.add(name)
        return names

    def _scan(self, module: ModuleInfo, node: ast.AST,
              registered: Set[str],
              held: List[str]) -> Iterator[Violation]:
        for child in ast.iter_child_nodes(node):
            yield from self._scan_one(module, child, registered, held)

    def _scan_one(self, module: ModuleInfo, node: ast.AST,
                  registered: Set[str],
                  held: List[str]) -> Iterator[Violation]:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # A def under a with runs later, not under the lock.
            yield from self._scan(module, node, registered, held=[])
            return
        if isinstance(node, ast.With):
            new_held = list(held)
            for item in node.items:
                yield from self._scan_one(module, item.context_expr,
                                          registered, held)
                name = _root_name(item.context_expr)
                if name in registered:
                    new_held.append(
                        module.segment(item.context_expr).strip())
            for stmt in node.body:
                yield from self._scan_one(module, stmt, registered,
                                          new_held)
            return
        if isinstance(node, ast.Call):
            yield from self._check_call(module, node, registered, held)
        yield from self._scan(module, node, registered, held)

    def _check_call(self, module: ModuleInfo, node: ast.Call,
                    registered: Set[str],
                    held: List[str]) -> Iterator[Violation]:
        fn = node.func
        if not isinstance(fn, ast.Attribute):
            return
        method = fn.attr
        receiver = fn.value
        if method in ("acquire", "release"):
            name = _root_name(receiver)
            if name in registered:
                yield Violation(
                    module.rel, node.lineno, node.col_offset, self.name,
                    f"bare .{method}() on registered lock {name!r} — "
                    f"use 'with' (exception-safe) or "
                    f"lock_witness.acquire_timeout for bounded "
                    f"shutdown paths")
            return
        if not held:
            return
        receiver_src = module.segment(receiver).strip()
        if method in ("wait", "wait_for") and receiver_src in held:
            return  # a condition's own wait releases the held lock
        blocking = method in ALWAYS_BLOCKING or (
            method in TIMEOUT_BLOCKING and not _has_timeout(node, method))
        if blocking:
            yield Violation(
                module.rel, node.lineno, node.col_offset, self.name,
                f"blocking call .{method}(...) while holding "
                f"registered lock(s) {', '.join(held)} — a peer that "
                f"needs the lock to make this call return deadlocks "
                f"the process")
