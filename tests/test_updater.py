"""Updater numerics: parity with the reference formulas (SURVEY.md §2.4).

Reference formulas validated against independent numpy implementations:
default add (ref: src/updater/updater.cpp:24-31), sgd
(ref: sgd_updater.h:15-19), momentum (ref: momentum_updater.h:17-26),
adagrad intended semantics (ref: adagrad_updater.h:23-41; see
rules.py docstring for the reference's accumulator bugs we do not clone).
"""

import numpy as np
import pytest

from multiverso_tpu.updater import (AddOption, GetOption, UpdateEngine,
                                    bucket_size, create_rule, pad_rows)
from multiverso_tpu.updater.rules import ADAGRAD_EPS


def make_engine(rule_name, shape, num_workers=2, dtype=np.float32):
    return UpdateEngine(create_rule(rule_name), shape, dtype, num_workers)


class TestOptions:
    def test_add_option_roundtrip(self):
        opt = AddOption(worker_id=3, momentum=0.9, learning_rate=0.05,
                        rho=0.2, lambda_=0.7)
        back = AddOption.from_blob(opt.to_blob())
        assert back.worker_id == 3
        assert back.momentum == pytest.approx(0.9)
        assert back.learning_rate == pytest.approx(0.05)
        assert back.rho == pytest.approx(0.2)
        assert back.lambda_ == pytest.approx(0.7)

    def test_add_option_wire_layout(self):
        # 5 slots x 4 bytes; slot 0 is an int32 (union layout,
        # ref: updater.h:53-69).
        blob = AddOption(worker_id=7).to_blob()
        assert blob.size == 20
        assert int(blob.as_array(np.int32)[0]) == 7

    def test_get_option_roundtrip(self):
        assert GetOption.from_blob(GetOption(5).to_blob()).worker_id == 5


class TestDenseRules:
    def test_default_adds(self):
        eng = make_engine("default", (8,))
        data = np.zeros(8, np.float32)
        out = eng.apply_dense(data, np.arange(8, dtype=np.float32))
        np.testing.assert_allclose(np.asarray(out), np.arange(8))

    def test_sgd_subtracts(self):
        eng = make_engine("sgd", (4,))
        out = eng.apply_dense(np.full(4, 10, np.float32),
                              np.full(4, 3, np.float32))
        np.testing.assert_allclose(np.asarray(out), np.full(4, 7.0))

    def test_momentum_smooths(self):
        eng = make_engine("momentum", (3,))
        opt = AddOption(momentum=0.5)
        data = np.zeros(3, np.float32)
        delta = np.ones(3, np.float32)
        # smooth = .5*0 + .5*1 = .5 ; data = -0.5
        data = eng.apply_dense(data, delta, opt)
        np.testing.assert_allclose(np.asarray(data), -0.5 * np.ones(3))
        # smooth = .5*.5 + .5*1 = .75 ; data = -1.25
        data = eng.apply_dense(data, delta, opt)
        np.testing.assert_allclose(np.asarray(data), -1.25 * np.ones(3))

    def test_adagrad_per_worker_state(self):
        eng = make_engine("adagrad", (2,), num_workers=2)
        opt0 = AddOption(worker_id=0, learning_rate=0.1, rho=0.1)
        data = np.zeros(2, np.float32)
        delta = np.full(2, 0.05, np.float32)
        grad = 0.05 / 0.1
        g_sqr = grad * grad
        expect = -0.1 * grad / np.sqrt(g_sqr + ADAGRAD_EPS)
        data = eng.apply_dense(data, delta, opt0)
        np.testing.assert_allclose(np.asarray(data), np.full(2, expect),
                                   rtol=1e-5)
        # Worker 1 has its own fresh accumulator -> same first step again.
        data2 = eng.apply_dense(np.zeros(2, np.float32), delta,
                                AddOption(worker_id=1, learning_rate=0.1,
                                          rho=0.1))
        np.testing.assert_allclose(np.asarray(data2), np.full(2, expect),
                                   rtol=1e-5)
        # Worker 0 again: accumulator doubled.
        data = eng.apply_dense(np.zeros(2, np.float32), delta, opt0)
        expect2 = -0.1 * grad / np.sqrt(2 * g_sqr + ADAGRAD_EPS)
        np.testing.assert_allclose(np.asarray(data), np.full(2, expect2),
                                   rtol=1e-5)

    def test_int_table_always_default(self):
        rule = create_rule("sgd", dtype=np.int32)
        assert rule.name == "default"  # ref: updater.cpp:42-45


class TestRowRules:
    def test_default_rows_scatter_add(self):
        eng = make_engine("default", (6, 3))
        data = np.zeros((6, 3), np.float32)
        rows = np.array([1, 4], np.int32)
        delta = np.ones((2, 3), np.float32)
        out = np.asarray(eng.apply_rows(data, rows, delta))
        assert out[1].sum() == 3 and out[4].sum() == 3
        assert out.sum() == 6

    def test_duplicate_rows_compound_for_add(self):
        eng = make_engine("default", (4, 2))
        out = np.asarray(eng.apply_rows(
            np.zeros((4, 2), np.float32), np.array([2, 2], np.int32),
            np.ones((2, 2), np.float32)))
        np.testing.assert_allclose(out[2], [2.0, 2.0])

    def test_momentum_rows_tracks_state(self):
        eng = make_engine("momentum", (5, 2))
        opt = AddOption(momentum=0.5)
        rows = np.array([3], np.int32)
        delta = np.ones((1, 2), np.float32)
        data = np.zeros((5, 2), np.float32)
        data = np.asarray(eng.apply_rows(data, rows, delta, opt))
        np.testing.assert_allclose(data[3], [-0.5, -0.5])
        data = np.asarray(eng.apply_rows(data, rows, delta, opt))
        np.testing.assert_allclose(data[3], [-1.25, -1.25])
        assert data[0].sum() == 0  # untouched rows

    def test_padding_rows_are_dropped(self):
        rows, delta = pad_rows(np.array([1], np.int32),
                               np.ones((1, 2), np.float32), num_rows=4)
        assert len(rows) == bucket_size(1)
        assert (rows[1:] == 4).all()  # out-of-range sentinel
        eng = make_engine("default", (4, 2))
        out = np.asarray(eng.apply_rows(np.zeros((4, 2), np.float32),
                                        np.array([1], np.int32),
                                        np.ones((1, 2), np.float32)))
        assert out.sum() == 2  # only the real row landed

    def test_bucket_sizes_bound_recompiles(self):
        assert bucket_size(1) == 8
        assert bucket_size(8) == 8
        assert bucket_size(9) == 16
        assert bucket_size(1000) == 1024


class TestDCASGD:
    """Delay-compensated ASGD (the reference's permanently-disabled
    updater hook, implemented for real — see DCASGDRule)."""

    def test_dense_compensation(self):
        eng = make_engine("dcasgd", (2,), num_workers=2)
        lr, lam = 0.1, 0.04
        opt = AddOption(worker_id=0, learning_rate=lr, lambda_=lam)
        data = np.full(2, 1.0, np.float32)
        delta = np.full(2, 0.05, np.float32)  # = lr * g, g = 0.5
        g = 0.05 / lr
        # First push: backup[0] is zeros -> compensation vs origin.
        expect = 1.0 - (0.05 + lr * lam * g * g * (1.0 - 0.0))
        data = eng.apply_dense(data, delta, opt)
        np.testing.assert_allclose(np.asarray(data), np.full(2, expect),
                                   rtol=1e-6)
        # Second push from the SAME worker: backup == current params, so
        # zero staleness -> plain sgd step.
        prev = float(np.asarray(data)[0])
        data = eng.apply_dense(data, delta, opt)
        np.testing.assert_allclose(np.asarray(data),
                                   np.full(2, prev - 0.05), rtol=1e-6)
        # A push from worker 1 moves params; worker 0's NEXT push now
        # sees nonzero staleness and compensates.
        data = eng.apply_dense(data, delta,
                               AddOption(worker_id=1, learning_rate=lr,
                                         lambda_=lam))
        w = float(np.asarray(data)[0])
        bak0 = prev - 0.05  # worker 0's backup after its second push
        expect = w - (0.05 + lr * lam * g * g * (w - bak0))
        data = eng.apply_dense(data, delta, opt)
        np.testing.assert_allclose(np.asarray(data), np.full(2, expect),
                                   rtol=1e-6)

    def test_rows_match_dense(self):
        lr, lam = 0.2, 0.1
        opt = AddOption(worker_id=0, learning_rate=lr, lambda_=lam)
        dense_eng = make_engine("dcasgd", (4, 3), num_workers=1)
        rows_eng = make_engine("dcasgd", (4, 3), num_workers=1)
        data_d = np.arange(12, dtype=np.float32).reshape(4, 3)
        data_r = data_d.copy()
        full_delta = np.zeros((4, 3), np.float32)
        rows = np.array([1, 3], np.int32)
        full_delta[rows] = 0.06
        data_d = dense_eng.apply_dense(data_d, full_delta, opt)
        data_r = rows_eng.apply_rows(data_r, rows,
                                     np.full((2, 3), 0.06, np.float32),
                                     opt)
        # Untouched rows see zero delta AND zero grad -> identical; the
        # dense path also rewrites its backup for untouched rows, which
        # only matters for later staleness, so compare the data only.
        np.testing.assert_allclose(np.asarray(data_d), np.asarray(data_r),
                                   rtol=1e-6)

    def test_rows_duplicates_compound_like_sgd(self):
        # Duplicate row ids in one Add must compound their deltas (the
        # scatter-add semantics sgd has); the compensation is evaluated
        # once against the pre-update rows.
        lr = 0.1
        opt = AddOption(worker_id=0, learning_rate=lr, lambda_=0.0)
        eng = make_engine("dcasgd", (4, 2), num_workers=1)
        data = np.ones((4, 2), np.float32)
        rows = np.array([3, 3, 3], np.int32)
        delta = np.full((3, 2), 0.05, np.float32)
        data = eng.apply_rows(data, rows, delta, opt)
        # lambda=0 -> pure sgd: three deltas land on row 3.
        np.testing.assert_allclose(np.asarray(data)[3],
                                   np.full(2, 1.0 - 3 * 0.05), rtol=1e-6)

    def test_momentum_sgd_alias(self):
        assert create_rule("momentum_sgd").name == "momentum"
