"""Self-tests for the mvlint interprocedural call graph
(tools/mvlint/callgraph.py) — the core passes 9 and 10 stand on.

Synthetic modules exercise each resolution mechanism in isolation:
virtual method dispatch under a subclass binding, self-attribute type
inference, Thread/spawn target edges (references resolved, but never
walked as same-thread control flow), functools.partial payloads, and
the recursion/depth bounds that keep the closure finite.
"""

from __future__ import annotations

import ast

from tools.mvlint.callgraph import DEPTH_LIMIT, CallGraph

REL = "multiverso_tpu/mod.py"


def _graph(source: str, rel: str = REL) -> CallGraph:
    graph = CallGraph()
    graph.add_module(rel, ast.parse(source))
    graph.finish()
    return graph


def _calls(graph: CallGraph, fn) -> list:
    return graph._calls_in(fn)


class TestMethodResolution:
    SRC = (
        "class Base:\n"
        "    def run(self):\n"
        "        self.step()\n"
        "    def step(self):\n"
        "        helper()\n"
        "class Child(Base):\n"
        "    def step(self):\n"
        "        other()\n"
        "def helper():\n"
        "    pass\n"
        "def other():\n"
        "    pass\n")

    def test_self_call_resolves_through_mro(self):
        graph = _graph(self.SRC)
        fn = graph.functions[f"{REL}::Base.run"]
        call = _calls(graph, fn)[0]
        resolved = graph.resolve_call(call, fn, None)
        assert [c.qual for c, _ in resolved] == ["Base.step"]

    def test_binding_class_picks_the_override(self):
        # Actor._main walked with binding Communicator must resolve
        # self.<method> to the subclass override — the mechanism that
        # keys every spawn entry by the *bound* class.
        graph = _graph(self.SRC)
        fn = graph.functions[f"{REL}::Base.run"]
        call = _calls(graph, fn)[0]
        resolved = graph.resolve_call(call, fn, "Child")
        assert [c.qual for c, _ in resolved] == ["Child.step"]

    def test_reachability_respects_the_binding(self):
        graph = _graph(self.SRC)
        fn = graph.functions[f"{REL}::Base.run"]
        enclosing = {w.qual for w, _, _
                     in graph.reachable_calls(fn, "Child")}
        assert "Child.step" in enclosing
        assert "Base.step" not in enclosing

    def test_self_attr_type_inference(self):
        graph = _graph(
            "class Worker:\n"
            "    def run(self):\n"
            "        pass\n"
            "class Owner:\n"
            "    def __init__(self):\n"
            "        self._w = Worker()\n"
            "    def go(self):\n"
            "        self._w.run()\n")
        fn = graph.functions[f"{REL}::Owner.go"]
        call = _calls(graph, fn)[0]
        resolved = graph.resolve_call(call, fn, None)
        assert [c.qual for c, _ in resolved] == ["Worker.run"]


class TestThreadTargetEdges:
    SRC = (
        "import threading\n"
        "def entry():\n"
        "    t = threading.Thread(target=worker)\n"
        "    t.start()\n"
        "    tick()\n"
        "def worker():\n"
        "    blocked()\n"
        "def tick():\n"
        "    pass\n"
        "def blocked():\n"
        "    pass\n")

    def test_target_reference_resolves(self):
        graph = _graph(self.SRC)
        fn = graph.module_funcs[(REL, "entry")]
        thread_call = _calls(graph, fn)[0]
        target = next(kw.value for kw in thread_call.keywords
                      if kw.arg == "target")
        resolved = graph.resolve_callable(target, fn, None)
        assert [c.qual for c, _ in resolved] == ["worker"]

    def test_spawned_target_is_not_same_thread_flow(self):
        # The closure must NOT walk into Thread/spawn targets: the
        # target runs on another thread, so its blocking calls are
        # not reachable *from the spawner* (pass 9 analyzes each
        # entry point separately).
        graph = _graph(self.SRC)
        fn = graph.module_funcs[(REL, "entry")]
        enclosing = {w.qual for w, _, _
                     in graph.reachable_calls(fn, None)}
        assert "tick" in enclosing or "entry" in enclosing
        assert "worker" not in enclosing


class TestPartial:
    def test_partial_payload_resolves(self):
        graph = _graph(
            "import functools\n"
            "class C:\n"
            "    def go(self):\n"
            "        return functools.partial(self._fill, 3)\n"
            "    def _fill(self, n):\n"
            "        pass\n")
        fn = graph.functions[f"{REL}::C.go"]
        partial_call = next(
            node for node in ast.walk(fn.node)
            if isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "partial")
        resolved = graph.resolve_callable(partial_call, fn, None)
        assert [c.qual for c, _ in resolved] == ["C._fill"]


class TestBounds:
    def test_recursion_terminates(self):
        graph = _graph(
            "def a():\n"
            "    a()\n"
            "    b()\n"
            "def b():\n"
            "    pass\n")
        fn = graph.module_funcs[(REL, "a")]
        sites = list(graph.reachable_calls(fn, None))
        # Two call sites in a(), each yielded once — the visited set
        # cuts the a->a cycle instead of looping.
        assert len(sites) == 2

    def test_depth_bound_cuts_deep_chains(self):
        n = DEPTH_LIMIT + 4
        src = "".join(f"def f{i}():\n    f{i + 1}()\n"
                      for i in range(n))
        src += f"def f{n}():\n    pass\n"
        graph = _graph(src)
        fn = graph.module_funcs[(REL, "f0")]
        enclosing = {w.qual for w, _, _
                     in graph.reachable_calls(fn, None)}
        assert f"f{DEPTH_LIMIT - 1}" in enclosing
        assert f"f{DEPTH_LIMIT}" not in enclosing
