"""Multi-process integration tests over the TCP transport.

The reference gates distributed correctness by really running
``mpirun -np 4 multiverso.test kv|array|net|allreduce``
(ref: deploy/docker/Dockerfile:100-110, Test/main.cpp:12-25). The moral
equivalent here: N OS processes over localhost TCP, machine-file
bootstrapped, running the same actor/table stack end to end —
raw transport ping-pong (ref: Test/test_net.cpp:9-90), sync-mode BSP adds
and gets (ref: Test/test_array_table.cpp:11-47), and ``-ma`` allreduce
(ref: Test/test_allreduce.cpp:10-19).
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, REPO)
from multiverso_tpu.util.net_util import free_listen_port  # noqa: E402

# Children must force the CPU platform in-process (the TPU image's
# sitecustomize pins the hardware platform at interpreter start, so env
# vars alone are not enough) and need a small virtual device mesh.
PRELUDE = """
import os, sys
import faulthandler
faulthandler.dump_traceback_later(200, exit=True)  # self-report hangs
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import numpy as np
import multiverso_tpu as mv
rank = int(os.environ["MV_RANK"])
"""


def run_cluster(bodies, timeout=240):
    """Spawn one python per body; body i runs with MV_RANK=i. Returns
    the stdout of each after asserting all exited cleanly."""
    procs = []
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        XLA_FLAGS="--xla_force_host_platform_device_count=2",
        PYTHONPATH=REPO,
    )
    for rank, body in enumerate(bodies):
        code = PRELUDE.format(repo=REPO) + body
        procs.append(subprocess.Popen(
            [sys.executable, "-c", code],
            env=dict(env, MV_RANK=str(rank)),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
    outs = []
    failures = []
    timed_out = False
    for rank, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            timed_out = True
            for q in procs:
                q.kill()
            out, err = p.communicate()
            failures.append(f"rank {rank} TIMED OUT:\n{err[-1500:]}")
            continue
        outs.append(out)
        if p.returncode != 0:
            state = "killed after sibling timeout" if timed_out \
                else f"rc={p.returncode}"
            failures.append(f"rank {rank} {state}:\n{err[-1500:]}")
    assert not failures, "\n---\n".join(failures)
    return outs


def write_machine_file(tmp_path, n):
    ports = [free_listen_port() for _ in range(n)]
    mf = tmp_path / "machines"
    mf.write_text("".join(f"127.0.0.1:{p}\n" for p in ports))
    return str(mf), ports


def test_raw_transport_pingpong(tmp_path):
    # ref: Test/test_net.cpp:9-90 — multi-blob message send/recv without
    # the actor stack.
    mf, ports = write_machine_file(tmp_path, 2)
    eps = [f"127.0.0.1:{p}" for p in ports]
    common = f"""
from multiverso_tpu.core.blob import Blob
from multiverso_tpu.core.message import Message, MsgType
from multiverso_tpu.runtime.tcp import TcpNet
net = TcpNet(rank, {eps!r})
"""
    body0 = common + """
msg = Message(src=0, dst=1, msg_type=MsgType.Request_Get, msg_id=7)
msg.push(Blob(np.arange(5, dtype=np.int32).view(np.uint8)))
msg.push(Blob(np.linspace(0, 1, 6, dtype=np.float32)))
net.send(msg)
reply = net.recv(timeout=60)
assert reply is not None and reply.msg_id == 7, reply
assert reply.type == MsgType.Reply_Get
np.testing.assert_array_equal(reply.data[0].as_array(np.int32),
                              np.arange(5, dtype=np.int32))
np.testing.assert_allclose(reply.data[1].as_array(np.float32),
                           np.linspace(0, 1, 6, dtype=np.float32))
net.finalize()
print("PINGPONG_OK")
"""
    body1 = common + """
msg = net.recv(timeout=60)
assert msg is not None and msg.src == 0 and msg.dst == 1
reply = msg.create_reply_message()
reply.data = list(msg.data)
net.send(reply)
net.recv(timeout=10)  # drain until peer closes (returns None)
net.finalize()
print("ECHO_OK")
"""
    outs = run_cluster([body0, body1])
    assert "PINGPONG_OK" in outs[0] and "ECHO_OK" in outs[1]


def test_four_process_bsp_sync(tmp_path):
    # The mpirun -np 4 array-table gate, BSP flavor: every worker's i-th
    # get sees exactly all workers' i-th adds
    # (ref: Test/test_array_table.cpp:11-47, src/server.cpp:61-222).
    n = 4
    mf, _ = write_machine_file(tmp_path, n)
    body = f"""
mv.init(["-machine_file={mf}", "-rank=" + str(rank), "-sync=true"])
table = mv.create_array_table(8)
seen = []
for it in range(3):
    table.add(np.full(8, 1.0, np.float32))
    out = table.get()
    seen.append(float(out[0]))
assert seen == [{n}.0, {2 * n}.0, {3 * n}.0], seen
mv.shutdown()
print("BSP_OK", seen)
"""
    outs = run_cluster([body] * n)
    assert all("BSP_OK" in o for o in outs)


def test_four_process_matrix_and_kv(tmp_path):
    # Row-sharded matrix + kv over 4 real processes (async mode with
    # barriers, ref: Test/test_matrix_table.cpp, test_kv.cpp).
    n = 4
    mf, _ = write_machine_file(tmp_path, n)
    body = f"""
mv.init(["-machine_file={mf}", "-rank=" + str(rank)])
matrix = mv.create_matrix_table(10, 3)
if rank == 0:
    matrix.add_rows(np.array([0, 9], np.int32), np.ones((2, 3), np.float32))
kv = mv.create_kv_table()
kv.add([rank], [float(rank + 1)])
mv.barrier()
out = matrix.get()
assert out.sum() == 6.0, out
got = kv.get([0, 1, 2, 3])
assert [got[k] for k in range(4)] == [1.0, 2.0, 3.0, 4.0], got
mv.barrier()
mv.shutdown()
print("TABLES_OK")
"""
    outs = run_cluster([body] * n)
    assert all("TABLES_OK" in o for o in outs)


def test_ma_allreduce_over_tcp(tmp_path):
    # -ma mode: no PS actors; MV_Aggregate drives the hand-rolled
    # allreduce engine over raw TCP send/recv
    # (ref: Test/test_allreduce.cpp:10-19). Small (<4KB allgather path)
    # and large (reduce-scatter path) payloads, back to back — the
    # persistent engine stash must carry between calls.
    n = 4
    mf, _ = write_machine_file(tmp_path, n)
    body = f"""
mv.init(["-machine_file={mf}", "-rank=" + str(rank), "-ma=true"])
small = mv.aggregate(np.full(4, float(rank + 1), np.float32))
np.testing.assert_allclose(small, np.full(4, 10.0))
big = mv.aggregate(np.full(4096, 1.0, np.float32) * (rank + 1))
np.testing.assert_allclose(big, np.full(4096, 10.0))
again = mv.aggregate(np.arange(3, dtype=np.float32))
np.testing.assert_allclose(again, np.arange(3) * {n})
mv.shutdown()
print("MA_OK")
"""
    outs = run_cluster([body] * n)
    assert all("MA_OK" in o for o in outs)


def test_ma_ring_allreduce_over_tcp(tmp_path):
    # The chunked pipelined ring path over real OS processes: 3 ranks
    # (non-power-of-two, so no surplus fold), forced ring with small
    # chunks so the sliding window and the writer threads actually
    # carry multiple frames in flight; then the int8 lossy tier with
    # its error-feedback residual across back-to-back calls.
    n = 3
    mf, _ = write_machine_file(tmp_path, n)
    body = f"""
mv.init(["-machine_file={mf}", "-rank=" + str(rank), "-ma=true",
         "-allreduce_algo=ring", "-allreduce_chunk_kb=64"])
big = mv.aggregate(np.full(300000, 1.0, np.float32) * (rank + 1))
np.testing.assert_allclose(big, np.full(300000, 6.0), rtol=1e-5)
rng = np.random.default_rng(rank)
odd = mv.aggregate(np.arange(120001, dtype=np.float32))
np.testing.assert_allclose(odd, np.arange(120001) * {n}, rtol=1e-5)
mv.set_flag("allreduce_lossy", True)
vals = (np.sign(np.random.default_rng(7).standard_normal(200000))
        * np.random.default_rng(8).uniform(0.5, 1.5, 200000)
        ).astype(np.float32)
lossy = mv.aggregate(vals)
np.testing.assert_allclose(lossy, vals * {n}, rtol=0.05, atol=0.2)
lossy2 = mv.aggregate(vals)
np.testing.assert_allclose(lossy2, vals * {n}, rtol=0.05, atol=0.2)
mv.shutdown()
print("MA_RING_OK")
"""
    outs = run_cluster([body] * n)
    assert all("MA_RING_OK" in o for o in outs)


def test_aggregate_refused_while_ps_owns_endpoint(tmp_path):
    # Outside ma mode the communicator's recv thread owns the endpoint;
    # a transport-level allreduce would race it for inbound messages, so
    # mv.aggregate must refuse loudly instead of corrupting both streams.
    n = 2
    mf, _ = write_machine_file(tmp_path, n)
    body = f"""
mv.init(["-machine_file={mf}", "-rank=" + str(rank)])
try:
    mv.aggregate(np.ones(4, np.float32))
except RuntimeError as e:
    assert "ma mode" in str(e), e
    print("GUARD_OK")
else:
    print("GUARD_MISSING")
mv.barrier()
mv.shutdown()
"""
    outs = run_cluster([body] * n)
    assert all("GUARD_OK" in o for o in outs)


def test_net_bind_connect_bootstrap(tmp_path):
    # App-driven deployment without a machine file: MV_NetBind +
    # MV_NetConnect parity (ref: include/multiverso/multiverso.h:55-64).
    ports = [free_listen_port(), free_listen_port()]
    eps = [f"127.0.0.1:{p}" for p in ports]
    body = f"""
eps = {eps!r}
peer = 1 - rank
mv.net_bind(rank, eps[rank])
mv.net_connect([peer], [eps[peer]])
mv.init([])
table = mv.create_array_table(6)
table.add(np.full(6, float(rank + 1), np.float32))
mv.barrier()
np.testing.assert_allclose(table.get(), np.full(6, 3.0))
mv.barrier()
mv.shutdown()
print("BINDCONNECT_OK")
"""
    outs = run_cluster([body] * 2)
    assert all("BINDCONNECT_OK" in o for o in outs)


def test_mixed_version_codec_negotiation(tmp_path):
    # Rank 0 runs with the wire codec, rank 1 emulates a pre-codec peer
    # (-wire_codec=false: advertises nothing, encodes nothing, and will
    # NOT decode). Negotiation must keep every frame toward rank 1
    # plain, so the cluster works end to end — merely uncompressed in
    # that direction — with exact values both ways.
    n = 2
    mf, _ = write_machine_file(tmp_path, n)
    body = f"""
flags = ["-machine_file={mf}", "-rank=" + str(rank)]
if rank == 1:
    flags.append("-wire_codec=false")
mv.init(flags)
zoo = mv.current_zoo()
from multiverso_tpu.util.wire_codec import CAP_WIRE_CODEC
assert zoo.peer_caps(0) & CAP_WIRE_CODEC, zoo._peer_caps
assert not zoo.peer_caps(1) & CAP_WIRE_CODEC, zoo._peer_caps
matrix = mv.create_matrix_table(64, 33, is_sparse=True)
if rank == 0:
    delta = np.zeros((3, 33), np.float32)
    delta[:, 5] = [1.5, -2.0, 3.25]
    matrix.add_rows(np.array([0, 31, 63], np.int32), delta)
mv.barrier()
out = matrix.get()
assert out[0, 5] == 1.5 and out[31, 5] == -2.0 and out[63, 5] == 3.25, out
assert abs(out.sum() - 2.75) < 1e-6, out.sum()
mv.barrier()
mv.shutdown()
print("MIXED_CODEC_OK")
"""
    outs = run_cluster([body] * n)
    assert all("MIXED_CODEC_OK" in o for o in outs)


def test_coalesced_adds_over_tcp(tmp_path):
    # Async-mode burst of Adds: the worker must coalesce shards bound
    # for the same server into Request_BatchAdd frames (observable via
    # the server-side dashboard monitor), every ack must arrive (the
    # final wait() returns), and the summed result must be exact. One
    # sub-add carries bad row ids: its error must come back through the
    # batched ack without poisoning the siblings.
    n = 2
    mf, _ = write_machine_file(tmp_path, n)
    body = f"""
mv.init(["-machine_file={mf}", "-rank=" + str(rank)])
table = mv.create_array_table(32)
matrix = mv.create_matrix_table(8, 4)  # collective: servers on BOTH ranks
if rank == 1:
    ids = [table.add_async(np.full(32, 1.0, np.float32))
           for _ in range(20)]
    for i in ids:
        table.wait(i)
    from multiverso_tpu.tables.table_interface import TableRequestError
    ok1 = matrix.add_rows_async(np.array([2], np.int32),
                                np.ones((1, 4), np.float32))
    # A doomed whole-table add rides the same burst: 5 floats against a
    # 4x4 shard passes partition (host-side slicing is silent) and
    # fails the SERVER-side size CHECK — its error must come back
    # through the (possibly batched) ack without poisoning siblings.
    from multiverso_tpu.core.blob import Blob
    doomed = matrix.add_async_raw(
        Blob(np.array([-1], np.int32).view(np.uint8)),
        Blob(np.ones(5, np.float32)))
    ok2 = matrix.add_rows_async(np.array([3], np.int32),
                                np.full((1, 4), 2.0, np.float32))
    matrix.wait(ok1)
    try:
        matrix.wait(doomed)
        raise SystemExit("BATCH_ERROR_LOST")
    except TableRequestError:
        pass
    matrix.wait(ok2)
    buf = matrix.get()
    assert np.allclose(buf[2], 1.0) and np.allclose(buf[3], 2.0), buf
    from multiverso_tpu.util.dashboard import Dashboard
    flushes = Dashboard.get("WORKER_COALESCE_FLUSH").count
    # The 20-add burst outruns the worker actor (it serializes and
    # ships each shard over a real socket), so at least one multi-add
    # batch must have formed — without this assert, a regression that
    # silently disables staging would leave the test green via the
    # plain per-shard path.
    assert flushes >= 1, flushes
    print("BATCH_FLUSHES", flushes)
mv.barrier()
out = table.get()
assert np.allclose(out, 20.0), out
mv.barrier()
mv.shutdown()
print("COALESCE_OK", rank)
"""
    outs = run_cluster([body] * n)
    assert all("COALESCE_OK" in o for o in outs)


def test_peer_death_aborts_instead_of_hanging(tmp_path):
    # Failure detection (absent in the reference — a dead MPI rank hangs
    # the cluster, SURVEY.md section 5.3): when a peer process dies
    # mid-run, survivors blocked in barrier() or a table wait must raise
    # ClusterAborted instead of blocking forever.
    mf, _ = write_machine_file(tmp_path, 2)
    survivor = f"""
import multiverso_tpu as mv
from multiverso_tpu.runtime.zoo import ClusterAborted
mv.init(["-machine_file={mf}", "-rank=" + str(rank)])
table = mv.create_array_table(4)
table.add(np.ones(4, np.float32))
mv.barrier()  # both ranks alive here
try:
    mv.barrier()  # rank 1 dies instead of joining this one
    print("BARRIER_RETURNED")
except ClusterAborted:
    print("ABORTED_OK")
mv.shutdown(finalize_net=True)
"""
    dier = f"""
import os
import multiverso_tpu as mv
mv.init(["-machine_file={mf}", "-rank=" + str(rank)])
table = mv.create_array_table(4)
table.add(np.ones(4, np.float32))
mv.barrier()
os._exit(1)  # crash without goodbye frames
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2",
               PYTHONPATH=REPO)
    procs = [subprocess.Popen([sys.executable, "-c",
                               PRELUDE.format(repo=REPO) + body],
                              env=dict(env, MV_RANK=str(rank)),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
             for rank, body in enumerate([survivor, dier])]
    try:
        out0, err0 = procs[0].communicate(timeout=180)
        procs[1].communicate(timeout=60)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        out0, err0 = procs[0].communicate()
    assert "ABORTED_OK" in out0, out0 + err0[-1000:]


def test_colocated_device_path_over_tcp(tmp_path):
    # Locality rule (r5): a worker co-located with EVERY server shard
    # keeps the zero-copy device pipeline even on a TCP cluster, while
    # the remote worker crosses the wire with host batches — the
    # reference's -ps_role mixed deployment (src/zoo.cpp:29-35), with
    # the data plane picked per rank by locality.
    mf, _ = write_machine_file(tmp_path, 2)
    corpus = tmp_path / "corpus.txt"
    rng = np.random.default_rng(0)
    topics = [[f"a{i}" for i in range(8)], [f"b{i}" for i in range(8)]]
    with open(corpus, "w") as f:
        for _ in range(200):
            topic = topics[rng.integers(0, 2)]
            f.write(" ".join(rng.choice(topic, size=10)) + "\n")
    common = f"""
from multiverso_tpu.models.wordembedding import (
    BlockLoader, Dictionary, PSDeviceCorpusTrainer, PSWord2Vec,
    TokenizedCorpus, Word2VecConfig, iter_pair_batches)
corpus = {str(corpus)!r}
d = Dictionary.build(corpus, min_count=1)
role = "all" if rank == 0 else "worker"
mv.init(["-machine_file=" + {mf!r}, "-rank=" + str(rank),
         "-ps_role=" + role])
config = Word2VecConfig(embedding_size=8, window=3, epochs=2,
                        init_learning_rate=0.02, batch_size=256,
                        sample=0, use_ps=True)
model = PSWord2Vec(config, d)
"""
    body0 = common + """
assert model._device_path, "co-located rank must keep the device path"
tok = TokenizedCorpus.build(d, corpus)
trainer = PSDeviceCorpusTrainer(model, tok, centers_per_step=64)
loss, pairs = trainer.train_epoch(seed=0)  # ends with one barrier
assert pairs > 0 and loss == loss
mv.barrier()
mv.shutdown()
print("RANK0_DEVICE_OK")
"""
    body1 = common + """
assert not model._device_path, "remote worker must take host batches"
loss_sum = 0.0
for b in iter_pair_batches(d, corpus, batch_size=256, window=3,
                           subsample=0, seed=0):
    loss_sum += model.train_batch(b)
model._drain_pushes()
mv.barrier()  # pairs rank 0's epoch-end barrier
mv.barrier()
mv.shutdown()
print("RANK1_HOSTBATCH_OK")
"""
    outs = run_cluster([body0, body1])
    assert "RANK0_DEVICE_OK" in outs[0], outs
    assert "RANK1_HOSTBATCH_OK" in outs[1], outs


@pytest.mark.xfail(
    reason="jax multiprocess CPU backend limitation on this container "
           "(jax.distributed.initialize over the CPU backend; "
           "seed-verified failing, CHANGES PR 7/9) — the bootstrap "
           "path works on real multi-host deployments",
    strict=False)
def test_init_distributed_two_processes(tmp_path):
    # Multi-host bootstrap: jax.distributed.initialize gives the data
    # plane; the TCP control mesh rendezvouses through its coordinator's
    # key-value store — no machine file (runtime/bootstrap.py).
    from multiverso_tpu.util.net_util import free_listen_port
    coord = f"127.0.0.1:{free_listen_port()}"
    body = f"""
import multiverso_tpu as mv
mv.init_distributed(coordinator_address={coord!r}, num_processes=2,
                    process_id=rank)
table = mv.create_array_table(6)
table.add(np.full(6, float(rank + 1), np.float32))
mv.barrier()
out = table.get()
mv.barrier()
assert np.allclose(out, 3.0), out  # 1 + 2 from both processes
mv.shutdown()
print("DISTRIBUTED_BOOTSTRAP_OK")
"""
    outs = run_cluster([body, body])
    assert all("DISTRIBUTED_BOOTSTRAP_OK" in o for o in outs), outs
