"""Observability layer tests (docs/OBSERVABILITY.md).

Distributed request tracing (util/tracing.py + the TRACE_SLOT wire
plumbing), the metrics export/aggregation pipeline
(runtime/metrics.py), the HTTP scrape surface (io/metrics_http.py),
and the PR's acceptance integration: a 3-process TCP PS cluster
(1 worker + 2 servers) whose merged /trace.json shows one Get's spans
crossing rank boundaries under one trace id, and whose /metrics
scrape exposes cluster-aggregated SERVER_PROCESS_GET counts equal to
the sum of the per-rank dumps.
"""

import json
import re
import struct
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.blob import Blob
from multiverso_tpu.core.message import (HEADER_SIZE, Message, MsgType,
                                         TRACE_SLOT, WIRE_SLOTS,
                                         pack_add_batch, stamp_trace,
                                         trace_of)
from multiverso_tpu.io.metrics_http import (MetricsHttpServer,
                                            json_route,
                                            prometheus_route)
from multiverso_tpu.runtime.metrics import (ClusterMetrics,
                                            parse_report,
                                            split_family)
from multiverso_tpu.runtime.tcp import _serialize
from multiverso_tpu.util import tracing
from multiverso_tpu.util.configure import set_flag
from multiverso_tpu.util.dashboard import (Dashboard, metrics_snapshot,
                                           reset_samples, samples)

from test_net_integration import run_cluster, write_machine_file


@pytest.fixture(autouse=True)
def _clean_registries():
    tracing.reset()
    Dashboard.reset()
    reset_samples()
    yield
    tracing.reset()
    Dashboard.reset()
    reset_samples()


# ---------------------------------------------------------------------------
# trace ids + sampling
# ---------------------------------------------------------------------------

class TestTraceIds:
    def test_default_off_draws_nothing(self):
        assert tracing.new_trace(rank=0) == 0
        assert tracing.new_trace(rank=3) == 0
        assert tracing.snapshot_events() == []

    def test_full_sampling_ids_unique_and_rank_tagged(self):
        set_flag("trace_sample_rate", 1.0)
        ids = [tracing.new_trace(rank=5) for _ in range(100)]
        assert all(i > 0 for i in ids)
        assert len(set(ids)) == 100
        assert all(tracing.trace_rank(i) == 5 for i in ids)
        assert all(i < 2 ** 31 for i in ids)  # rides an int32 slot

    def test_partial_sampling_is_a_subset(self):
        set_flag("trace_sample_rate", 0.3)
        drawn = sum(1 for _ in range(500)
                    if tracing.new_trace(rank=0))
        assert 0 < drawn < 500  # statistically certain at 0.3/500


# ---------------------------------------------------------------------------
# span recording + ring bound + watchdog
# ---------------------------------------------------------------------------

class TestSpanRecording:
    def test_span_and_event_record(self):
        with tracing.span(7, "table_op:get", rank=1,
                          args={"table": 0}):
            time.sleep(0.001)
        tracing.event(7, "waiter_notify", rank=1)
        events = tracing.snapshot_events()
        assert [e["name"] for e in events] == ["table_op:get",
                                              "waiter_notify"]
        x, i = events
        assert x["ph"] == "X" and x["dur"] >= 1_000_000  # >= 1ms in ns
        assert x["args"] == {"table": 0}
        assert i["ph"] == "i"
        assert all(e["trace"] == 7 and e["rank"] == 1 for e in events)

    def test_untraced_span_is_inert_and_shared(self):
        a = tracing.span(0, "x", rank=0)
        b = tracing.span(0, "y", rank=0)
        assert a is b  # the shared null singleton: no per-call alloc
        with a:
            pass
        tracing.event(0, "z", rank=0)
        assert tracing.snapshot_events() == []

    def test_ring_buffer_bounds_memory(self):
        set_flag("trace_buffer", 32)
        for k in range(100):
            tracing.event(1, f"e{k}", rank=0)
        events = tracing.snapshot_events()
        assert len(events) == 32
        # Newest retained: the last 32 of the 100.
        assert events[0]["name"] == "e68"
        assert events[-1]["name"] == "e99"

    def test_drain_since_is_incremental(self):
        tracing.event(1, "a", rank=0)
        first = tracing.drain_since(0)
        assert [e["name"] for e in first] == ["a"]
        tracing.event(1, "b", rank=0)
        fresh = tracing.drain_since(max(e["seq"] for e in first))
        assert [e["name"] for e in fresh] == ["b"]

    def test_slow_watchdog_logs_timeline(self, capsys):
        set_flag("trace_slow_ms", 1.0)
        t0 = tracing.now_ns()
        tracing.event(9, "server_mailbox_enqueue", rank=1)
        time.sleep(0.01)
        tracing.end_root(9, "worker_issue:Request_Get[t0]", 0, t0)
        err = capsys.readouterr().err
        assert "slow request" in err
        assert "worker_issue:Request_Get[t0]" in err
        assert "server_mailbox_enqueue" in err

    def test_fast_root_stays_quiet(self, capsys):
        set_flag("trace_slow_ms", 10_000.0)
        tracing.end_root(9, "worker_issue:Request_Get[t0]", 0,
                         tracing.now_ns())
        assert "slow request" not in capsys.readouterr().err


# ---------------------------------------------------------------------------
# chrome trace export schema
# ---------------------------------------------------------------------------

def validate_chrome_trace(doc):
    """Schema check for the merged Chrome-trace JSON (the acceptance
    test loads /trace.json through this)."""
    assert isinstance(doc, dict)
    assert isinstance(doc["traceEvents"], list)
    for e in doc["traceEvents"]:
        assert isinstance(e["name"], str) and e["name"]
        assert e["ph"] in ("X", "i")
        assert isinstance(e["ts"], (int, float))
        assert isinstance(e["pid"], int)
        assert isinstance(e["tid"], str)
        assert isinstance(e["args"]["trace"], int)
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    return doc["traceEvents"]


class TestChromeExport:
    def test_export_schema_and_merge(self):
        with tracing.span(3, "tcp_send", rank=0):
            pass
        rank0 = tracing.snapshot_events()
        rank1 = [{"trace": 3, "name": "server_process_get", "ph": "X",
                  "rank": 1, "ts": tracing.now_ns(), "dur": 500,
                  "thread": "mv-server-r1", "seq": 1}]
        doc = tracing.chrome_trace([rank0, rank1])
        events = validate_chrome_trace(doc)
        assert {e["pid"] for e in events} == {0, 1}
        assert {e["args"]["trace"] for e in events} == {3}
        # ns -> us conversion
        assert events[0]["ts"] == pytest.approx(
            min(rank0[0]["ts"], rank1[0]["ts"]) / 1e3)


# ---------------------------------------------------------------------------
# wire: TRACE_SLOT plumbing + byte identity at sample rate 0
# ---------------------------------------------------------------------------

def _serialize_9int(msg):
    """What the pre-trace (9-int header) build put on the wire — the
    reference layout the byte-identity acceptance compares against."""
    blobs = [b.wire_bytes().tobytes() for b in msg.data]
    legacy = msg.header[:9]  # mvlint: ignore[wire-slot] - the legacy
    # 9-int layout is exactly what this helper reconstructs
    parts = [struct.pack("<9i", *[int(v) for v in legacy]),
             struct.pack("<I", len(blobs))]
    parts += [struct.pack("<Q", len(b)) for b in blobs]
    parts += blobs
    body = b"".join(parts)
    return struct.pack("<Q", len(body)) + body


class TestWirePlumbing:
    def test_trace_slot_registered(self):
        assert WIRE_SLOTS["TRACE_SLOT"] == TRACE_SLOT == 9
        assert HEADER_SIZE == 10

    def test_reply_carries_request_trace(self):
        msg = Message(src=0, dst=1, msg_type=MsgType.Request_Get,
                      table_id=2, msg_id=3)
        stamp_trace(msg, 1234)
        reply = msg.create_reply_message()
        assert trace_of(reply) == 1234
        untraced = Message(src=0, dst=1,
                           msg_type=MsgType.Request_Get)
        assert trace_of(untraced.create_reply_message()) == 0

    def test_batch_inherits_first_sampled_sub(self):
        subs = []
        for k in range(3):
            sub = Message(src=0, dst=1, msg_type=MsgType.Request_Add,
                          table_id=k, msg_id=k)
            sub.push(Blob(np.ones(2, np.float32)))
            subs.append(sub)
        stamp_trace(subs[1], 77)
        batch = pack_add_batch(subs)
        assert trace_of(batch) == 77
        assert trace_of(pack_add_batch([subs[0], subs[2]])) == 0

    def test_untraced_wire_bytes_identical_modulo_header_bump(self):
        """Acceptance: with -trace_sample_rate=0 (default) the wire
        bytes of a Get/Add exchange are byte-identical to a pre-trace
        build everywhere except the declared header-length bump — i.e.
        the frame differs ONLY by four zero bytes of header slot 9 and
        the total-length prefix that grows with them."""
        for msg_type in (MsgType.Request_Get, MsgType.Request_Add):
            msg = Message(src=0, dst=1, msg_type=msg_type,
                          table_id=2, msg_id=3)
            msg.push(Blob(np.arange(6, dtype=np.int32)
                          .view(np.uint8)))
            msg.push(Blob(np.linspace(0, 1, 5, dtype=np.float32)))
            frame = _serialize(msg)
            old = _serialize_9int(msg)
            # New frame: 4 extra bytes total, all in the header.
            (total,) = struct.unpack_from("<Q", frame, 0)
            (old_total,) = struct.unpack_from("<Q", old, 0)
            assert total == old_total + 4
            header = struct.unpack_from(f"<{HEADER_SIZE}i", frame, 8)
            assert header[TRACE_SLOT] == 0
            # Splicing the 10th header int out reproduces the old
            # frame exactly, byte for byte.
            spliced = struct.pack("<Q", old_total) \
                + frame[8:8 + 9 * 4] + frame[8 + 10 * 4:]
            assert spliced == old

    def test_sampled_trace_id_survives_the_frame(self):
        from multiverso_tpu.runtime.tcp import _deserialize
        msg = Message(src=0, dst=1, msg_type=MsgType.Request_Get)
        msg.push(Blob(np.ones(3, np.float32)))
        stamp_trace(msg, 4242)
        frame = _serialize(msg)
        out = _deserialize(frame[8:])
        assert trace_of(out) == 4242


# ---------------------------------------------------------------------------
# metrics snapshot + cluster aggregation + prometheus rendering
# ---------------------------------------------------------------------------

PROM_LINE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"          # metric name
    r"(\{[a-zA-Z0-9_]+=\"[^\"]*\""        # first label
    r"(,[a-zA-Z0-9_]+=\"[^\"]*\")*\})?"   # more labels
    r" -?[0-9.eE+-]+(inf)?$")             # value


def validate_prometheus(text):
    """Line-level validation of the text exposition format; returns
    {(metric, frozenset(labels)): float value}."""
    series = {}
    for line in text.strip().splitlines():
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ",
                            line), line
            continue
        assert PROM_LINE_RE.match(line), f"bad exposition line: {line}"
        name_labels, value = line.rsplit(" ", 1)
        name, _, labels = name_labels.partition("{")
        labels = labels.rstrip("}")
        key = (name, frozenset(labels.split(",")) if labels
               else frozenset())
        series[key] = float(value)
    return series


def _fake_report(rank, gets, window):
    return {"v": 1, "rank": rank,
            "monitors": {"SERVER_PROCESS_GET":
                         {"count": gets, "elapsed_ms": gets * 1.5}},
            "samples": {"DISPATCH_MS[d1]":
                        {"count": len(window), "recent": window}},
            "trace_events": [
                {"trace": 5, "name": "server_process_get", "ph": "X",
                 "rank": rank, "ts": 1000, "dur": 10, "seq": rank}]}


class TestClusterMetrics:
    def test_snapshot_is_versioned_and_complete(self):
        Dashboard.get("SERVER_PROCESS_GET").add(2.0)
        samples("DISPATCH_MS[d0]").add(1.25)
        snap = metrics_snapshot()
        assert snap["v"] == 1
        assert snap["monitors"]["SERVER_PROCESS_GET"]["count"] == 1
        assert snap["samples"]["DISPATCH_MS[d0]"]["recent"] == [1.25]

    def test_parse_report_rejects_foreign_versions(self):
        msg = Message(src=1, dst=0, msg_type=MsgType.Control_Metrics)
        msg.push(Blob(np.frombuffer(
            json.dumps({"v": 99, "rank": 1}).encode(),
            np.uint8).copy()))
        assert parse_report(msg) is None
        bad = Message(src=1, dst=0, msg_type=MsgType.Control_Metrics)
        bad.push(Blob(np.frombuffer(b"not json", np.uint8).copy()))
        assert parse_report(bad) is None
        assert parse_report(Message()) is None

    def test_cluster_sum_and_merged_percentiles(self):
        cm = ClusterMetrics()
        cm.ingest(_fake_report(1, 30, [1.0, 2.0]))
        cm.ingest(_fake_report(2, 12, [100.0, 200.0]))
        cm.ingest(_fake_report(1, 31, [1.0, 2.0]))  # newest per rank wins
        view = cm.cluster_view()
        agg = view["monitors_sum"]["SERVER_PROCESS_GET"]
        assert agg["count"] == 31 + 12
        merged = view["samples_merged"]["DISPATCH_MS[d1]"]
        assert merged["count"] == 4
        assert merged["max"] == 200.0
        assert merged["p50"] == 2.0  # nearest-rank over the union
        assert view["ranks"][2]["monitors"][
            "SERVER_PROCESS_GET"]["count"] == 12

    def test_prometheus_text_is_valid_and_sums(self):
        cm = ClusterMetrics()
        cm.ingest(_fake_report(1, 30, [1.0]))
        cm.ingest(_fake_report(2, 12, [3.0]))
        series = validate_prometheus(cm.prometheus_text())
        name = 'name="SERVER_PROCESS_GET"'
        per_rank = [v for (metric, labels), v in series.items()
                    if metric == "mv_monitor_count_total"
                    and name in labels]
        assert sorted(per_rank) == [12.0, 30.0]
        total = series[("mv_cluster_monitor_count_total",
                        frozenset([name]))]
        assert total == sum(per_rank) == 42.0
        q99 = series[("mv_cluster_samples",
                      frozenset(['name="DISPATCH_MS"', 'key="d1"',
                                 'quantile="0.99"']))]
        assert q99 == 3.0

    def test_split_family(self):
        assert split_family("DISPATCH_MS[d1]") == ("DISPATCH_MS", "d1")
        assert split_family("SERVER_PROCESS_GET") \
            == ("SERVER_PROCESS_GET", "")

    def test_merged_trace_feeds_chrome_export(self):
        cm = ClusterMetrics()
        cm.ingest(_fake_report(1, 1, []))
        cm.ingest(_fake_report(2, 1, []))
        events = validate_chrome_trace(cm.chrome_trace_json())
        assert {e["pid"] for e in events} == {1, 2}


# ---------------------------------------------------------------------------
# HTTP scrape surface
# ---------------------------------------------------------------------------

class TestMetricsHttp:
    def test_routes_content_and_404(self):
        server = MetricsHttpServer(0, {
            "/metrics": prometheus_route(lambda: "mv_up 1\n"),
            "/trace.json": json_route(
                lambda: {"traceEvents": []}),
        }, host="127.0.0.1")
        try:
            base = f"http://127.0.0.1:{server.port}"
            with urllib.request.urlopen(f"{base}/metrics",
                                        timeout=10) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith(
                    "text/plain; version=0.0.4")
                assert resp.read() == b"mv_up 1\n"
            with urllib.request.urlopen(f"{base}/trace.json",
                                        timeout=10) as resp:
                assert resp.headers["Content-Type"].startswith(
                    "application/json")
                assert json.loads(resp.read()) == {"traceEvents": []}
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(f"{base}/nope", timeout=10)
            assert exc.value.code == 404
        finally:
            server.stop()

    def test_renderer_failure_is_a_500_not_a_crash(self):
        def boom():
            raise RuntimeError("broken renderer")
        server = MetricsHttpServer(0, {
            "/metrics": prometheus_route(boom)}, host="127.0.0.1")
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{server.port}/metrics",
                    timeout=10)
            assert exc.value.code == 500
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# in-process end to end: root span envelops the server-side spans
# ---------------------------------------------------------------------------

class TestInProcessEndToEnd:
    def test_sampled_get_produces_nested_spans(self):
        mv.init(["-trace_sample_rate=1.0"])
        try:
            table = mv.create_matrix_table(32, 4)
            table.add_rows(np.arange(8, dtype=np.int32),
                           np.ones((8, 4), np.float32))
            table.get_rows(np.arange(8, dtype=np.int32))
        finally:
            mv.shutdown()
        events = tracing.snapshot_events()
        roots = [e for e in events
                 if e["name"].startswith("worker_issue:Request_Get")]
        assert roots, [e["name"] for e in events]
        root = roots[-1]
        nested = [e for e in events
                  if e["trace"] == root["trace"]
                  and e["name"] == "table_op:get"]
        assert nested, [e["name"] for e in events]
        for inner in nested:
            assert root["ts"] <= inner["ts"]
            assert inner["ts"] + inner["dur"] \
                <= root["ts"] + root["dur"]

    def test_default_rate_records_nothing(self):
        mv.init([])
        try:
            table = mv.create_matrix_table(16, 4)
            table.get_rows(np.arange(4, dtype=np.int32))
        finally:
            mv.shutdown()
        assert tracing.snapshot_events() == []


# ---------------------------------------------------------------------------
# acceptance: 3-process TCP cluster (1 worker + 2 servers)
# ---------------------------------------------------------------------------

def test_three_process_trace_and_metrics_scrape(tmp_path):
    """The PR's acceptance integration: full sampling + metrics export
    over a real 3-process TCP cluster. The worker writes the /metrics
    and /trace.json scrapes to files this process then validates:
    (a) at least one Get's spans cross rank boundaries and nest under
    one trace id; (b) the Prometheus scrape is valid text exposition
    and its cluster-aggregated SERVER_PROCESS_GET equals the sum of
    the per-rank dumps the servers print."""
    from multiverso_tpu.util.net_util import free_listen_port
    n = 3
    mf, _ = write_machine_file(tmp_path, n)
    mport = free_listen_port()
    trace_path = tmp_path / "trace.json"
    prom_path = tmp_path / "metrics.txt"
    common = f"""
role = "worker" if rank == 0 else "server"
mv.init(["-machine_file={mf}", "-rank=" + str(rank),
         "-ps_role=" + role, "-trace_sample_rate=1.0",
         "-metrics_interval_s=0.2", "-metrics_port={mport}"])
from multiverso_tpu.runtime.zoo import current_zoo
from multiverso_tpu.util.dashboard import Dashboard
zoo = current_zoo()
table = mv.create_matrix_table(16, 4)
"""
    worker = common + f"""
import time, urllib.request
ids = np.arange(16, dtype=np.int32)   # spans BOTH server shards
table.add_rows(ids, np.ones((16, 4), np.float32))
for _ in range(20):
    out = table.get_rows(ids)
assert out.shape == (16, 4) and out.sum() > 0
mv.barrier()            # traffic done cluster-wide
zoo.metrics_flush()     # final local report
mv.barrier()            # every rank flushed
base = "http://127.0.0.1:{mport}"
# Remote reports ride async writer threads: scrape until the cluster
# SERVER_PROCESS_GET stabilizes across two polls (bounded).
prev = None
for _ in range(50):
    prom = urllib.request.urlopen(base + "/metrics",
                                  timeout=10).read()
    import re as _re
    m = _re.search(rb'mv_cluster_monitor_count_total'
                   rb'\\{{name="SERVER_PROCESS_GET"\\}} (\\d+)', prom)
    cur = m.group(1) if m else None
    if cur is not None and cur == prev:
        break
    prev = cur
    time.sleep(0.3)
trace = urllib.request.urlopen(base + "/trace.json",
                               timeout=10).read()
open(r"{prom_path}", "wb").write(prom)
open(r"{trace_path}", "wb").write(trace)
mv.barrier()            # keep the scrape inside the cluster lifetime
mv.shutdown()
print("WORKER_OK")
"""
    server = common + """
mv.barrier()            # traffic done
zoo.metrics_flush()
mv.barrier()
print("SERVER_GET_COUNT=%d"
      % Dashboard.get("SERVER_PROCESS_GET").count)
mv.barrier()            # wait out the worker's scrape
mv.shutdown()
print("SERVER_OK")
"""
    outs = run_cluster([worker, server, server], timeout=300)
    assert "WORKER_OK" in outs[0]
    per_rank = [int(m.group(1)) for o in outs[1:]
                for m in [re.search(r"SERVER_GET_COUNT=(\d+)", o)]
                if m]
    assert len(per_rank) == 2 and all(c > 0 for c in per_rank), outs

    # (b) valid Prometheus exposition; cluster aggregate == sum of the
    # per-rank dumps, and the per-rank series match them too.
    series = validate_prometheus(prom_path.read_text())
    name = 'name="SERVER_PROCESS_GET"'
    total = series[("mv_cluster_monitor_count_total",
                    frozenset([name]))]
    assert total == sum(per_rank)
    scraped_ranks = sorted(
        v for (metric, labels), v in series.items()
        if metric == "mv_monitor_count_total" and name in labels
        and 'rank="0"' not in labels)
    assert scraped_ranks == sorted(float(c) for c in per_rank)

    # (a) merged chrome trace: a Get whose spans cross rank boundaries
    # and nest under one trace id (worker issue envelops the server
    # span recorded on ANOTHER rank).
    events = validate_chrome_trace(
        json.loads(trace_path.read_text()))
    by_trace = {}
    for e in events:
        by_trace.setdefault(e["args"]["trace"], []).append(e)
    nested_cross_rank = 0
    for tid, group in by_trace.items():
        roots = [e for e in group
                 if e["name"].startswith("worker_issue:Request_Get")]
        if not roots:
            continue
        root = roots[0]
        for e in group:
            if (e["pid"] != root["pid"]
                    and e["name"] == "server_process_get"
                    and e["ts"] >= root["ts"]
                    and e["ts"] + e["dur"]
                    <= root["ts"] + root["dur"]):
                nested_cross_rank += 1
    assert nested_cross_rank > 0, (
        f"no cross-rank nested Get trace among {len(by_trace)} traces")
