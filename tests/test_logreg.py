"""LogisticRegression application tests.

Mirrors the reference's mnist example flow
(ref: Applications/LogisticRegression/example/mnist.config, src/logreg.cpp)
on synthetic data: dense softmax, sparse sigmoid, FTRL, local + PS models,
reader formats, and the end-to-end CLI.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.logreg import (Configure, FTRLModel, LocalModel,
                                          PSModel, create_model,
                                          iter_samples, make_batches,
                                          parse_text_line)
from multiverso_tpu.models.logreg.main import LogReg


def write_dense_data(path, n=400, d=8, classes=3, seed=0):
    """Linearly separable synthetic set. Class centers come from a fixed
    seed so train/test splits with different sample seeds share the same
    distribution."""
    rng = np.random.default_rng(seed)
    centers = np.random.default_rng(42).standard_normal((classes, d)) * 3
    lines = []
    for _ in range(n):
        label = rng.integers(0, classes)
        x = centers[label] + rng.standard_normal(d) * 0.3
        lines.append(str(label) + " " + " ".join(f"{v:.5f}" for v in x))
    path.write_text("\n".join(lines))


def write_sparse_data(path, n=300, d=50, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.standard_normal(d)
    lines = []
    for _ in range(n):
        nnz = rng.integers(3, 8)
        keys = np.sort(rng.choice(d, nnz, replace=False))
        vals = rng.standard_normal(nnz)
        label = int(w_true[keys] @ vals > 0)
        lines.append(f"{label} " + " ".join(
            f"{k}:{v:.5f}" for k, v in zip(keys, vals)))
    path.write_text("\n".join(lines))


def accuracy(model, config, path):
    correct = total = 0
    for batch in make_batches(config, iter_samples(config, str(path))):
        pred = model.predict(batch)[:batch.count]
        labels = batch.labels[:batch.count]
        if pred.shape[1] == 1:
            hits = (pred[:, 0] >= 0.5).astype(np.int32) == labels
        else:
            hits = pred.argmax(axis=1).astype(np.int32) == labels
        correct += int(hits.sum())
        total += batch.count
    return correct / total


class TestReader:
    def test_parse_dense(self):
        s = parse_text_line("2 0.5 -1.0 3.25", sparse=False, weighted=False)
        assert s.label == 2 and s.weight == 1.0
        np.testing.assert_allclose(s.values, [0.5, -1.0, 3.25])

    def test_parse_sparse_libsvm(self):
        s = parse_text_line("1 3:0.5 17:2.0", sparse=True, weighted=False)
        assert s.label == 1
        np.testing.assert_array_equal(s.keys, [3, 17])
        np.testing.assert_allclose(s.values, [0.5, 2.0])

    def test_parse_weighted(self):
        s = parse_text_line("1:0.25 1.0 2.0", sparse=False, weighted=True)
        assert s.label == 1 and s.weight == 0.25

    def test_batching_pads_fixed_shapes(self, tmp_path):
        path = tmp_path / "d.txt"
        write_dense_data(path, n=25, d=4, classes=2)
        config = Configure(input_size=4, output_size=2, minibatch_size=10)
        config.train_file = str(path)
        batches = list(make_batches(config,
                                    iter_samples(config, str(path))))
        assert [b.count for b in batches] == [10, 10, 5]
        assert all(b.x.shape == (10, 4) for b in batches)
        assert batches[-1].weights[5:].sum() == 0  # padding rows weigh 0

    def test_sparse_batch_padding(self, tmp_path):
        path = tmp_path / "s.txt"
        write_sparse_data(path, n=12, d=30)
        config = Configure(input_size=30, output_size=1, sparse=True,
                           minibatch_size=6)
        batches = list(make_batches(config,
                                    iter_samples(config, str(path))))
        for b in batches:
            assert b.keys.shape == b.values.shape
            assert (b.keys <= 30).all()  # padding key == input_size


class TestLocalModel:
    def test_dense_softmax_learns(self, tmp_path):
        path = tmp_path / "train.txt"
        write_dense_data(path, n=600, d=8, classes=3)
        config = Configure(input_size=8, output_size=3,
                           objective_type="softmax", updater_type="sgd",
                           learning_rate=0.5, minibatch_size=20,
                           regular_type="L2", regular_coef=1e-4)
        model = LocalModel(config)
        for _ in range(4):
            for batch in make_batches(config,
                                      iter_samples(config, str(path))):
                model.update(batch)
        assert accuracy(model, config, path) > 0.95

    def test_sparse_sigmoid_learns(self, tmp_path):
        path = tmp_path / "train.txt"
        write_sparse_data(path, n=400, d=50)
        config = Configure(input_size=50, output_size=1, sparse=True,
                           objective_type="sigmoid", updater_type="sgd",
                           learning_rate=0.5, minibatch_size=16)
        model = LocalModel(config)
        for _ in range(6):
            for batch in make_batches(config,
                                      iter_samples(config, str(path))):
                model.update(batch)
        assert accuracy(model, config, path) > 0.9

    def test_ftrl_learns(self, tmp_path):
        path = tmp_path / "train.txt"
        write_sparse_data(path, n=400, d=50)
        config = Configure(input_size=50, output_size=1, sparse=True,
                           objective_type="sigmoid", updater_type="ftrl",
                           alpha=0.1, beta=1.0, lambda1=0.01, lambda2=0.01,
                           minibatch_size=16)
        model = FTRLModel(config)
        for _ in range(6):
            for batch in make_batches(config,
                                      iter_samples(config, str(path))):
                model.update(batch)
        assert accuracy(model, config, path) > 0.9


class TestPSModel:
    def test_dense_ps_learns(self, tmp_path):
        path = tmp_path / "train.txt"
        write_dense_data(path, n=600, d=8, classes=3)
        mv.init([])
        try:
            config = Configure(input_size=8, output_size=3, use_ps=True,
                               objective_type="softmax", updater_type="sgd",
                               learning_rate=0.5, minibatch_size=20,
                               sync_frequency=2)
            model = PSModel(config)
            for _ in range(4):
                for batch in make_batches(config,
                                          iter_samples(config, str(path))):
                    model.update(batch)
            assert accuracy(model, config, path) > 0.95
        finally:
            mv.shutdown()

    def test_sparse_ps_pull_receives_server_rows(self, tmp_path):
        # Regression: the sparse pull buffer must be writable — a
        # read-only np.asarray(jax) destination made every pull a silent
        # no-op inside the worker actor.
        mv.init([])
        try:
            config = Configure(input_size=10, output_size=1, use_ps=True,
                               sparse=True, objective_type="sigmoid",
                               updater_type="sgd")
            model = PSModel(config)
            # Another worker's update dirties rows for worker 0.
            from multiverso_tpu.updater import AddOption
            model._table.add_rows(np.array([4], np.int32),
                                  np.full((1, 1), -3.0, np.float32),
                                  option=AddOption(worker_id=1))
            model._pull()
            assert model.weights[4, 0] == pytest.approx(3.0)  # sgd: -=
        finally:
            mv.shutdown()

    def test_sparse_ps_learns(self, tmp_path):
        path = tmp_path / "train.txt"
        write_sparse_data(path, n=300, d=40)
        mv.init([])
        try:
            config = Configure(input_size=40, output_size=1, use_ps=True,
                               sparse=True, objective_type="sigmoid",
                               updater_type="sgd", learning_rate=0.5,
                               minibatch_size=16, sync_frequency=1)
            model = PSModel(config)
            for _ in range(6):
                for batch in make_batches(config,
                                          iter_samples(config, str(path))):
                    model.update(batch)
            assert accuracy(model, config, path) > 0.85
        finally:
            mv.shutdown()


class TestEndToEnd:
    def test_cli_config_flow(self, tmp_path):
        # The reference mnist.config flow on synthetic data.
        train, test = tmp_path / "train.data", tmp_path / "test.data"
        write_dense_data(train, n=500, d=8, classes=3, seed=1)
        write_dense_data(test, n=100, d=8, classes=3, seed=2)
        config_file = tmp_path / "syn.config"
        config_file.write_text(f"""
input_size=8
output_size=3
objective_type=softmax
regular_type=L2
updater_type=sgd
train_epoch=4
sparse=false
use_ps=false
minibatch_size=20
train_file={train}
test_file={test}
output_file={tmp_path}/test.out
output_model_file={tmp_path}/model.bin
learning_rate=0.5
regular_coef=0.0007
""")
        app = LogReg(str(config_file))
        app.train()
        acc = app.test()
        app.close()
        assert acc > 0.9
        assert (tmp_path / "model.bin").exists()
        out_lines = (tmp_path / "test.out").read_text().strip().split("\n")
        assert len(out_lines) == 100

    def test_model_save_load_roundtrip(self, tmp_path):
        path = tmp_path / "train.txt"
        write_dense_data(path, n=200, d=6, classes=2)
        config = Configure(input_size=6, output_size=2,
                           objective_type="softmax", updater_type="sgd",
                           learning_rate=0.5)
        model = LocalModel(config)
        for batch in make_batches(config, iter_samples(config, str(path))):
            model.update(batch)
        from multiverso_tpu.io import StreamFactory
        with StreamFactory.get_stream(str(tmp_path / "m.bin"), "w") as s:
            model.store(s)
        model2 = LocalModel(config)
        with StreamFactory.get_stream(str(tmp_path / "m.bin"), "r") as s:
            model2.load(s)
        np.testing.assert_array_equal(model.weights, model2.weights)
