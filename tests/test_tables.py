"""Table tests: ports of the reference's table test suite.

Mirrors Test/unittests/test_array.cpp:27-68 (partition as a unit + in-process
add/get roundtrips), Test/test_array_table.cpp:11-47 (multi-rank sync loop),
Test/unittests/test_kv.cpp, Test/test_matrix_table.cpp (row adds/gets), and
the sparse dirty-row semantics of src/table/sparse_matrix_table.cpp:200-258.
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.core.blob import Blob
from multiverso_tpu.core.message import MsgType
from multiverso_tpu.runtime.cluster import LocalCluster
from multiverso_tpu.tables import server_offsets, row_offsets
from multiverso_tpu.updater import AddOption


@pytest.fixture
def env():
    """Single-process worker+server environment
    (ref: Test/unittests/multiverso_env.h:9-31)."""
    mv.init([])
    yield
    mv.shutdown()


@pytest.fixture
def sync_env():
    mv.init(["-sync=true"])
    yield
    mv.shutdown()


class TestPartitionMath:
    def test_array_offsets_match_reference(self):
        # ref: array_table.cpp:14-20 — i*length, last absorbs remainder.
        assert server_offsets(10, 3) == [0, 3, 6, 10]
        assert server_offsets(9, 3) == [0, 3, 6, 9]
        assert server_offsets(5, 1) == [0, 5]

    def test_matrix_row_offsets_match_reference(self):
        # ref: matrix_table.cpp:24-41.
        assert row_offsets(10, 2) == [0, 5, 10]
        assert row_offsets(5, 3) == [0, 1, 2, 5]
        # Degenerate: fewer rows than servers -> one row per server.
        assert row_offsets(3, 8) == [0, 1, 2, 3]

    def test_array_partition_unit(self, env):
        # ref: Test/unittests/test_array.cpp:27-47 exercises Partition
        # directly as a unit.
        from multiverso_tpu.tables.array_table import ArrayWorker
        worker = ArrayWorker(10)  # one server in env
        values = np.arange(10, dtype=np.float32)
        parts = worker.partition(
            [Blob(np.array([-1], np.int32)), Blob(values)],
            MsgType.Request_Add)
        assert set(parts.keys()) == {0}
        np.testing.assert_array_equal(
            parts[0][1].as_array(np.float32), values)


class TestArrayTable:
    def test_add_get_roundtrip(self, env):
        table = mv.create_array_table(100)
        out = table.get()
        np.testing.assert_array_equal(out, np.zeros(100, np.float32))
        delta = np.arange(100, dtype=np.float32)
        table.add(delta)
        table.add(delta)
        np.testing.assert_array_equal(table.get(), 2 * delta)

    def test_async_add_then_wait(self, env):
        table = mv.create_array_table(16)
        ids = [table.add_async(np.ones(16, np.float32)) for _ in range(8)]
        for msg_id in ids:
            assert table.wait(msg_id, timeout=30)
        np.testing.assert_array_equal(table.get(), 8 * np.ones(16))

    def test_sgd_updater_subtracts(self, env):
        table = mv.create_array_table(8, updater_type="sgd")
        table.add(np.full(8, 2.5, np.float32))
        np.testing.assert_array_equal(table.get(),
                                      np.full(8, -2.5, np.float32))

    def test_get_into_user_buffer(self, env):
        table = mv.create_array_table(32)
        table.add(np.ones(32, np.float32))
        buf = np.zeros(32, np.float32)
        ret = table.get(out=buf)
        assert ret is buf
        np.testing.assert_array_equal(buf, np.ones(32))


class TestMatrixTable:
    def test_whole_table_roundtrip(self, env):
        table = mv.create_matrix_table(20, 5)
        out = table.get()
        assert out.shape == (20, 5)
        assert out.sum() == 0
        delta = np.ones((20, 5), np.float32)
        table.add(delta)
        np.testing.assert_array_equal(table.get(), delta)

    def test_row_add_get(self, env):
        table = mv.create_matrix_table(10, 4)
        rows = np.array([2, 7], np.int32)
        delta = np.stack([np.full(4, 1.0), np.full(4, 2.0)]).astype(np.float32)
        table.add_rows(rows, delta)
        got = table.get_rows(rows)
        np.testing.assert_array_equal(got, delta)
        whole = table.get()
        assert whole.sum() == delta.sum()

    def test_random_init_server(self, env):
        from multiverso_tpu.tables.matrix_table import MatrixServer, \
            MatrixWorker
        MatrixServer(6, 3, random_init=(-0.1, 0.1), seed=7)
        worker = MatrixWorker(6, 3)
        mv.barrier()
        vals = worker.get()
        assert (np.abs(vals) <= 0.1).all()
        assert np.abs(vals).sum() > 0

    def test_adagrad_matrix(self, env):
        table = mv.create_matrix_table(4, 2, updater_type="adagrad")
        opt = AddOption(worker_id=0, learning_rate=0.1, rho=0.1)
        table.add_rows(np.array([1], np.int32),
                       np.full((1, 2), 0.05, np.float32), option=opt)
        got = table.get()
        assert got[1, 0] < 0  # adagrad descends
        assert got[0].sum() == 0


class TestSparseMatrix:
    def test_dirty_row_tracking(self, env):
        table = mv.create_matrix_table(8, 2, is_sparse=True)
        # Initial get: everything dirty -> full table lands.
        out = table.get()
        assert out.shape == (8, 2)
        # Worker 0 adds rows 1,3 -> for itself they are now clean.
        table.add_rows(np.array([1, 3], np.int32),
                       np.ones((2, 2), np.float32),
                       option=AddOption(worker_id=0))
        stale = np.full((8, 2), -7.0, np.float32)
        table.get(out=stale)
        # Nothing dirty for worker 0 -> buffer untouched.
        np.testing.assert_array_equal(stale, np.full((8, 2), -7.0))

    def test_adder_does_not_clean_others_dirty_mark(self, env):
        # Regression (round-1 advice): worker B dirties a row, then worker
        # A adds to that same row. A's pending dirty mark must survive A's
        # own add — only Gets clean flags (ref: sparse_matrix_table.cpp
        # UpdateAddState skips just the adder) — so A's next dirty-only get
        # still returns the row with B's update folded in.
        table = mv.create_matrix_table(4, 2, is_sparse=True)
        table.get()  # worker 0: everything clean
        table.add_rows(np.array([2], np.int32),
                       np.ones((1, 2), np.float32),
                       option=AddOption(worker_id=1))  # B's add
        table.add_rows(np.array([2], np.int32),
                       np.ones((1, 2), np.float32),
                       option=AddOption(worker_id=0))  # A's add
        buf = np.full((4, 2), -1.0, np.float32)
        table.get(out=buf)  # A's dirty-only get
        np.testing.assert_array_equal(buf[2], [2.0, 2.0])

    def test_sparse_get_zeroed_when_out_omitted(self, env):
        # Regression (round-1 advice): a sparse whole-table get with no out
        # buffer must not surface uninitialized memory in clean rows.
        table = mv.create_matrix_table(4, 2, is_sparse=True)
        table.get()  # clean all for worker 0
        out = table.get()  # nothing dirty -> all rows must read as zeros
        np.testing.assert_array_equal(out, np.zeros((4, 2), np.float32))

    def test_wire_compression_roundtrip_and_shrink(self, env):
        # Sparse traffic runs through the wire codec both directions
        # (ref: sparse_matrix_table.cpp:148-153): a mostly-zero row delta
        # must round-trip exactly AND shrink on the wire. In-process
        # tables skip the filter automatically (no wire), so force it on
        # both endpoints to exercise the cross-process machinery.
        from multiverso_tpu.core.message import MsgType

        cols = 64
        table = mv.create_matrix_table(8, cols, is_sparse=True)
        table._compress = True
        mv.current_zoo()._server_tables[table.table_id]._compress = True
        table.get()  # clean all for worker 0
        delta = np.zeros((2, cols), np.float32)
        delta[0, 3] = 7.0
        delta[1, 60] = -2.5
        rows = np.array([1, 5], np.int32)
        # Wire-size proof: partition output IS the wire payload.
        from multiverso_tpu.core.blob import Blob
        from multiverso_tpu.updater import AddOption
        blobs = [Blob(rows.view(np.uint8)), Blob(delta.reshape(-1)),
                 AddOption(worker_id=1).to_blob()]
        shards = table.partition(blobs, MsgType.Request_Add)
        wire = sum(b.size for shard in shards.values() for b in shard)
        uncompressed = rows.nbytes + delta.nbytes + blobs[2].size
        assert wire < uncompressed, (wire, uncompressed)

        # Full-stack roundtrip: worker 1 adds, worker 0's dirty-only get
        # returns the exact values through the compressed path.
        table.add_rows(rows, delta, option=AddOption(worker_id=1))
        buf = np.full((8, cols), -1.0, np.float32)
        table.get(out=buf)
        np.testing.assert_array_equal(buf[1], delta[0])
        np.testing.assert_array_equal(buf[5], delta[1])

    def test_wire_compression_dense_payload_uncompressed(self, env):
        # >50% non-zero values must ride uncompressed (the filter's
        # break-even rule) and still round-trip.
        table = mv.create_matrix_table(6, 4, is_sparse=True)
        table.get()
        dense = np.arange(8, dtype=np.float32).reshape(2, 4) + 1
        table.add_rows(np.array([0, 3], np.int32), dense,
                       option=AddOption(worker_id=1))
        buf = np.zeros((6, 4), np.float32)
        table.get(out=buf)
        np.testing.assert_array_equal(buf[0], dense[0])
        np.testing.assert_array_equal(buf[3], dense[1])

    def test_compress_mismatch_degrades_to_raw(self, env):
        # A peer running WITHOUT the table-level codec (-sparse_compress
        # mismatch or a pre-codec build) sends raw [keys, values] — a
        # compress-enabled server must sniff the frame magic and take
        # the raw path instead of raising inside the actor loop (which
        # would strand the requester's waiter forever).
        table = mv.create_matrix_table(8, 16, is_sparse=True)
        server = mv.current_zoo()._server_tables[table.table_id]
        server._compress = True
        table._compress = True
        table.get()  # clean all for worker 0 (codec reply path)
        delta = np.zeros((2, 16), np.float32)
        delta[0, 1], delta[1, 15] = 3.0, -4.0
        table._compress = False  # emulate a plain-sending peer's Add
        table.add_rows(np.array([2, 6], np.int32), delta,
                       option=AddOption(worker_id=1))
        table._compress = True
        buf = np.zeros((8, 16), np.float32)
        table.get(out=buf)  # codec reply decodes exactly
        np.testing.assert_array_equal(buf[2], delta[0])
        np.testing.assert_array_equal(buf[6], delta[1])

    def test_wire_compression_lossy_error_feedback(self, env):
        # -wire_codec_lossy: quantized Add pushes with worker-side error
        # feedback. Repeating the same push must converge to the exact
        # accumulated sum (residual folding), not drift by one
        # quantization step per iteration.
        table = mv.create_matrix_table(8, 64, is_sparse=True)
        table._compress = True
        table._lossy = True
        mv.current_zoo()._server_tables[table.table_id]._compress = True
        table.get()  # clean all for worker 0
        rows = np.array([1, 5], np.int32)
        delta = np.zeros((2, 64), np.float32)
        delta[0, 3], delta[1, 60] = 0.731, -0.292
        steps = 16
        for _ in range(steps):
            table.add_rows(rows, delta, option=AddOption(worker_id=1))
        buf = np.zeros((8, 64), np.float32)
        table.get(out=buf)
        np.testing.assert_allclose(buf[1], steps * delta[0],
                                   rtol=0, atol=0.02)
        np.testing.assert_allclose(buf[5], steps * delta[1],
                                   rtol=0, atol=0.02)

    def test_row_get_marks_clean(self, env):
        table = mv.create_matrix_table(6, 2, is_sparse=True)
        table.get()  # clean all
        table.add_rows(np.array([2], np.int32),
                       np.full((1, 2), 5.0, np.float32),
                       option=AddOption(worker_id=1))  # dirty for worker 0
        buf = np.zeros((6, 2), np.float32)
        table.get(out=buf)
        np.testing.assert_array_equal(buf[2], [5.0, 5.0])
        assert buf[0].sum() == 0


class TestDonationSafety:
    def test_async_get_then_add_keeps_reply_alive(self, env):
        # A Get reply snapshot must survive the next donated update: the
        # sync-server drain pattern is get-reply-then-cached-adds
        # (regression: "Array has been deleted" on materialize).
        table = mv.create_array_table(64)  # 64 == padded size on 8 devices
        msg_id = table.get_async()
        for _ in range(4):
            table.add(np.ones(64, np.float32))
        assert table.wait(msg_id, timeout=30)
        # Reply content is a consistent snapshot (0..4 adds may have landed
        # first in async mode), not garbage from a deleted buffer.
        assert float(table._dest[0]) in {0.0, 1.0, 2.0, 3.0, 4.0}


class TestDeviceResidentPath:
    def test_array_device_add_get(self, env):
        import jax.numpy as jnp
        table = mv.create_array_table(64)
        delta = jnp.ones(64, jnp.float32)
        table.add(delta)  # device delta, no host roundtrip
        out = table.get_device()
        assert hasattr(out, "addressable_shards")
        np.testing.assert_array_equal(np.asarray(out), np.ones(64))
        # host path still agrees
        np.testing.assert_array_equal(table.get(), np.ones(64))

    def test_matrix_device_add_get(self, env):
        import jax.numpy as jnp
        table = mv.create_matrix_table(16, 4)
        table.add(jnp.full((16, 4), 2.0, jnp.float32))
        out = table.get_device()
        assert out.shape == (16, 4)
        np.testing.assert_array_equal(np.asarray(out), np.full((16, 4), 2.0))

    def test_matrix_device_rows_roundtrip(self, env):
        # Device row pull + device delta push: nothing leaves HBM in
        # process; results must match the host-path row APIs exactly.
        import jax.numpy as jnp
        table = mv.create_matrix_table(32, 4)
        table.add(np.arange(32 * 4, dtype=np.float32).reshape(32, 4))
        rows = np.array([1, 5, 5, 31], np.int32)  # dups allowed
        dev = table.get_rows_device(rows)
        assert hasattr(dev, "addressable_shards")
        np.testing.assert_array_equal(np.asarray(dev),
                                      table.get_rows(rows))
        # device delta push (incl. a duplicated row id: both add)
        table.add_rows(rows, jnp.ones((4, 4), jnp.float32))
        got = table.get_rows(np.array([1, 5, 31], np.int32))
        base = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        np.testing.assert_array_equal(got[0], base[1] + 1)
        np.testing.assert_array_equal(got[1], base[5] + 2)  # dup summed
        np.testing.assert_array_equal(got[2], base[31] + 1)

    def test_matrix_device_KEYS_roundtrip(self, env):
        # Device-RESIDENT id vectors (any shape, unsorted, duplicated)
        # pull and push without the ids ever touching the host — the
        # enabler for device-computed row sets (PS device pipeline).
        import jax.numpy as jnp
        table = mv.create_matrix_table(32, 4)
        base = np.arange(32 * 4, dtype=np.float32).reshape(32, 4)
        table.add(base)
        ids = jnp.asarray(np.array([[3, 1], [1, 31], [7, 7]], np.int32))
        out = table.get_rows_device(ids)
        assert out.shape == (3, 2, 4)
        np.testing.assert_array_equal(np.asarray(out),
                                      base[np.asarray(ids)])
        # device-key push: duplicates sum (ids 1 and 7 appear twice)
        table.add_rows(ids, jnp.ones((3, 2, 4), jnp.float32))
        got = table.get_rows(np.array([3, 1, 31, 7], np.int32))
        np.testing.assert_array_equal(got[0], base[3] + 1)
        np.testing.assert_array_equal(got[1], base[1] + 2)
        np.testing.assert_array_equal(got[2], base[31] + 1)
        np.testing.assert_array_equal(got[3], base[7] + 2)

    def test_sparse_dirty_device_roundtrip(self, env):
        # Device-reply dirty gets: same staleness semantics as the host
        # path (ref: sparse_matrix_table.cpp:226-258), payload in HBM.
        import jax.numpy as jnp
        table = mv.create_matrix_table(16, 4, is_sparse=True)
        ids0, vals0 = table.get_dirty_device()  # initial: all dirty
        assert ids0.size == 16 and vals0.shape == (16, 4)
        rows = np.array([2, 9], np.int32)
        table.add_rows(rows, jnp.ones((2, 4), jnp.float32),
                       option=AddOption(worker_id=1))
        ids, vals = table.get_dirty_device()
        assert hasattr(vals, "addressable_shards")
        np.testing.assert_array_equal(ids, rows)
        np.testing.assert_array_equal(np.asarray(vals),
                                      np.ones((2, 4), np.float32))
        ids2, _ = table.get_dirty_device()  # now clean
        assert ids2.size == 0

    def test_fused_add_get_dirty_matches_composed(self, env):
        # The -4 fused add+dirty-get must be the exact composition of
        # add_rows + get_dirty_device (same bookkeeping, one program):
        # interleaving fused and composed iterations stays consistent.
        import jax.numpy as jnp
        table = mv.create_matrix_table(16, 4, is_sparse=True)
        table.get_dirty_device()  # worker 0 starts clean
        rows = np.array([2, 9], np.int32)
        one = jnp.ones((2, 4), jnp.float32)
        ids, vals = table.add_get_dirty_device(
            rows, one, option=AddOption(worker_id=1), get_worker=0)
        np.testing.assert_array_equal(ids, rows)
        np.testing.assert_array_equal(np.asarray(vals),
                                      np.ones((2, 4), np.float32))
        ids2, vals2 = table.add_get_dirty_device(
            rows, one, option=AddOption(worker_id=1), get_worker=0)
        np.testing.assert_array_equal(ids2, rows)
        np.testing.assert_array_equal(np.asarray(vals2),
                                      2 * np.ones((2, 4), np.float32))
        # Device-mirror ids (the upload-skipping form) and the cached
        # dirty device vector produce the same result. The mirror must
        # be bucket-padded like the host path (compile-per-bucket, not
        # per distinct k).
        from multiverso_tpu.updater.engine import pad_ids
        ids_m, vals_m = table.add_get_dirty_device(
            rows, one, option=AddOption(worker_id=1), get_worker=0,
            row_ids_device=jnp.asarray(pad_ids(rows, 16)))
        np.testing.assert_array_equal(ids_m, rows)
        np.testing.assert_array_equal(np.asarray(vals_m),
                                      3 * np.ones((2, 4), np.float32))
        # The composed pair continues from the fused state seamlessly.
        table.add_rows(rows, one, option=AddOption(worker_id=1))
        ids3, vals3 = table.get_dirty_device()
        np.testing.assert_array_equal(ids3, rows)
        np.testing.assert_array_equal(np.asarray(vals3),
                                      4 * np.ones((2, 4), np.float32))

    def test_device_keys_rejected_stateful_updater(self, env):
        # Duplicate device ids only SUM correctly under stateless rules;
        # the misconfiguration must raise in the CALLER (the server-side
        # CHECK fires inside the actor, which swallows it and the ack
        # never comes — a silent hang).
        import jax.numpy as jnp
        table = mv.create_matrix_table(16, 4, updater_type="momentum")
        with pytest.raises(Exception, match="stateless"):
            table.add_rows(jnp.asarray(np.array([1, 2], np.int32)),
                           jnp.ones((2, 4), jnp.float32))

    def test_stray_negative_key_fails_fast(self, env):
        # Only -1/-2 are whole-table sentinels; any other negative id
        # must raise in the CALLER (partition runs inside the worker
        # actor, where an exception degrades to a silent bad reply).
        table = mv.create_matrix_table(16, 4)
        with pytest.raises(Exception, match="out of range"):
            table.get_rows(np.array([-3], np.int32))
        with pytest.raises(Exception, match="out of range"):
            table.add_rows(np.array([-3, 5], np.int32),
                           np.ones((2, 4), np.float32))
        with pytest.raises(Exception, match="out of range"):
            table.get_rows(np.array([16], np.int32))
        # Defense in depth: partition itself also rejects non-sentinels
        # (-3/-4 are the segmented / fused-dirty markers, so the stray
        # probe uses -5; a bare -3 with no segment blobs fails its own
        # layout CHECK).
        with pytest.raises(Exception, match="sentinel"):
            table.partition([Blob(np.array([-5], np.int32).view(np.uint8))],
                            MsgType.Request_Get)
        with pytest.raises(Exception, match="one id blob per server"):
            table.partition([Blob(np.array([-3], np.int32).view(np.uint8))],
                            MsgType.Request_Get)

    def test_sync_server_ticks_clock_on_error(self):
        # BSP: a failed add must still tick the vector clock — otherwise
        # the failed worker's clock stays behind and the gate caches
        # every other worker's requests forever (cluster-wide hang).
        from multiverso_tpu.tables.table_interface import TableRequestError

        def body(rank):
            table = mv.create_matrix_table(8, 2)
            if rank == 0:  # bad add: wrong-sized whole-table delta
                mid = table.add_async_raw(
                    Blob(np.array([-1], np.int32).view(np.uint8)),
                    Blob(np.ones(3, np.float32)))
                failed = False
                try:
                    table.wait(mid)
                except TableRequestError:
                    failed = True
            else:
                table.add(np.ones((8, 2), np.float32))
                failed = None
            got = table.get()  # would hang without the clock tick
            # Round 2: WORKER-side failure (partition raises before any
            # shard is sent) — the empty clock-tick shards must keep the
            # BSP clocks level for the other worker.
            if rank == 0:
                mid = table.add_async_raw(
                    Blob(np.array([-9], np.int32).view(np.uint8)),
                    Blob(np.ones(2, np.float32)))
                try:
                    table.wait(mid)
                    failed = False
                except TableRequestError as exc:
                    failed = failed and "partition" in str(exc)
            else:
                table.add(np.ones((8, 2), np.float32))
            got2 = table.get()  # would hang without the tick shards
            mv.current_zoo().barrier()
            return failed, float(got[0, 0]), float(got2[0, 0])

        results = LocalCluster(2, argv=["-sync=true"]).run(body)
        assert results[0][0] is True
        assert results[0][1] == results[1][1] == 1.0
        assert results[0][2] == results[1][2] == 2.0

    def test_remote_failures_raise_in_caller(self, env):
        # Failures inside the actor runtime must surface as
        # TableRequestError in the REQUESTER's wait(), not degrade to a
        # log line plus garbage/empty results (the actor loop swallows).
        from multiverso_tpu.tables.table_interface import TableRequestError
        table = mv.create_matrix_table(16, 4)
        # Worker-side: partition rejects the stray sentinel (raw API
        # bypasses the caller-side range CHECK).
        mid = table.get_async_raw(
            Blob(np.array([-3], np.int32).view(np.uint8)))
        with pytest.raises(TableRequestError, match="partition"):
            table.wait(mid)
        # Server-side: a wrong-sized whole-table add fails in
        # process_add; the error reply must carry the text back.
        mid = table.add_async_raw(
            Blob(np.array([-1], np.int32).view(np.uint8)),
            Blob(np.ones(7, np.float32)))
        with pytest.raises(TableRequestError, match="size mismatch"):
            table.wait(mid)
        # The table stays usable afterwards.
        table.add(np.ones((16, 4), np.float32))
        np.testing.assert_array_equal(table.get(),
                                      np.ones((16, 4), np.float32))

    def test_matrix_device_keys_multi_server_roundtrip(self):
        # Device keys broadcast to every server; each masks foreign
        # rows (gather fills 0, scatter drops) and the worker SUMS the
        # replies — exact gather/scatter semantics across 2 servers,
        # duplicates included, without the ids ever touching the host.
        def body(rank):
            import jax.numpy as jnp
            table = mv.create_matrix_table(10, 3)
            base = np.arange(30, dtype=np.float32).reshape(10, 3)
            if rank == 0:
                table.add(base)
            mv.current_zoo().barrier()
            # ids span both servers' row ranges (0-4 / 5-9), unsorted,
            # with a duplicate
            ids = jnp.asarray(np.array([[7, 1], [1, 9]], np.int32))
            got = np.asarray(table.get_rows_device(ids))
            ok_get = np.array_equal(got, base[np.asarray(ids)])
            if rank == 0:
                table.add_rows(ids, jnp.ones((2, 2, 3), jnp.float32))
            mv.current_zoo().barrier()
            after = table.get_rows(np.array([7, 1, 9, 0], np.int32))
            ok_add = (np.array_equal(after[0], base[7] + 1)
                      and np.array_equal(after[1], base[1] + 2)  # dup
                      and np.array_equal(after[2], base[9] + 1)
                      and np.array_equal(after[3], base[0]))
            mv.current_zoo().barrier()
            return ok_get and ok_add

        assert all(LocalCluster(2).run(body))

    def test_matrix_device_rows_two_servers(self):
        # Sorted row ids spanning both servers' ranges reassemble in
        # order; device push partitions into per-server device segments.
        def body(rank):
            import jax.numpy as jnp
            table = mv.create_matrix_table(10, 3)
            if rank == 0:
                table.add_rows(np.array([1, 4, 8], np.int32),
                               jnp.ones((3, 3), jnp.float32) * 2.0)
            mv.current_zoo().barrier()
            rows = np.array([1, 4, 8], np.int32)
            out = np.asarray(table.get_rows_device(rows))
            host = table.get_rows(rows)
            mv.current_zoo().barrier()
            return out.tolist(), host.tolist()

        for dev, host in LocalCluster(2).run(body):
            assert dev == host == [[2.0] * 3] * 3

    def test_device_path_multi_server(self):
        def body(rank):
            import jax.numpy as jnp
            table = mv.create_array_table(32)
            if rank == 0:
                table.add(jnp.ones(32, jnp.float32))
            mv.current_zoo().barrier()
            out = np.asarray(table.get_device())
            mv.current_zoo().barrier()
            return out.tolist()

        r0, r1 = LocalCluster(2).run(body)
        assert r0 == r1 == [1.0] * 32


class TestKVTable:
    def test_add_get(self, env):
        table = mv.create_kv_table()
        table.add([1, 5, 9], [1.0, 2.0, 3.0])
        table.add([1], [10.0])
        got = table.get([1, 5, 9, 42])
        assert got[1] == pytest.approx(11.0)
        assert got[5] == pytest.approx(2.0)
        assert got[42] == 0


class TestMultiRank:
    def test_array_table_two_ranks(self):
        # ref: Test/test_array_table.cpp:11-47 — every worker adds, then
        # everyone sees the combined result (async mode; barrier between).
        def body(rank):
            table = mv.create_array_table(10)
            table.add(np.full(10, rank + 1, np.float32))
            zoo = mv.current_zoo()
            zoo.barrier()
            out = table.get()
            zoo.barrier()
            return out.tolist()

        r0, r1 = LocalCluster(2).run(body)
        assert r0 == r1 == [3.0] * 10  # 1 + 2

    def test_matrix_table_two_servers_partition(self):
        def body(rank):
            table = mv.create_matrix_table(10, 3)
            if rank == 0:
                table.add_rows(np.array([0, 7], np.int32),
                               np.ones((2, 3), np.float32))
            mv.current_zoo().barrier()
            out = table.get()
            mv.current_zoo().barrier()
            return out.sum()

        results = LocalCluster(2).run(body)
        assert results == [6.0, 6.0]

    def test_sync_mode_bsp_contract(self):
        # BSP: the i-th Get sees exactly all workers' i-th Adds
        # (ref: src/server.cpp:60-66, Test/test_array_table sync loop).
        def body(rank):
            table = mv.create_array_table(4)
            seen = []
            for it in range(3):
                table.add(np.full(4, 1.0, np.float32))
                out = table.get()
                seen.append(float(out[0]))
            return seen

        results = LocalCluster(2, argv=["-sync=true"]).run(body)
        for seen in results:
            assert seen == [2.0, 4.0, 6.0]  # both workers' adds, per round

    def test_sparse_dirty_device_two_servers(self):
        # Device-reply dirty pulls across a 2-server partition (the
        # reference's dirty tracking works for any server count,
        # ref: sparse_matrix_table.cpp:226-258): per-server dirty sets
        # concatenate globally sorted; a server with zero dirty rows
        # contributes an empty segment (attributed by the server-id
        # blob, not by guessing from keys).
        def body(rank):
            import jax.numpy as jnp
            table = mv.create_matrix_table(16, 4, is_sparse=True)
            zoo = mv.current_zoo()
            ids0, vals0 = table.get_dirty_device()  # initial: all dirty
            ok0 = ids0.size == 16 and vals0.shape == (16, 4)
            zoo.barrier()
            rows = np.array([2, 9, 13], np.int32)  # spans both ranges
            if rank == 0:
                table.add_rows(rows, jnp.ones((3, 4), jnp.float32),
                               option=AddOption(worker_id=0))
            zoo.barrier()
            ids, vals = table.get_dirty_device()
            zoo.barrier()
            return ok0, ids.tolist(), float(np.asarray(vals).sum())

        r0, r1 = LocalCluster(2).run(body)
        # The adder's own flags stay clean; the other worker sees the
        # dirty rows from both servers, in global order.
        assert r0 == (True, [], 0.0)
        assert r1 == (True, [2, 9, 13], 12.0)

    def test_kv_two_servers(self):
        def body(rank):
            table = mv.create_kv_table()
            table.add([rank, 100 + rank], [1.0, 2.0])
            mv.current_zoo().barrier()
            got = table.get([0, 1, 100, 101])
            mv.current_zoo().barrier()
            return got

        for got in LocalCluster(2).run(body):
            assert got[0] == 1.0 and got[1] == 1.0
            assert got[100] == 2.0 and got[101] == 2.0


class TestOneBitPush:
    """-one_bit_push: 1-bit quantized Add traffic with worker-side error
    feedback (completes the reference's empty OneBitsFilter stub,
    ref: quantization_util.h:160-161)."""

    def test_wire_shrinks(self):
        from multiverso_tpu.util.configure import reset_flags, set_flag
        mv.init([])
        try:
            set_flag("one_bit_push", True)
            table = mv.create_matrix_table(16, 64)
            delta = np.linspace(-1.0, 1.0, 16 * 64,
                                dtype=np.float32).reshape(16, 64)
            shards = table.partition(
                [Blob(np.array([-1], np.int32).view(np.uint8)),
                 Blob(delta.reshape(-1))], MsgType.Request_Add)
            wire_bytes = sum(b.size for b in shards[0][1:])
            # sign bits (1/32 of float bytes) + tiny meta blob
            assert wire_bytes < delta.nbytes / 8, wire_bytes
        finally:
            reset_flags()
            mv.shutdown()

    def test_error_feedback_bounds_drift(self):
        from multiverso_tpu.util.configure import reset_flags, set_flag
        mv.init([])
        try:
            set_flag("one_bit_push", True)
            table = mv.create_matrix_table(16, 64)
            delta = np.linspace(-1.0, 1.0, 16 * 64,
                                dtype=np.float32).reshape(16, 64)
            # One push is lossy (just signs + means)...
            table.add(delta)
            assert not np.allclose(table.get(), delta, atol=1e-3)
            # ...but the feedback residual keeps the accumulated error
            # BOUNDED: the max error after 40 pushes must not be ~4x the
            # error after 10 (which unquantized drift-free error would
            # also satisfy, and feedback-free quantization would not).
            for _ in range(9):
                table.add(delta)
            err10 = np.abs(table.get() - 10 * delta).max()
            for _ in range(30):
                table.add(delta)
            err40 = np.abs(table.get() - 40 * delta).max()
            assert err40 < 2.5 * err10, (err10, err40)
            # and the RELATIVE per-push error shrinks with the horizon
            assert err40 / 40 < err10 / 10
        finally:
            reset_flags()
            mv.shutdown()
