"""WordEmbedding application tests.

Covers the reference's component behaviors (dictionary/huffman/reader,
ref: Applications/WordEmbedding/src/) plus end-to-end training quality:
on a synthetic corpus with two disjoint topic clusters, within-topic
embedding similarity must exceed cross-topic similarity for every mode
(SGNS skip-gram, CBOW, hierarchical softmax, PS-backed).
"""

import numpy as np
import pytest

import multiverso_tpu as mv
from multiverso_tpu.models.wordembedding import (Dictionary, PSWord2Vec,
                                                 Word2Vec, Word2VecConfig,
                                                 build_huffman,
                                                 iter_pair_batches,
                                                 sentence_pairs)


def write_topic_corpus(path, n_sentences=800, seed=0):
    """Two topic clusters; words co-occur only within their topic."""
    rng = np.random.default_rng(seed)
    topics = [[f"a{i}" for i in range(8)], [f"b{i}" for i in range(8)]]
    lines = []
    for _ in range(n_sentences):
        topic = topics[rng.integers(0, 2)]
        lines.append(" ".join(rng.choice(topic, size=12)))
    path.write_text("\n".join(lines))


def topic_separation(model, dictionary):
    emb = model.embeddings
    emb = emb / np.maximum(np.linalg.norm(emb, axis=1, keepdims=True),
                           1e-9)
    ids_a = [dictionary.word2id[w] for w in dictionary.words
             if w.startswith("a")]
    ids_b = [dictionary.word2id[w] for w in dictionary.words
             if w.startswith("b")]
    sims = emb @ emb.T
    within = (sims[np.ix_(ids_a, ids_a)].mean()
              + sims[np.ix_(ids_b, ids_b)].mean()) / 2
    across = sims[np.ix_(ids_a, ids_b)].mean()
    return within - across


class TestDictionary:
    def test_build_and_counts(self, tmp_path):
        path = tmp_path / "c.txt"
        path.write_text("x x x y y z\nx y q")
        d = Dictionary.build(str(path), min_count=2)
        assert d.word2id["x"] == 0  # most frequent first
        assert set(d.words) == {"x", "y"}
        assert d.counts[d.word2id["x"]] == 4

    def test_store_load_roundtrip(self, tmp_path):
        path = tmp_path / "c.txt"
        path.write_text("x x x y y z z z z")
        d = Dictionary.build(str(path), min_count=1)
        d.store(str(tmp_path / "vocab.txt"))
        d2 = Dictionary.load(str(tmp_path / "vocab.txt"))
        assert d2.words == d.words
        np.testing.assert_array_equal(d2.counts, d.counts)

    def test_negative_table_sums_to_one(self, tmp_path):
        path = tmp_path / "c.txt"
        path.write_text("x x x y y z")
        d = Dictionary.build(str(path), min_count=1)
        table = d.negative_table()
        assert table.sum() == pytest.approx(1.0, rel=1e-5)


class TestHuffman:
    def test_codes_are_prefix_free(self):
        counts = np.array([50, 30, 10, 5, 3, 2])
        tree = build_huffman(counts)
        codes = []
        for i in range(len(counts)):
            length = tree.code_lengths[i]
            codes.append(tuple(tree.codes[i, :length]))
        for i, c1 in enumerate(codes):
            for j, c2 in enumerate(codes):
                if i != j:
                    assert c1 != c2[:len(c1)], "prefix violation"

    def test_frequent_words_get_short_codes(self):
        counts = np.array([1000, 500, 10, 5, 2, 1, 1, 1])
        tree = build_huffman(counts)
        assert tree.code_lengths[0] <= tree.code_lengths[-1]

    def test_inner_node_count(self):
        tree = build_huffman(np.array([5, 4, 3, 2, 1]))
        assert tree.num_inner_nodes == 4  # vocab-1 inner nodes


class TestPairGeneration:
    def test_sentence_pairs_within_window(self):
        rng = np.random.default_rng(0)
        ids = np.arange(10, dtype=np.int32)
        pairs = sentence_pairs(ids, window=3, rng=rng)
        assert pairs.shape[0] == 2
        assert (pairs[0] != pairs[1]).any()
        # Every pair must be within the max window.
        pos = {int(v): i for i, v in enumerate(ids)}
        for c, t in pairs.T:
            assert 1 <= abs(pos[int(c)] - pos[int(t)]) <= 3

    def test_batches_have_fixed_shape(self, tmp_path):
        path = tmp_path / "c.txt"
        write_topic_corpus(path, n_sentences=50)
        d = Dictionary.build(str(path), min_count=1)
        batches = list(iter_pair_batches(d, str(path), batch_size=256,
                                         window=3, subsample=0))
        assert all(b.centers.shape == (256,) for b in batches)
        assert all(b.count <= 256 for b in batches)

    def test_batch_words_sum_to_corpus_tokens(self, tmp_path):
        # words (the lr-schedule unit) must count corpus words, not pairs
        # (pairs ~ window x words).
        path = tmp_path / "c.txt"
        write_topic_corpus(path, n_sentences=50)
        d = Dictionary.build(str(path), min_count=1)
        batches = list(iter_pair_batches(d, str(path), batch_size=256,
                                         window=3, subsample=0))
        total_words = sum(b.words for b in batches)
        total_pairs = sum(b.count for b in batches)
        assert total_words == pytest.approx(d.total_count, rel=1e-6)
        assert total_pairs > 2 * total_words  # different units indeed

    def test_tail_padding_pairs_do_not_train(self, tmp_path):
        # A tail batch's padded (0,0) rows must not push word 0 toward
        # itself as a positive pair: with every pair masked out, the step
        # must be an exact no-op.
        from multiverso_tpu.models.wordembedding.data import PairBatch
        path = tmp_path / "c.txt"
        path.write_text("q0 q1 q2 q0 q1 q2\n")
        d = Dictionary.build(str(path), min_count=1)
        config = Word2VecConfig(embedding_size=8, window=2, epochs=1,
                                init_learning_rate=0.1, batch_size=16,
                                sample=0)
        model = Word2Vec(config, d)
        before = np.asarray(model._emb_in).copy()
        all_padding = PairBatch(np.zeros(16, np.int32),
                                np.zeros(16, np.int32), count=0, words=0)
        loss = model.train_batch_async(all_padding)
        assert float(loss) == 0.0
        np.testing.assert_array_equal(np.asarray(model._emb_in), before)


class TestStopwords:
    def test_cli_stopwords_filtered(self, tmp_path):
        # ref: Applications/WordEmbedding/src/reader.cpp — the -stopwords
        # table drops listed words before training.
        from multiverso_tpu.models.wordembedding.main import run
        corpus = tmp_path / "c.txt"
        corpus.write_text("the a0 the a1 the a2 a0 a1\n"
                          "the a1 a2 the a0 a2 a1 a0\n" * 10)
        stop = tmp_path / "stop.txt"
        stop.write_text("the\n")
        model = run([f"-train_file={corpus}", f"-stopwords={stop}",
                     "-min_count=1", "-size=8", "-epoch=1",
                     "-batch_size=64",
                     f"-output_file={tmp_path / 'v.txt'}"])
        assert "the" not in model.dictionary.word2id
        assert "a0" in model.dictionary.word2id


class TestDeviceCorpusTrainer:
    def test_device_pipeline_separates_topics(self, tmp_path):
        # The HBM-resident pipeline (in-jit subsample/window/negatives)
        # must learn the same structure the host-batch path does.
        from multiverso_tpu.models.wordembedding import (
            DeviceCorpusTrainer, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                                init_learning_rate=0.01, batch_size=1024,
                                sample=0)
        model = Word2Vec(config, d)
        trainer = DeviceCorpusTrainer(model, tok, centers_per_step=128,
                                      steps_per_dispatch=4)
        losses = []
        for epoch in range(3):
            loss, pairs = trainer.train_epoch(seed=epoch)
            losses.append(loss / max(pairs, 1))
        assert losses[-1] < losses[0], losses
        sep = topic_separation(model, d)
        assert sep > 0.3, f"separation {sep}"
        assert model.trained_words == pytest.approx(3 * tok.flat.size)

    def test_device_pipeline_subsample_counts(self, tmp_path):
        # With aggressive subsampling the trained pair count must drop
        # but raw-word accounting (the lr clock) must still cover the
        # whole corpus (ref: reader.cpp counts discarded words too).
        from multiverso_tpu.models.wordembedding import (
            DeviceCorpusTrainer, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        pair_counts = {}
        for sample in (0, 1e-4):
            config = Word2VecConfig(embedding_size=8, window=3, epochs=1,
                                    batch_size=256, sample=sample)
            model = Word2Vec(config, d)
            trainer = DeviceCorpusTrainer(model, tok,
                                          centers_per_step=128,
                                          steps_per_dispatch=2)
            _, pairs = trainer.train_epoch(seed=0)
            pair_counts[sample] = pairs
            assert model.trained_words == pytest.approx(tok.flat.size)
        assert pair_counts[1e-4] < 0.7 * pair_counts[0]

    def test_device_pipeline_max_steps_and_accounting(self, tmp_path):
        from multiverso_tpu.models.wordembedding import (
            DeviceCorpusTrainer, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path, n_sentences=100)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        model = Word2Vec(Word2VecConfig(embedding_size=8, window=2,
                                        epochs=1, batch_size=128,
                                        sample=0), d)
        trainer = DeviceCorpusTrainer(model, tok, centers_per_step=64,
                                      steps_per_dispatch=4)
        # A truncated (warmup-style) epoch trains only max_steps steps.
        _, pairs = trainer.train_epoch(seed=0, max_steps=2)
        assert 0 < pairs < tok.flat.size * 4  # a fraction of the epoch
        assert trainer.kept_words_trained == 2 * 64
        # lr clock advanced proportionally, not a full epoch.
        assert 0 < model.trained_words < tok.flat.size

    def test_device_pipeline_group_hook_words_sum(self, tmp_path):
        from multiverso_tpu.models.wordembedding import (
            DeviceCorpusTrainer, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path, n_sentences=100)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        model = Word2Vec(Word2VecConfig(embedding_size=8, window=2,
                                        epochs=1, batch_size=128,
                                        sample=0), d)
        trainer = DeviceCorpusTrainer(model, tok, centers_per_step=64,
                                      steps_per_dispatch=4)
        seen = []
        trainer.train_epoch(seed=0, group_hook=seen.append)
        # Hook word counts must sum to exactly the epoch's raw words
        # (the words/sec denominators depend on it).
        assert sum(seen) == pytest.approx(tok.flat.size)
        assert model.trained_words == pytest.approx(tok.flat.size)

    def test_device_pipeline_per_pair_separates_topics(self, tmp_path):
        # The quality mode (per-pair negatives, sequential window
        # sub-steps) must train at least as well as the banded fast
        # path on the topic corpus.
        from multiverso_tpu.models.wordembedding import (
            DeviceCorpusTrainer, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                                init_learning_rate=0.01,
                                batch_size=1024, sample=0,
                                per_pair=True)
        model = Word2Vec(config, d)
        trainer = DeviceCorpusTrainer(model, tok, centers_per_step=128,
                                      steps_per_dispatch=4)
        losses = []
        for epoch in range(3):
            loss, pairs = trainer.train_epoch(seed=epoch)
            losses.append(loss / max(pairs, 1))
        assert losses[-1] < losses[0], losses
        sep = topic_separation(model, d)
        assert sep > 0.3, f"separation {sep}"

    def test_device_pipeline_cbow_separates_topics(self, tmp_path):
        from multiverso_tpu.models.wordembedding import (
            DeviceCorpusTrainer, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                                init_learning_rate=0.02, batch_size=1024,
                                sample=0, cbow=True)
        model = Word2Vec(config, d)
        trainer = DeviceCorpusTrainer(model, tok, centers_per_step=128,
                                      steps_per_dispatch=4)
        losses = []
        for epoch in range(3):
            loss, examples = trainer.train_epoch(seed=epoch)
            losses.append(loss / max(examples, 1))
        assert losses[-1] < losses[0], losses
        sep = topic_separation(model, d)
        assert sep > 0.3, f"separation {sep}"

    def test_device_pipeline_hs_separates_topics(self, tmp_path):
        # Hierarchical softmax on the device pipeline: skip-gram over
        # the context word's Huffman path (code 0 = positive).
        from multiverso_tpu.models.wordembedding import (
            DeviceCorpusTrainer, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                                init_learning_rate=0.02, batch_size=1024,
                                sample=0, hs=True, negative=0)
        model = Word2Vec(config, d)
        trainer = DeviceCorpusTrainer(model, tok, centers_per_step=128,
                                      steps_per_dispatch=4)
        losses = []
        for epoch in range(3):
            loss, pairs = trainer.train_epoch(seed=epoch)
            losses.append(loss / max(pairs, 1))
        assert losses[-1] < losses[0], losses
        sep = topic_separation(model, d)
        assert sep > 0.3, f"separation {sep}"

    def test_device_pipeline_cbow_hs_separates_topics(self, tmp_path):
        # The last cell of the mode matrix on the device pipeline:
        # CBOW + hierarchical softmax (window mean vs the center's
        # Huffman path; ref: wordembedding.h:95-125 trains all four
        # combinations through one loop).
        from multiverso_tpu.models.wordembedding import (
            DeviceCorpusTrainer, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                                init_learning_rate=0.04,
                                batch_size=1024, sample=0, hs=True,
                                cbow=True, negative=0)
        model = Word2Vec(config, d)
        trainer = DeviceCorpusTrainer(model, tok, centers_per_step=128,
                                      steps_per_dispatch=4)
        losses = []
        for epoch in range(3):
            loss, examples = trainer.train_epoch(seed=epoch)
            losses.append(loss / max(examples, 1))
        assert losses[-1] < losses[0], losses
        sep = topic_separation(model, d)
        assert sep > 0.3, f"separation {sep}"

    def test_ps_device_pipeline_hs(self, tmp_path):
        # HS through the PS device pipeline (VERDICT r3 #5): path-node
        # ids computed in-jit, pulled/pushed as device keys.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        mv.init([])
        try:
            config = Word2VecConfig(embedding_size=16, window=3,
                                    epochs=3, init_learning_rate=0.02,
                                    batch_size=1024, sample=0, hs=True,
                                    negative=0)
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128)
            losses = []
            for epoch in range(3):
                loss, pairs = trainer.train_epoch(seed=epoch)
                losses.append(loss / max(pairs, 1))
            assert losses[-1] < losses[0], losses
            sep = topic_separation(model, d)
            assert sep > 0.3, f"separation {sep}"
        finally:
            mv.shutdown()


class TestMAWord2Vec:
    def test_ma_group_trains_over_mesh(self):
        # The reference's -ma mode on the flagship: each mesh device
        # trains a table replica on its corpus shard, MV_Aggregate =
        # in-jit pmean over the mesh. Replicas must come back averaged
        # (identical) and the loss finite.
        import jax
        import jax.numpy as jnp
        from multiverso_tpu.models.wordembedding.device_train import (
            _ma_group_fn)
        from multiverso_tpu.sharding import mesh as meshlib
        ndev = len(jax.devices())
        mesh = meshlib.local_mesh(ndev)
        C, W, K, n_local, V, D, G = 64, 2, 3, 512, 40, 8, 2
        rng = np.random.default_rng(0)
        fn = _ma_group_fn(mesh, C, W, K)
        emb_in = jnp.asarray(
            (rng.random((V, D)).astype(np.float32) - 0.5) / D)
        emb_out = jnp.zeros((V, D), jnp.float32)
        kept = jnp.asarray(
            rng.integers(0, V, ndev * n_local).astype(np.int32))
        ksent = jnp.asarray(np.repeat(
            np.arange(ndev * n_local // 16, dtype=np.int32), 16))
        keys = jax.random.split(jax.random.PRNGKey(0), ndev)
        bases = jnp.asarray((np.arange(G) * C).astype(np.int32))
        lrs = jnp.full(G, 0.05, jnp.float32)
        n_kept_local = jnp.full(ndev, n_local, jnp.int32)
        neg_prob = jnp.ones(V, jnp.float32)
        neg_alias = jnp.asarray(np.arange(V, dtype=np.int32))
        before = np.asarray(emb_out).copy()
        emb_in, emb_out, loss, pairs, next_keys = fn(
            emb_in, emb_out, kept, ksent, neg_prob, neg_alias, keys,
            bases, lrs, n_kept_local)
        assert np.isfinite(float(loss)) and float(pairs) > 0
        assert not np.allclose(np.asarray(emb_out), before)  # trained
        # Averaged result is a single replicated array; keys advanced.
        assert emb_in.shape == (V, D)
        assert next_keys.shape == keys.shape
        assert not np.array_equal(np.asarray(next_keys),
                                  np.asarray(keys))
        # Chained dispatch with the advanced keys draws FRESH windows:
        # a second group over the same bases must not reproduce the
        # first group's loss (replayed keys would, bit for bit).
        _, _, loss2, _, _ = fn(
            emb_in, emb_out, kept, ksent, neg_prob, neg_alias,
            next_keys, bases, lrs, n_kept_local)
        assert float(loss2) != float(loss)


class TestMACorpusTrainer:
    def _run(self, tmp_path, overlap, sharded=False):
        from multiverso_tpu.models.wordembedding import (MACorpusTrainer,
                                                         TokenizedCorpus)
        from multiverso_tpu.runtime.cluster import LocalCluster
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path, n_sentences=200)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))

        def body(rank):
            config = Word2VecConfig(embedding_size=8, window=2, epochs=2,
                                    init_learning_rate=0.02,
                                    batch_size=256, sample=0,
                                    negative=3, seed=7)
            model = Word2Vec(config, d)
            trainer = MACorpusTrainer(model, tok, avg_every=2,
                                      overlap=overlap, sharded=sharded,
                                      centers_per_step=64,
                                      steps_per_dispatch=1)
            losses = []
            for epoch in range(2):
                loss, examples = trainer.train_epoch(seed=epoch)
                losses.append(loss / max(examples, 1))
            trainer.finish()
            return (np.asarray(model._emb_in).copy(), losses,
                    trainer.comm_rounds)

        return LocalCluster(2, argv=["-ma=true"]).run(body)

    def test_uneven_shards_with_group_quota(self, tmp_path):
        # Data-parallel shards of different sizes produce different
        # group counts per epoch; group_quota (the largest rank's
        # count) keeps every rank joining the same number of
        # collectives instead of hanging the longer rank's average.
        from multiverso_tpu.models.wordembedding import (MACorpusTrainer,
                                                         TokenizedCorpus)
        from multiverso_tpu.runtime.cluster import LocalCluster
        paths = [tmp_path / "a.txt", tmp_path / "b.txt"]
        write_topic_corpus(paths[0], n_sentences=150)
        write_topic_corpus(paths[1], n_sentences=60, seed=1)
        d = Dictionary.build(str(paths[0]), min_count=1)
        toks = [TokenizedCorpus.build(d, str(p)) for p in paths]

        def body(rank):
            config = Word2VecConfig(embedding_size=8, window=2, epochs=1,
                                    init_learning_rate=0.02,
                                    batch_size=256, sample=0,
                                    negative=3, seed=5)
            model = Word2Vec(config, d)
            trainer = MACorpusTrainer(model, toks[rank], avg_every=2,
                                      overlap=True, centers_per_step=64,
                                      steps_per_dispatch=1)
            trainer.train_epoch(seed=0, group_quota=40)
            trainer.finish()
            return (trainer.comm_rounds,
                    float(np.asarray(model._emb_in).sum()))

        outs = LocalCluster(2, argv=["-ma=true"]).run(body)
        assert outs[0][0] == outs[1][0]  # same collective count
        assert abs(outs[0][1] - outs[1][1]) < 1e-5  # replicas agree

    def test_overlap_bit_identical_to_sync_and_trains(self, tmp_path):
        # The MA overlap acceptance contract: with -allreduce_lossy
        # off, the double-buffered trainer follows EXACTLY the sync
        # trainer's trajectory (the average is applied at the same
        # point in both modes; only where the stall lands differs) —
        # and the model actually learns.
        sync = self._run(tmp_path, overlap=False)
        over = self._run(tmp_path, overlap=True)
        for rank in range(2):
            np.testing.assert_array_equal(sync[rank][0], over[rank][0])
        losses = sync[0][1]
        assert losses[-1] < losses[0], losses
        assert sync[0][2] > 0  # averages actually happened
        assert sync[0][2] == over[0][2]

    def test_sharded_bit_identical_sync_overlap_and_trains(self, tmp_path):
        # The sharded-average (delta-vs-last-average) trainer keeps the
        # same contract the dense mode established: sync and overlapped
        # schedules apply the same update at the same point, so the
        # trajectories are BIT-IDENTICAL — and the model still learns.
        # (Sharded-vs-dense-ring bit-identity of the collective itself
        # is pinned in tests/test_allreduce.py TestShardedAverage.)
        sync = self._run(tmp_path, overlap=False, sharded=True)
        over = self._run(tmp_path, overlap=True, sharded=True)
        for rank in range(2):
            np.testing.assert_array_equal(sync[rank][0], over[rank][0])
        # Replicas agree after finish() (the reference is rebuilt from
        # collective results, identical on every rank).
        np.testing.assert_array_equal(sync[0][0], sync[1][0])
        losses = sync[0][1]
        assert losses[-1] < losses[0], losses
        assert sync[0][2] > 0
        assert sync[0][2] == over[0][2]
        # Delta-MA converges where dense MA does: same data, same
        # schedule, embeddings in the same neighborhood (NOT bitwise —
        # averaging params vs averaging deltas associates differently).
        dense = self._run(tmp_path, overlap=False, sharded=False)
        assert np.abs(sync[0][0] - dense[0][0]).max() < 0.05


class TestPSDevicePipeline:
    def test_ps_device_pipeline_trains_through_tables(self, tmp_path):
        # The HBM corpus pipeline driving PARAMETER-SERVER tables with
        # device-resident keys: pulls/pushes ride the full actor stack,
        # loss decreases, and the trained state lives in the tables.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        mv.init([])
        try:
            config = Word2VecConfig(embedding_size=16, window=3,
                                    epochs=3, init_learning_rate=0.01,
                                    batch_size=1024, sample=0)
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128)
            losses = []
            for epoch in range(3):
                loss, pairs = trainer.train_epoch(seed=epoch)
                assert pairs > 0
                losses.append(loss / pairs)
            assert losses[-1] < losses[0], losses
            sep = topic_separation(model, d)
            assert sep > 0.3, f"separation {sep}"
        finally:
            mv.shutdown()


    def test_ps_device_pipeline_cbow(self, tmp_path):
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        mv.init([])
        try:
            config = Word2VecConfig(embedding_size=16, window=3,
                                    epochs=3, init_learning_rate=0.02,
                                    batch_size=1024, sample=0, cbow=True)
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128)
            losses = []
            for epoch in range(3):
                loss, examples = trainer.train_epoch(seed=epoch)
                losses.append(loss / max(examples, 1))
            assert losses[-1] < losses[0], losses
            sep = topic_separation(model, d)
            assert sep > 0.3, f"separation {sep}"
        finally:
            mv.shutdown()

    def test_ps_device_pipeline_bsp_sync(self, tmp_path):
        # The device-key PS pipeline under -sync=true: both workers
        # issue identical per-block op sequences (same corpus, same
        # seeds), so the SyncServer vector clock must admit every pull
        # and training must converge.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        from multiverso_tpu.runtime.cluster import LocalCluster
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path, n_sentences=300)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))

        def body(rank):
            config = Word2VecConfig(embedding_size=8, window=3,
                                    epochs=2, init_learning_rate=0.02,
                                    batch_size=256, sample=0)
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128)
            losses = []
            for epoch in range(2):
                loss, examples = trainer.train_epoch(seed=epoch)
                losses.append(loss / max(examples, 1))
            return losses

        results = LocalCluster(2, argv=["-sync=true"],
                               roles=["all", "worker"]).run(body)
        for losses in results:
            assert losses[-1] < losses[0], losses

    def test_ps_device_pipeline_two_workers(self, tmp_path):
        # Two virtual worker ranks drive the device-key PS pipeline
        # against one shared server (device keys need a single server):
        # delta scaling 1/num_workers, interleaved device-key
        # pulls/pushes through one device.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        from multiverso_tpu.runtime.cluster import LocalCluster
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))

        def body(rank):
            config = Word2VecConfig(embedding_size=16, window=3,
                                    epochs=3, init_learning_rate=0.01,
                                    batch_size=1024, sample=0)
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128)
            for epoch in range(3):
                loss, pairs = trainer.train_epoch(seed=100 * rank + epoch)
                assert np.isfinite(loss) and pairs > 0
            mv.current_zoo().barrier()
            return topic_separation(model, d)

        seps = LocalCluster(2, roles=["all", "worker"]).run(body)
        assert all(s > 0.3 for s in seps), seps

    def test_ps_device_pipeline_per_pair(self, tmp_path):
        # Quality mode through the PS: per-pair negatives + sequential
        # window sub-steps on the pulled copies, net delta pushed.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        mv.init([])
        try:
            config = Word2VecConfig(embedding_size=16, window=3,
                                    epochs=3, init_learning_rate=0.01,
                                    batch_size=1024, sample=0,
                                    per_pair=True)
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128)
            losses = []
            for epoch in range(3):
                loss, pairs = trainer.train_epoch(seed=epoch)
                losses.append(loss / max(pairs, 1))
            assert losses[-1] < losses[0], losses
            sep = topic_separation(model, d)
            assert sep > 0.3, f"separation {sep}"
        finally:
            mv.shutdown()

    def test_ps_device_pipeline_grouped_blocks(self, tmp_path):
        # blocks_per_dispatch > 1: G blocks per pull/step/push round
        # trip (bounded staleness, the reference's sync_frequency
        # trade). Must converge and handle the padded tail group.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        mv.init([])
        try:
            config = Word2VecConfig(embedding_size=16, window=3,
                                    epochs=3, init_learning_rate=0.01,
                                    batch_size=1024, sample=0)
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128,
                                            blocks_per_dispatch=4)
            losses = []
            for epoch in range(3):
                loss, pairs = trainer.train_epoch(seed=epoch)
                assert pairs > 0
                losses.append(loss / pairs)
            assert losses[-1] < losses[0], losses
            sep = topic_separation(model, d)
            assert sep > 0.3, f"separation {sep}"
        finally:
            mv.shutdown()

    @pytest.mark.parametrize("mode", ["per_pair", "hs", "two_servers"])
    def test_ps_device_pipeline_grouped_variants(self, tmp_path, mode):
        # The grouped-dispatch wrappers vmap every step variant: the
        # per-pair quality step (the bench's quality-PS config), the HS
        # step (tuple aux pytree), and multi-server reply tuples.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        from multiverso_tpu.runtime.cluster import LocalCluster
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))
        kw = {"per_pair": True} if mode == "per_pair" else \
            ({"hs": True, "negative": 0} if mode == "hs" else {})
        config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                                init_learning_rate=0.002, sample=0,
                                batch_size=1024, **kw)

        def train(seed_base=0):
            model = PSWord2Vec(config, d)
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128,
                                            blocks_per_dispatch=4)
            losses = []
            for epoch in range(3):
                loss, pairs = trainer.train_epoch(seed=seed_base + epoch)
                assert pairs > 0
                losses.append(loss / pairs)
            assert losses[-1] < losses[0], losses
            return True

        if mode == "two_servers":
            def body(rank):
                if rank == 1:  # server-only rank hosts the second shard
                    PSWord2Vec(config, d)
                    for _ in range(3):
                        mv.current_zoo().barrier()
                    return True
                return train()
            assert all(LocalCluster(
                2, roles=["all", "server"]).run(body))
        else:
            mv.init([])
            try:
                assert train()
            finally:
                mv.shutdown()

    def test_ps_device_pipeline_two_servers(self, tmp_path):
        # Multi-server device keys (VERDICT r3 #3): the PS device
        # pipeline drives TWO in-process servers — ids broadcast, each
        # server masks foreign rows, worker sums the replies — and
        # training converges to the same topic structure.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        from multiverso_tpu.runtime.cluster import LocalCluster
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))

        def body(rank):
            config = Word2VecConfig(embedding_size=16, window=3,
                                    epochs=3, init_learning_rate=0.01,
                                    batch_size=1024, sample=0)
            model = PSWord2Vec(config, d)
            if rank == 1:  # server-only rank holds the second shard
                for _ in range(3):  # mirror the per-epoch barrier
                    mv.current_zoo().barrier()
                return None
            assert model._in_table._num_server == 2
            trainer = PSDeviceCorpusTrainer(model, tok,
                                            centers_per_step=128)
            losses = []
            for epoch in range(3):
                loss, pairs = trainer.train_epoch(seed=epoch)
                losses.append(loss / max(pairs, 1))
            assert losses[-1] < losses[0], losses
            return topic_separation(model, d)

        seps = LocalCluster(2, roles=["all", "server"]).run(body)
        assert seps[0] is not None and seps[0] > 0.3, seps

    @pytest.mark.parametrize("grouped", [1, 2])
    def test_ps_device_segmented_matches_broadcast(self, tmp_path,
                                                   grouped):
        # Round 5: per-server SEGMENTED device keys (each server gets a
        # calibrated slice of the sorted ids) must train to the same
        # tables as the broadcast+mask form — same update math, leaner
        # routing (ref: src/table/matrix_table.cpp:234-315). Pulled
        # rows reassemble to identical values; only duplicate-id
        # scatter-add order may differ, so allow float slop.
        from multiverso_tpu.models.wordembedding import (
            PSDeviceCorpusTrainer, PSWord2Vec, TokenizedCorpus)
        from multiverso_tpu.runtime.cluster import LocalCluster
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        tok = TokenizedCorpus.build(d, str(path))

        def run(segment):
            def body(rank):
                config = Word2VecConfig(embedding_size=16, window=3,
                                        epochs=2,
                                        init_learning_rate=0.01,
                                        batch_size=1024, sample=0)
                model = PSWord2Vec(config, d)
                if rank == 1:  # server-only rank holds the second shard
                    for _ in range(2):
                        mv.current_zoo().barrier()
                    return None
                trainer = PSDeviceCorpusTrainer(
                    model, tok, centers_per_step=128,
                    blocks_per_dispatch=grouped,
                    segment_keys=segment)
                for epoch in range(2):
                    trainer.train_epoch(seed=epoch)
                assert (trainer._seg_ids is not None) == segment
                return model._in_table.get_rows(
                    np.arange(d.size, dtype=np.int32))
            return LocalCluster(2, roles=["all", "server"]).run(body)[0]

        broadcast, segmented = run(False), run(True)
        np.testing.assert_allclose(segmented, broadcast, rtol=1e-4,
                                   atol=1e-6)


class TestBatchGroup:
    @pytest.mark.parametrize("mode", ["sgns", "cbow", "hs"])
    def test_grouped_scan_matches_sequential(self, tmp_path, mode):
        # The lax.scan multi-step must be bit-identical to dispatching
        # the same batches one step at a time (same key-split order) —
        # including a short tail group padded with count=0 slots.
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path, n_sentences=60)
        d = Dictionary.build(str(path), min_count=1)
        kw = {"cbow": mode == "cbow", "hs": mode == "hs"}
        if mode == "hs":
            kw["negative"] = 0
        embs = []
        for group in (1, 4):
            config = Word2VecConfig(embedding_size=8, window=3, epochs=2,
                                    batch_size=256, sample=0,
                                    batch_group=group, **kw)
            model = Word2Vec(config, d)
            loss = 0.0
            # TWO epochs: each ends with a padded tail group, which must
            # not desync the per-batch key stream across epochs.
            for epoch in range(2):
                ep_loss, pairs = model.train_batches(iter_pair_batches(
                    d, str(path), batch_size=256, window=3, subsample=0,
                    cbow=config.cbow, seed=5 + epoch))
                loss += ep_loss
                assert pairs > 256  # several batches incl. a padded tail
            embs.append((model.embeddings, loss))
        np.testing.assert_array_equal(embs[0][0], embs[1][0])
        assert embs[0][1] == pytest.approx(embs[1][1], rel=1e-6)


def train_and_separate(tmp_path, **config_kw):
    path = tmp_path / "corpus.txt"
    write_topic_corpus(path)
    d = Dictionary.build(str(path), min_count=1)
    # Small lr: batch-summed gradients on this tiny vocab hit each row
    # ~64x per batch (see model.py on per-pair lr semantics).
    config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                            init_learning_rate=0.01, batch_size=1024,
                            sample=0, **config_kw)
    model = Word2Vec(config, d)
    for epoch in range(config.epochs):
        for batch in iter_pair_batches(d, str(path), batch_size=1024,
                                       window=3, subsample=0,
                                       cbow=config.cbow,
                                       seed=epoch):
            model.train_batch(batch)
    return topic_separation(model, d), model, d


class TestTraining:
    def test_sgns_skipgram_separates_topics(self, tmp_path):
        sep, _, _ = train_and_separate(tmp_path)
        assert sep > 0.3, f"separation {sep}"

    def test_cbow_separates_topics(self, tmp_path):
        sep, _, _ = train_and_separate(tmp_path, cbow=True)
        assert sep > 0.3, f"separation {sep}"

    def test_hierarchical_softmax_separates_topics(self, tmp_path):
        sep, _, _ = train_and_separate(tmp_path, hs=True, negative=0)
        assert sep > 0.3, f"separation {sep}"

    def test_save_embeddings_format(self, tmp_path):
        _, model, d = train_and_separate(tmp_path)
        out = tmp_path / "vec.txt"
        model.save_embeddings(str(out))
        lines = out.read_text().strip().split("\n")
        header = lines[0].split()
        assert int(header[0]) == d.size and int(header[1]) == 16
        assert len(lines) == d.size + 1


class TestPSWord2Vec:
    def test_ps_training_separates_topics(self, tmp_path):
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        mv.init([])
        try:
            config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                                    init_learning_rate=0.01,
                                    batch_size=1024, sample=0, use_ps=True)
            model = PSWord2Vec(config, d)
            for epoch in range(config.epochs):
                for batch in iter_pair_batches(d, str(path),
                                               batch_size=1024, window=3,
                                               subsample=0, seed=epoch):
                    model.train_batch(batch)
            sep = topic_separation(model, d)
        finally:
            mv.shutdown()
        assert sep > 0.3, f"separation {sep}"

    @pytest.mark.parametrize("mode", ["cbow", "hs"])
    def test_ps_compact_step_modes(self, tmp_path, mode):
        # CBOW and hierarchical softmax through the compact pulled-row
        # step (the PS redesign trains on [R, D] row sets, not V x D).
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)
        d = Dictionary.build(str(path), min_count=1)
        mv.init([])
        try:
            kw = dict(cbow=True) if mode == "cbow" \
                else dict(hs=True, negative=0)
            config = Word2VecConfig(embedding_size=16, window=3, epochs=5,
                                    init_learning_rate=0.01,
                                    batch_size=1024, sample=0, use_ps=True,
                                    **kw)
            model = PSWord2Vec(config, d)
            for epoch in range(config.epochs):
                loss_sum, pairs = model.train_batches(iter_pair_batches(
                    d, str(path), batch_size=1024, window=3, subsample=0,
                    cbow=config.cbow, seed=epoch))
                assert np.isfinite(loss_sum) and pairs > 0
            sep = topic_separation(model, d)
        finally:
            mv.shutdown()
        assert sep > 0.3, f"separation {sep}"

    def test_ps_pulls_are_row_sparse(self, tmp_path):
        # The PS path must pull only the rows a batch touches — never the
        # whole table (the round-1 design pulled V x D per batch).
        rng = np.random.default_rng(3)
        vocab = [f"w{i}" for i in range(600)]
        path = tmp_path / "corpus.txt"
        path.write_text("\n".join(
            " ".join(rng.choice(vocab, size=10)) for _ in range(400)))
        d = Dictionary.build(str(path), min_count=1)
        mv.init([])
        try:
            config = Word2VecConfig(embedding_size=8, window=2, epochs=1,
                                    batch_size=128, sample=0, use_ps=True)
            model = PSWord2Vec(config, d)
            pulled = []
            orig_host = model._in_table.get_rows_async
            orig_dev = model._in_table.get_rows_device_async

            def spy_host(rows, out=None):
                pulled.append(len(rows))
                return orig_host(rows, out=out)

            def spy_dev(rows):
                pulled.append(len(rows))
                return orig_dev(rows)

            model._in_table.get_rows_async = spy_host
            model._in_table.get_rows_device_async = spy_dev
            loss_sum, pairs = model.train_batches(iter_pair_batches(
                d, str(path), batch_size=128, window=2, subsample=0))
            assert pairs > 0 and np.isfinite(loss_sum)
            assert pulled, "no row pulls recorded"
            # 128 pairs touch at most 128 input rows (padded to a power of
            # two) out of a 600-word vocab.
            assert max(pulled) <= 128 < d.size, pulled
        finally:
            mv.shutdown()

    def test_ps_two_workers_cluster(self, tmp_path):
        # Two virtual ranks train concurrently against shared tables:
        # delta scaling (1/num_workers) and concurrent row pulls/pushes.
        from multiverso_tpu.runtime.cluster import LocalCluster
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path)

        def body(rank):
            d = Dictionary.build(str(path), min_count=1)
            config = Word2VecConfig(embedding_size=16, window=3, epochs=3,
                                    init_learning_rate=0.005,
                                    batch_size=1024, sample=0, use_ps=True)
            model = PSWord2Vec(config, d)
            for epoch in range(config.epochs):
                model.train_batches(iter_pair_batches(
                    d, str(path), batch_size=1024, window=3, subsample=0,
                    seed=100 * rank + epoch))
            mv.current_zoo().barrier()
            return topic_separation(model, d)

        seps = LocalCluster(2).run(body)
        assert all(s > 0.3 for s in seps), seps

    def test_ps_word_count_drives_lr(self, tmp_path):
        path = tmp_path / "corpus.txt"
        write_topic_corpus(path, n_sentences=100)
        d = Dictionary.build(str(path), min_count=1)
        mv.init([])
        try:
            config = Word2VecConfig(embedding_size=8, window=2, epochs=1,
                                    batch_size=512, sample=0, use_ps=True)
            model = PSWord2Vec(config, d)
            lr0 = model.learning_rate()
            for batch in iter_pair_batches(d, str(path), batch_size=512,
                                           window=2, subsample=0):
                model.train_batch(batch)
            assert model.trained_words > 0
            assert model.learning_rate() < lr0
        finally:
            mv.shutdown()


class TestPreprocess:
    def test_word_count_cli(self, tmp_path):
        # ref: Applications/WordEmbedding/preprocess/word_count.cpp:30-46
        # — count, filter by min_count + stopwords, save, reload.
        corpus = tmp_path / "c.txt"
        corpus.write_text("a b c a b a\nthe the the a b\n")
        (tmp_path / "sw.txt").write_text("the\n")
        vocab = tmp_path / "v.txt"
        from multiverso_tpu.models.wordembedding import preprocess
        from multiverso_tpu.util.configure import reset_flags
        reset_flags()
        try:
            d = preprocess.run([f"-train_file={corpus}",
                                f"-save_vocab_file={vocab}",
                                "-min_count=2",
                                f"-sw_file={tmp_path / 'sw.txt'}"])
        finally:
            reset_flags()
        assert d.size == 2 and "the" not in d.word2id
        reloaded = Dictionary.load(str(vocab))
        assert reloaded.word2id == d.word2id
        assert list(reloaded.counts) == list(d.counts)
