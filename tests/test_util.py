"""Utility-layer unit tests (mirrors reference Test/unittests tier 1,
ref: Test/unittests/test_blob.cpp, test_message.cpp, test_node.cpp plus
flag/queue/waiter/dashboard coverage)."""

import threading
import time

import numpy as np
import pytest

from multiverso_tpu.core import Blob, Message, MsgType, Node, Role, is_server, is_worker
from multiverso_tpu.util import (ASyncBuffer, Dashboard, MtQueue, OneBitFilter,
                                 SparseFilter, Timer, Waiter, configure, monitor)
from multiverso_tpu.util.log import CHECK, FatalError


class TestBlob:
    def test_alloc_and_view(self):
        b = Blob(size=12)
        assert b.size == 12
        f = b.as_array(np.float32)
        assert f.size == 3
        f[:] = [1.0, 2.0, 3.0]
        assert b.as_array(np.float32)[1] == 2.0

    def test_wrap_shares_memory(self):
        arr = np.arange(4, dtype=np.float32)
        b = Blob(arr)
        b2 = Blob(b)  # shallow copy shares storage like ref copy-ctor
        b2.as_array(np.float32)[0] = 42.0
        assert arr[0] == 42.0

    def test_copy_is_deep(self):
        arr = np.arange(4, dtype=np.int32)
        b = Blob(arr).copy()
        b.as_array(np.int32)[0] = 9
        assert arr[0] == 0

    def test_typed_count(self):
        b = Blob(np.zeros(10, dtype=np.float64))
        assert b.count(np.float64) == 10
        assert b.count(np.float32) == 20


class TestMessage:
    def test_header_roundtrip(self):
        m = Message(src=3, dst=5, msg_type=MsgType.Request_Add, table_id=2, msg_id=7)
        assert (m.src, m.dst, m.type, m.table_id, m.msg_id) == \
            (3, 5, MsgType.Request_Add, 2, 7)

    def test_reply_flips(self):
        m = Message(src=3, dst=5, msg_type=MsgType.Request_Get, table_id=1, msg_id=9)
        r = m.create_reply_message()
        assert r.src == 5 and r.dst == 3
        assert r.type == MsgType.Reply_Get
        assert r.table_id == 1 and r.msg_id == 9

    def test_payload(self):
        m = Message()
        m.push(np.arange(3, dtype=np.float32))
        m.push(np.arange(5, dtype=np.int32))
        assert m.size() == 2
        assert m.data[0].count(np.float32) == 3


class TestNode:
    def test_roles(self):
        assert is_worker(Role.WORKER) and not is_server(Role.WORKER)
        assert is_server(Role.SERVER) and not is_worker(Role.SERVER)
        assert is_worker(Role.ALL) and is_server(Role.ALL)
        assert not is_worker(Role.NONE) and not is_server(Role.NONE)

    def test_default_node(self):
        n = Node()
        assert n.rank == -1 and n.role == Role.ALL


# The registry-machinery tests exercise define/get/set on deliberately
# synthetic flag names — the one place unregistered names are the point.
class TestConfigure:  # mvlint: ignore[flag-lint]
    def test_parse_cmd_flags(self):
        configure.define_int("test_port", 9999)
        configure.define_bool("test_sync", False)
        configure.define_string("test_name", "x")
        argv = ["prog", "-test_port=1234", "keepme", "-test_sync=true",
                "-test_name=hello"]
        rest = configure.parse_cmd_flags(argv)
        assert rest == ["prog", "keepme"]
        assert configure.get_flag("test_port") == 1234
        assert configure.get_flag("test_sync") is True
        assert configure.get_flag("test_name") == "hello"

    def test_set_flag_coerces(self):
        configure.define_double("test_lr", 0.1)
        configure.set_flag("test_lr", "0.5")
        assert configure.get_flag("test_lr") == 0.5

    def test_unknown_flag_left_in_argv(self):
        # Reference parity: ParseCMDFlags only consumes registered flags
        # (configure.cpp:19-53); unknown entries stay for downstream parsers.
        rest = configure.parse_cmd_flags(["-brandnew=abc"])
        assert rest == ["-brandnew=abc"]
        # Programmatic set_flag (the reference's SetCMDFlag/MV_SetFlag)
        # still registers implicitly.
        configure.set_flag("brandnew", "abc")
        assert configure.get_flag("brandnew") == "abc"

    def test_bad_value_names_flag(self):
        configure.define_int("test_badval", 1)
        with pytest.raises(ValueError, match="test_badval"):
            configure.parse_cmd_flags(["-test_badval=abc"])

    def test_unknown_flag_warns_once_with_suggestion(self, capsys):
        # A typo'd get_flag must not silently return the caller's
        # default: one loud line per process, naming the nearest
        # registered flag (difflib), value still the caller's default.
        configure._warned_unknown.discard("allreduce_windw")
        assert configure.get_flag("allreduce_windw", 7) == 7
        err = capsys.readouterr().err
        assert "allreduce_windw" in err
        assert "did you mean -allreduce_window?" in err
        assert "IGNORED" in err
        # Second read: same value, no second warning.
        assert configure.get_flag("allreduce_windw", 7) == 7
        assert "allreduce_windw" not in capsys.readouterr().err

    def test_canonical_but_unloaded_flag_stays_quiet(self, capsys):
        # A canonical flag whose defining module is not imported reads
        # as the caller default silently (legitimate late binding).
        # 'debug_locks' may already be registered in this process; use
        # a canonical name guaranteed unregistered via a fresh check.
        reg = configure.FlagRegister.get()
        name = next((n for n in configure.CANONICAL_FLAGS
                     if not reg.has(n)), None)
        if name is None:
            pytest.skip("every canonical flag already registered")
        configure.get_flag(name, configure.CANONICAL_FLAGS[name])
        assert name not in capsys.readouterr().err

    def test_define_drift_warns(self, capsys):
        # Registering a canonical flag with a different default is
        # default drift — mvlint catches it statically, the runtime
        # warns on dynamic paths.
        reg = configure.FlagRegister.get()
        fresh = not reg.has("send_queue_mb")
        configure.define_int("send_queue_mb", 99)
        assert "canonical default" in capsys.readouterr().err
        if fresh:  # don't leave the drifted default behind
            reg._flags.pop("send_queue_mb", None)


class TestMtQueue:
    def test_fifo(self):
        q = MtQueue()
        for i in range(5):
            q.push(i)
        assert q.size() == 5
        assert [q.pop() for _ in range(5)] == [0, 1, 2, 3, 4]

    def test_blocking_pop(self):
        q = MtQueue()
        result = []

        def consumer():
            result.append(q.pop())

        t = threading.Thread(target=consumer)
        t.start()
        time.sleep(0.05)
        q.push("item")
        t.join(timeout=2)
        assert result == ["item"]

    def test_exit_unblocks(self):
        q = MtQueue()
        result = []

        def consumer():
            result.append(q.pop())

        t = threading.Thread(target=consumer)
        t.start()
        q.exit()
        t.join(timeout=2)
        assert result == [None]
        ok, _ = q.try_pop()
        assert not ok


class TestWaiter:
    def test_countdown(self):
        w = Waiter(2)
        done = []

        def waiter_thread():
            w.wait()
            done.append(True)

        t = threading.Thread(target=waiter_thread)
        t.start()
        w.notify()
        time.sleep(0.02)
        assert not done
        w.notify()
        t.join(timeout=2)
        assert done

    def test_reset(self):
        w = Waiter(1)
        w.notify()
        assert w.wait(timeout=0.1)
        w.reset(1)
        assert not w.wait(timeout=0.05)


class TestAsyncBuffer:
    def test_prefetch_sequence(self):
        counter = {"n": 0}

        def fill(buf):
            counter["n"] += 1
            buf[0] = counter["n"]

        ab = ASyncBuffer([0], [0], fill)
        first = ab.get()
        assert first[0] == 1
        second = ab.get()
        assert second[0] == 2
        ab.stop()


class TestSparseFilter:
    def test_compress_roundtrip(self):
        f = SparseFilter(clip_value=0.0)
        dense = np.zeros(100, dtype=np.float32)
        dense[[3, 50, 99]] = [1.5, -2.0, 3.0]
        blobs, sizes = f.filter_in([dense])
        assert sizes[0] == 100
        # Compact codec frame (float64-pair format removed): 24-byte
        # header + u32 first idx + 2 u16 gaps + 3 fp32 values = 44 B,
        # vs 48 B of float64 pairs.
        assert blobs[0].dtype == np.uint8 and blobs[0].size == 44
        out = f.filter_out(blobs, sizes)
        np.testing.assert_array_equal(out[0], dense)

    def test_lossy_residual_exposed(self):
        f = SparseFilter(lossy=True)
        dense = np.zeros(4096, dtype=np.float32)
        rng = np.random.default_rng(3)
        hot = rng.choice(4096, 200, replace=False)
        dense[hot] = rng.standard_normal(200).astype(np.float32)
        blobs, sizes = f.filter_in([dense])
        out = f.filter_out(blobs, sizes)[0]
        residual = f.last_residuals[0]
        if residual is None:  # heuristic picked a lossless tier
            np.testing.assert_array_equal(out, dense)
        else:
            np.testing.assert_allclose(out + residual, dense,
                                       rtol=0, atol=1e-5)

    def test_dense_passthrough(self):
        f = SparseFilter()
        dense = np.arange(1, 11, dtype=np.float32)
        blobs, sizes = f.filter_in([dense])
        assert sizes[0] == -1
        out = f.filter_out(blobs, sizes)
        np.testing.assert_array_equal(out[0], dense)

    def test_one_bit(self):
        f = OneBitFilter()
        arr = np.array([1.0, 2.0, -1.0, -3.0], dtype=np.float32)
        enc, residual = f.encode(arr)
        dec = f.decode(enc)
        np.testing.assert_allclose(dec, [1.5, 1.5, -2.0, -2.0])
        np.testing.assert_allclose(arr - dec, residual)


class TestDashboardAndTimer:
    def test_monitor_counts(self):  # mvlint: ignore[metric-name]
        Dashboard.reset()
        with monitor("unit_test_region"):
            time.sleep(0.01)
        with monitor("unit_test_region"):
            pass
        mon = Dashboard.get("unit_test_region")
        assert mon.count == 2
        assert mon.elapse >= 10.0
        assert "unit_test_region" in Dashboard.display()

    def test_timer(self):
        t = Timer()
        time.sleep(0.01)
        assert t.elapse() >= 9.0
        t.start()
        assert t.elapse() < 9.0


class TestCheck:
    def test_check_raises(self):
        with pytest.raises(FatalError):
            CHECK(False, "boom")
        CHECK(True)


class TestTraceTo:
    def test_trace_capture_writes_xplane(self, tmp_path):
        # Whole-program xprof capture (the TPU-side tracing complement
        # to the Dashboard counters, SURVEY.md section 5.1).
        import glob

        import jax.numpy as jnp

        from multiverso_tpu.util import monitor, trace_to
        with trace_to(str(tmp_path)):
            with monitor("TRACE_REGION",  # mvlint: ignore[metric-name]
                         trace=True):
                jnp.ones((32, 32)) @ jnp.ones((32, 32))
        files = glob.glob(str(tmp_path) + "/**/*", recursive=True)
        assert any("xplane" in f or "trace" in f for f in files), files


class TestMonitorResetRegression:
    def test_monitor_ctx_survives_dashboard_reset(self):
        # Regression (ISSUE 9 satellite): the context manager used to
        # cache its Monitor at CONSTRUCTION, so a Dashboard.reset()
        # (every bench phase does one) left long-lived monitor(...)
        # instances writing to unregistered orphans invisible to
        # display()/snapshots.
        ctx = monitor("reset_survivor")  # mvlint: ignore[metric-name]
        with ctx:
            pass
        assert Dashboard.get("reset_survivor").count == 1
        Dashboard.reset()
        with ctx:  # must re-resolve into the FRESH registry
            pass
        assert Dashboard.get("reset_survivor").count == 1
        assert "reset_survivor" in Dashboard.display()

    def test_display_sorted_with_samples_section(self):
        from multiverso_tpu.util.dashboard import reset_samples, samples
        Dashboard.reset()
        reset_samples()
        with monitor("zz_late"):  # mvlint: ignore[metric-name]
            pass
        with monitor("aa_early"):  # mvlint: ignore[metric-name]
            pass
        samples("mm_samples").add(2.0)  # mvlint: ignore[metric-name]
        samples("mm_samples").add(4.0)  # mvlint: ignore[metric-name]
        report = Dashboard.display()
        # Monitors sorted by name regardless of registration order,
        # and the Samples registry is part of the report.
        assert report.index("[aa_early]") < report.index("[zz_late]")
        assert "[mm_samples]" in report and "p99" in report
        # Deterministic: two successive dumps diff clean.
        assert report == Dashboard.display()
        Dashboard.reset()
        reset_samples()


class TestSamplesEdges:
    def _fresh(self, cap):
        from multiverso_tpu.util.dashboard import Samples
        return Samples("edge_test", cap=cap)

    def test_ring_wraparound_keeps_most_recent_cap(self):
        s = self._fresh(cap=8)
        for v in range(30):
            s.add(float(v))
        assert s.count == 30
        # Exactly the newest 8 retained, in order.
        assert s.export_recent(100) == [float(v) for v in range(22, 30)]
        assert s.percentile(0) == 22.0
        assert s.percentile(100) == 29.0

    def test_export_recent_limit_and_prewrap_order(self):
        s = self._fresh(cap=8)
        for v in range(5):
            s.add(float(v))
        assert s.export_recent(100) == [0.0, 1.0, 2.0, 3.0, 4.0]
        assert s.export_recent(2) == [3.0, 4.0]

    def test_nearest_rank_one_element_window(self):
        s = self._fresh(cap=4)
        s.add(7.5)
        for p in (0, 1, 50, 99, 100):
            assert s.percentile(p) == 7.5
        snap = s.snapshot()
        assert snap["p50"] == snap["p99"] == snap["max"] == 7.5
        assert snap["count"] == 1

    def test_nearest_rank_two_element_window(self):
        s = self._fresh(cap=4)
        s.add(10.0)
        s.add(20.0)
        # Nearest-rank: ceil(p/100 * 2) -> p50 is the LOWER value,
        # p51+ the upper; p0 clamps to the min.
        assert s.percentile(0) == 10.0
        assert s.percentile(50) == 10.0
        assert s.percentile(51) == 20.0
        assert s.percentile(99) == 20.0
        assert s.percentile(100) == 20.0

    def test_empty_window(self):
        s = self._fresh(cap=4)
        assert s.percentile(50) == 0.0
        assert s.snapshot() == {"count": 0}
        assert s.export_recent() == []

    def test_concurrent_add_under_debug_locks(self):
        # The reservoir's lock goes through the lock_witness factory;
        # with -debug_locks on, witnessed concurrent adds must neither
        # deadlock nor lose counts, and the ring bound must hold.
        from multiverso_tpu.util.configure import set_flag
        set_flag("debug_locks", True)
        try:
            s = self._fresh(cap=64)
            n_threads, per_thread = 8, 500

            def pound(seed):
                for k in range(per_thread):
                    s.add(float(seed * per_thread + k))

            threads = [threading.Thread(target=pound, args=(t,))
                       for t in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert not any(t.is_alive() for t in threads)
            assert s.count == n_threads * per_thread
            assert len(s.export_recent(1000)) == 64
            snap = s.snapshot()
            assert snap["p50"] <= snap["p99"] <= snap["max"]
        finally:
            set_flag("debug_locks", False)
